"""Preemption-capable jitted drain kernel (unified workload axis).

Extends the fit-only drain (kernels.py) with the reference's preemption
semantics, fully on-device:

- batched classical candidate generation: legality masks from the
  within-CQ / reclaim-within-cohort / borrowWithinCohort policies
  (classical/candidate_generator.go:34-160), hierarchical-advantage rings
  (hierarchical_preemption.go collectCandidatesForHierarchicalReclaim),
  and the candidate ordering (common/ordering.go) as lexsort keys;
- the remove-then-fill-back victim search (preemption.go:271-341) as a
  masked lax.scan per preemptor, vmapped over the round's preempt-mode
  heads;
- the cycle contract of scheduler.go:286-467: entry ordering, one
  overlapping-preemption skip, fits re-check under simulated removal of
  already-preempted workloads, reserve-and-park for Preempt/NoCandidates.

Admitted workloads live on the same axis as pending ones: eviction flips
them back to pending (ordered by a per-round eviction timestamp rank,
workload.Ordering semantics) so preemptors re-attempt the next round
against the freed capacity, exactly like the host Simulator.

Static caps (compile-time constants baked into the program):
- H_MAX preempt-mode heads are searched per round; later ones wait a
  round (the reference searches all, but its cycle admits at most one
  conflicting entry anyway, so extra searches mostly re-run next cycle).
- P_MAX candidates considered per search; a victim set needing more
  candidates fails the search (NoCandidates semantics). The engine sizes
  these from the problem.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_oss_tpu.solver.kernels import (
    M_FIT,
    M_NOFIT,
    M_PREEMPT,
    _add_usage_along_path,
    _avail_along_path,
    available_all,
    borrow_levels,
    potential_available_all,
    refresh_cohort_usage,
)
from kueue_oss_tpu.solver.tensors import (
    BIG,
    POLICY_ANY,
    POLICY_LOWER_OR_NEWER_EQUAL,
    POLICY_LOWER_PRIORITY,
    POLICY_NEVER,
    NO_THRESHOLD,
    SolverProblem,
)

# candidate variants (classical/candidate_generator.go)
V_NEVER = 0
V_WITHIN_CQ = 1
V_HIERARCHICAL_RECLAIM = 2
V_RECLAIM_WITHOUT_BORROWING = 3
V_RECLAIM_WHILE_BORROWING = 4

# preemption-mode lattice (flavorassigner.go:429-437); mirrors the host
# flavor_assigner P_* constants so granular modes compare identically.
P_NOFIT = 0
P_NO_CANDIDATES = 1
P_PREEMPT = 2
P_RECLAIM = 3
P_FIT = 4

#: cap on borrow levels when packing granular modes into one sort key
#: (levels are cohort-tree heights, far below this)
B_CAP = 64


class FullTensors(NamedTuple):
    """Device-side mirror of the extended SolverProblem."""

    parent: jnp.ndarray
    depth: jnp.ndarray
    height: jnp.ndarray
    has_parent: jnp.ndarray
    is_cq: jnp.ndarray
    path: jnp.ndarray
    subtree: jnp.ndarray
    local_quota: jnp.ndarray
    nominal: jnp.ndarray
    has_borrow: jnp.ndarray
    borrow_limit: jnp.ndarray
    usage0: jnp.ndarray
    cq_node: jnp.ndarray
    cq_strict: jnp.ndarray
    cq_try_next: jnp.ndarray
    cq_nflavors: jnp.ndarray
    cq_within_policy: jnp.ndarray
    cq_reclaim_policy: jnp.ndarray
    cq_bwc_forbidden: jnp.ndarray
    cq_bwc_threshold: jnp.ndarray
    cq_preempt_try_next: jnp.ndarray
    cq_pref_pob: jnp.ndarray
    cq_fair_weight: jnp.ndarray
    cq_root: jnp.ndarray
    cq_opt_group: jnp.ndarray    # [C, K]
    cq_opt_pos: jnp.ndarray      # [C, K] position of option within its group
    cq_ngroups: jnp.ndarray
    wl_cqid: jnp.ndarray
    wl_prio: jnp.ndarray
    wl_ts0: jnp.ndarray
    wl_uid: jnp.ndarray
    wl_req: jnp.ndarray
    wl_valid: jnp.ndarray
    wl_parked0: jnp.ndarray
    wl_admitted0: jnp.ndarray
    wl_evicted0: jnp.ndarray
    wl_admit_rank0: jnp.ndarray
    ad_usage: jnp.ndarray
    fr_resource: jnp.ndarray     # [F] int32 resource id per FR column
    res_onehot: jnp.ndarray      # [F, R] int32 one-hot of fr_resource
    node_fair_weight: jnp.ndarray  # [N+1] float32
    wl_class: jnp.ndarray        # [W+1] int32 scheduling-equivalence class
    class_root: jnp.ndarray      # [n_classes+1] int32
    wl_lq: jnp.ndarray           # [W+1] int32 dense LQ id (AFS)
    wl_ts_buf: jnp.ndarray       # [W+1] int32 newer-eq threshold rank
    wl_afs_penalty: jnp.ndarray  # [W+1] float32 admission penalty inc
    lq_penalty0: jnp.ndarray     # [L+1] float32 decayed start penalties
    cq_afs: jnp.ndarray          # [C] bool UsageBasedAdmissionFairSharing
    ts_evict_base: jnp.ndarray   # scalar int32
    admit_rank_base: jnp.ndarray  # scalar int32


#: FullTensors fields carried on the [W+1] workload axis — the set the
#: pod-scale row sharding block-distributes over the mesh ``wl`` axis
#: (sharded.full_shardings); everything else (cohort tree, CQ policy,
#: flavor metadata) replicates. Scatter/gather ops against these fields
#: cross shards under GSPMD; the victim-search lane shard_map
#: (_run_searches) composes with — it re-gathers the rows it scans.
FULL_WL_FIELDS = ("wl_cqid", "wl_prio", "wl_ts0", "wl_uid", "wl_req",
                  "wl_valid", "wl_parked0", "wl_admitted0",
                  "wl_evicted0", "wl_admit_rank0", "ad_usage",
                  "wl_class", "wl_lq", "wl_ts_buf", "wl_afs_penalty")


def host_tensors_full(p: SolverProblem) -> FullTensors:
    """The full kernel's input tensors as HOST (numpy) arrays — see
    kernels.host_tensors for why this is split from the upload."""
    import numpy as np

    is_cq = np.zeros(p.parent.shape[0], dtype=bool)
    is_cq[p.cq_node] = True
    # position of option k within its group, for per-group flavor cursors
    C, K = p.cq_opt_group.shape if p.cq_opt_group is not None else (0, 1)
    opt_pos = np.zeros((C, K), dtype=np.int32)
    for c in range(C):
        counts: dict[int, int] = {}
        for k in range(K):
            g = int(p.cq_opt_group[c, k])
            if g < 0:
                continue
            opt_pos[c, k] = counts.get(g, 0)
            counts[g] = counts.get(g, 0) + 1
    return FullTensors(
        parent=p.parent,
        depth=p.depth,
        height=p.height,
        has_parent=p.has_parent,
        is_cq=is_cq,
        path=p.path,
        subtree=p.subtree,
        local_quota=p.local_quota,
        nominal=p.nominal,
        has_borrow=p.has_borrow,
        borrow_limit=p.borrow_limit,
        usage0=p.usage0,
        cq_node=p.cq_node,
        cq_strict=p.cq_strict,
        cq_try_next=p.cq_try_next,
        cq_nflavors=p.cq_nflavors,
        cq_within_policy=p.cq_within_policy,
        cq_reclaim_policy=p.cq_reclaim_policy,
        cq_bwc_forbidden=p.cq_bwc_forbidden,
        cq_bwc_threshold=p.cq_bwc_threshold,
        cq_preempt_try_next=p.cq_preempt_try_next,
        cq_pref_pob=p.cq_pref_pob,
        cq_fair_weight=p.cq_fair_weight,
        cq_root=p.cq_root,
        cq_opt_group=p.cq_opt_group,
        cq_opt_pos=opt_pos,
        cq_ngroups=p.cq_ngroups,
        wl_cqid=p.wl_cqid,
        wl_prio=p.wl_prio,
        wl_ts0=p.wl_ts,
        wl_uid=p.wl_uid,
        wl_req=p.wl_req,
        wl_valid=p.wl_valid,
        wl_parked0=p.wl_parked0,
        wl_admitted0=p.wl_admitted0,
        wl_evicted0=p.wl_evicted0,
        wl_admit_rank0=p.wl_admit_rank,
        ad_usage=p.ad_usage,
        fr_resource=p.fr_resource,
        res_onehot=np.eye(p.n_resources, dtype=np.int32)[p.fr_resource],
        node_fair_weight=p.node_fair_weight,
        wl_class=p.wl_class,
        class_root=p.class_root,
        wl_lq=(p.wl_lq if p.wl_lq is not None
               else np.zeros(p.wl_cqid.shape[0], np.int32)),
        wl_ts_buf=(p.wl_ts_buf if p.wl_ts_buf is not None else p.wl_ts),
        wl_afs_penalty=(
            p.wl_afs_penalty if p.wl_afs_penalty is not None
            else np.zeros(p.wl_cqid.shape[0], np.float32)),
        lq_penalty0=(p.lq_penalty0 if p.lq_penalty0 is not None
                     else np.zeros(1, np.float32)),
        cq_afs=(p.cq_afs if p.cq_afs is not None
                else np.zeros(p.cq_node.shape[0], bool)),
        ts_evict_base=np.asarray(p.ts_evict_base, dtype=np.int32),
        admit_rank_base=np.asarray(p.admit_rank_base, dtype=np.int32),
    )


def to_device_full(p: SolverProblem) -> FullTensors:
    return jax.tree_util.tree_map(jnp.asarray, host_tensors_full(p))


# ---------------------------------------------------------------------------
# path helpers
# ---------------------------------------------------------------------------


def _remove_usage_along_path(t, usage: jnp.ndarray, cq_node: jnp.ndarray,
                             val: jnp.ndarray) -> jnp.ndarray:
    """removeUsage with bubbling (resource_node.go:147-158) along one path:
    the parent's share shrinks by min(val, usage stored in parent)."""
    path = t.path[cq_node]
    null = t.parent.shape[0] - 1
    for d in range(path.shape[0]):
        node = path[d]
        is_valid = node != null
        stored = usage[node] - t.local_quota[node]
        usage = usage.at[node].add(jnp.where(is_valid, -val, 0))
        val = jnp.where(stored > 0, jnp.minimum(val, stored), 0)
    return usage


def _height_along_path(t, usage, cq_node, req):
    """FindHeightOfLowestSubtreeThatFits for one CQ under ``usage``.

    Elementwise over the FR axis; returns (level [F] int32,
    may_reclaim [F] bool). Reference parity:
    classical/hierarchical_preemption.go:221-243 — same walk as
    borrow_levels (kernels.py) but along a single CQ path so it can run
    on mid-search usage (simulate_preemption's borrow-after-removal).
    """
    path = t.path[cq_node]
    null = t.parent.shape[0] - 1
    found = req == 0
    level = jnp.zeros_like(req)
    may_reclaim = jnp.zeros(req.shape, dtype=bool)
    rem = req
    root = cq_node
    for d in range(path.shape[0]):
        node = path[d]
        valid = node != null
        root = jnp.where(valid, node, root)
        not_borrowing = usage[node] + rem <= t.subtree[node]
        newly = (~found) & not_borrowing & valid
        level = jnp.where(newly, t.height[node], level)
        may_reclaim = jnp.where(newly, t.has_parent[node], may_reclaim)
        found = found | newly
        la = jnp.maximum(0, t.local_quota[node] - usage[node])
        rem = jnp.where(found | ~valid, rem, rem - la)
    level = jnp.where(found, level, t.height[root])
    return level, may_reclaim


# ---------------------------------------------------------------------------
# head selection: per-CQ min by (-priority, ts, uid) over the pending set
# ---------------------------------------------------------------------------


def select_heads_full(t: FullTensors, admitted, parked, ts,
                      lq_penalty=None):
    C = t.cq_node.shape[0]
    W1 = t.wl_cqid.shape[0]
    W_null = W1 - 1
    pending = ~admitted & ~parked
    seg = t.wl_cqid[:-1]
    if lq_penalty is not None:
        # Admission fair sharing (KEP-4136): within a
        # UsageBasedAdmissionFairSharing CQ the head is the entry whose
        # LocalQueue carries the lowest decayed usage; the normal
        # (priority, ts, uid) order is the tie-break
        # (queue_manager.pop_head afs_key).
        is_afs = t.cq_afs[jnp.minimum(seg, C - 1)]
        pen = lq_penalty[t.wl_lq[:-1]]
        pen_eff = jnp.where(pending[:-1] & is_afs, pen, jnp.inf)
        min_pen = jax.ops.segment_min(pen_eff, seg,
                                      num_segments=C + 1)[:C]
        pending_head = pending[:-1] & (
            ~is_afs | (pen == min_pen[seg]))
    else:
        pending_head = pending[:-1]
    prio_eff = jnp.where(pending_head, t.wl_prio[:-1], -BIG)
    max_prio = jax.ops.segment_max(prio_eff, seg, num_segments=C + 1)[:C]
    c1 = pending_head & (t.wl_prio[:-1] == max_prio[seg])
    ts_eff = jnp.where(c1, ts[:-1], BIG)
    min_ts = jax.ops.segment_min(ts_eff, seg, num_segments=C + 1)[:C]
    c2 = c1 & (ts[:-1] == min_ts[seg])
    uid_eff = jnp.where(c2, t.wl_uid[:-1], BIG)
    min_uid = jax.ops.segment_min(uid_eff, seg, num_segments=C + 1)[:C]
    c3 = c2 & (t.wl_uid[:-1] == min_uid[seg])
    w_idx = jnp.arange(W1 - 1, dtype=jnp.int32)
    head_w = jax.ops.segment_min(
        jnp.where(c3, w_idx, W_null), seg, num_segments=C + 1)[:C]
    has_head = max_prio > -BIG
    return jnp.where(has_head, head_w, W_null).astype(jnp.int32)


# ---------------------------------------------------------------------------
# per-group nomination
# ---------------------------------------------------------------------------


def nominate_full(t: FullTensors, usage, avail, pot, cand_w, cursor,
                  g_max: int, fs_enabled: bool = False):
    """Classify each CQ's head across (group, flavor) options.

    Per resource group the walk mirrors findFlavorForPodSets: start at the
    group's flavor cursor, prefer Fit per the whenCanBorrow policy, fall
    back to Preempt. The entry's mode is the worst group mode; its usage
    is the sum of the chosen options' requests. Returns (mode [C],
    k_chosen [C, G], req_total [C, F], borrow [C], next_cursor [C, G]).
    """
    C, K = t.cq_opt_group.shape
    req = t.wl_req[cand_w]                       # [C,K,F]
    grp = t.cq_opt_group                         # [C,K]
    pos = t.cq_opt_pos                           # [C,K]
    cursor_k = jnp.take_along_axis(
        cursor[cand_w], jnp.maximum(grp, 0), axis=1)  # [C,K]
    valid = (t.wl_valid[cand_w] & (grp >= 0)
             & (pos >= cursor_k))                # [C,K]

    avail_cq = avail[t.cq_node][:, None, :]
    pot_cq = pot[t.cq_node][:, None, :]
    nominal_cq = t.nominal[t.cq_node][:, None, :]
    level, may_reclaim = borrow_levels(t, usage, cand_w)

    nonzero = req > 0
    fit_fr = (~nonzero) | (req <= avail_cq)
    within_cap = (~nonzero) | (req <= pot_cq)
    # flavorassigner.go:1071-1108: preemption is considered when the value
    # is within nominal, a higher subtree could reclaim, or the CQ may
    # preempt while borrowing (borrowWithinCohort enabled; under fair
    # sharing also any reclaimWithinCohort policy —
    # flavor_assigner._can_preempt_while_borrowing)
    can_pwb = (~t.cq_bwc_forbidden
               | (fs_enabled
                  & (t.cq_reclaim_policy != POLICY_NEVER)))[:, None, None]
    preemptish_fr = (~nonzero) | (
        within_cap & ((req <= nominal_cq) | may_reclaim | can_pwb))
    opt_fit = valid & jnp.all(fit_fr, axis=-1)
    opt_preempt = valid & jnp.all(fit_fr | preemptish_fr, axis=-1)
    opt_level = jnp.max(jnp.where(nonzero, level, 0), axis=-1)  # [C,K]

    k_idx = jnp.arange(K, dtype=jnp.int32)[None, :]
    group_active = jnp.zeros((C, g_max), dtype=bool)
    mode = jnp.full((C,), M_FIT, dtype=jnp.int32)
    k_chosen = jnp.zeros((C, g_max), dtype=jnp.int32)
    next_cursor = jnp.zeros((C, g_max), dtype=jnp.int32)
    req_total = jnp.zeros((C, req.shape[2]), dtype=req.dtype)
    borrow = jnp.zeros((C,), dtype=jnp.int32)

    for g in range(g_max):
        in_g = grp == g                          # [C,K]
        has_g = jnp.any(in_g, axis=1)
        active = jnp.any(in_g & jnp.any(nonzero, axis=-1), axis=1)
        group_active = group_active.at[:, g].set(active)
        fit_g = opt_fit & in_g
        pre_g = opt_preempt & in_g & ~opt_fit

        def first_true(mask):
            return jnp.min(jnp.where(mask, k_idx, K), axis=1)

        k_default = first_true(fit_g)
        k_nonborrow = first_true(fit_g & (opt_level == 0))
        lvl_key = jnp.where(fit_g, opt_level * K + k_idx, BIG)
        k_bestlvl = jnp.argmin(lvl_key, axis=1).astype(jnp.int32)
        k_try_next = jnp.where(
            k_nonborrow < K, k_nonborrow,
            jnp.where(jnp.any(fit_g, axis=1), k_bestlvl, K))
        k_fit = jnp.where(t.cq_try_next, k_try_next, k_default)
        any_fit = k_fit < K
        k_preempt = first_true(pre_g)
        any_preempt = k_preempt < K
        k_g = jnp.where(any_fit, k_fit,
                        jnp.where(any_preempt, k_preempt,
                                  first_true(in_g))).astype(jnp.int32)
        k_g = jnp.minimum(k_g, K - 1)
        mode_g = jnp.where(any_fit, M_FIT,
                           jnp.where(any_preempt, M_PREEMPT, M_NOFIT))
        # Inactive groups (no requested resources) are vacuous fits.
        mode_g = jnp.where(active & has_g, mode_g, M_FIT)
        mode = jnp.minimum(mode, mode_g)
        k_chosen = k_chosen.at[:, g].set(jnp.where(active, k_g, 0))
        take = jnp.take_along_axis
        req_g = take(req, k_g[:, None, None], axis=1)[:, 0, :]
        req_total = req_total + jnp.where(active[:, None], req_g, 0)
        borrow_g = take(opt_level, k_g[:, None], axis=1)[:, 0]
        borrow = jnp.maximum(borrow, jnp.where(active, borrow_g, 0))
        # flavor cursor per group (flavorassigner.go:843 LastTriedFlavorIdx)
        early_break = jnp.where(t.cq_try_next, k_nonborrow < K, any_fit)
        pos_g = take(pos, k_g[:, None], axis=1)[:, 0]
        n_in_g = jnp.sum(in_g, axis=1)
        nc = jnp.where(early_break & (pos_g < n_in_g - 1), pos_g + 1, 0)
        next_cursor = next_cursor.at[:, g].set(
            jnp.where(active, nc, 0).astype(jnp.int32))

    return (mode, k_chosen, req_total, borrow, next_cursor,
            opt_fit, opt_preempt, opt_level, group_active, valid)


def walk_assign(t: FullTensors, head_w, pmode_k, borrow_k, valid_k,
                group_active_row, g_max: int):
    """The assigner's flavor walk over granular modes, for ONE head (vmap).

    Emulates _find_flavor_for_podsets (flavorassigner.go:812-951): per
    resource group, walk options in order; the first option where
    should_try_next_flavor is false wins (early break); otherwise the best
    option by is_preferred — (pmode desc, borrow asc, index asc) under
    BorrowingOverPreemption, (borrow asc, pmode desc, index asc) under
    PreemptionOverBorrowing (flavorassigner.go:439-470). ``pmode_k`` /
    ``borrow_k`` carry the per-option granular modes, with preempt-mode
    options already classified by an actual victim-search simulation
    (P_NO_CANDIDATES / P_PREEMPT / P_RECLAIM with borrow-after levels —
    preemption_oracle.go SimulatePreemption).

    Returns (mode, k_out [G], req [F], borrow, next_cursor [G],
    pmode_sel [G]).
    """
    C = t.cq_node.shape[0]
    K = t.cq_opt_group.shape[1]
    cqi = jnp.minimum(t.wl_cqid[head_w], C - 1)
    grp = t.cq_opt_group[cqi]                    # [K]
    pos = t.cq_opt_pos[cqi]                      # [K]
    req_k = t.wl_req[head_w]                     # [K, F]
    pmode_k = jnp.where(valid_k, pmode_k, P_NOFIT)
    is_pre_pm = (pmode_k == P_PREEMPT) | (pmode_k == P_RECLAIM)
    stn = ((pmode_k == P_NOFIT) | (pmode_k == P_NO_CANDIDATES)
           | (is_pre_pm & t.cq_preempt_try_next[cqi])
           | ((borrow_k != 0) & t.cq_try_next[cqi]))
    brk = valid_k & ~stn
    k_idx = jnp.arange(K, dtype=jnp.int32)
    bor = jnp.minimum(borrow_k, B_CAP - 1)
    key_bop = ((P_FIT - pmode_k) * B_CAP + bor) * K + k_idx
    key_pob = (bor * (P_FIT + 1) + (P_FIT - pmode_k)) * K + k_idx
    key = jnp.where(t.cq_pref_pob[cqi], key_pob, key_bop)
    eligible = valid_k & (pmode_k > P_NOFIT)

    k_out = jnp.zeros((g_max,), dtype=jnp.int32)
    next_cursor = jnp.zeros((g_max,), dtype=jnp.int32)
    req = jnp.zeros((req_k.shape[1],), dtype=req_k.dtype)
    borrow = jnp.zeros((), dtype=jnp.int32)
    mode = jnp.full((), M_FIT, dtype=jnp.int32)
    pmode_sel = jnp.full((g_max,), P_FIT, dtype=jnp.int32)
    for g in range(g_max):
        in_g = grp == g
        has_g = jnp.any(in_g)
        active = group_active_row[g]
        k_brk = jnp.min(jnp.where(brk & in_g, k_idx, K))
        elig_g = eligible & in_g
        any_elig = jnp.any(elig_g)
        k_best = jnp.argmin(jnp.where(elig_g, key, BIG)).astype(jnp.int32)
        k_first = jnp.min(jnp.where(in_g, k_idx, K))
        k_g = jnp.where(k_brk < K, k_brk,
                        jnp.where(any_elig, k_best,
                                  jnp.minimum(k_first, K - 1)))
        k_g = k_g.astype(jnp.int32)
        pm_g = jnp.where((k_brk < K) | any_elig, pmode_k[k_g], P_NOFIT)
        m_g = jnp.where(pm_g == P_FIT, M_FIT,
                        jnp.where(pm_g == P_NOFIT, M_NOFIT, M_PREEMPT))
        # Inactive groups (no requested resources) are vacuous fits.
        m_g = jnp.where(active & has_g, m_g, M_FIT)
        mode = jnp.minimum(mode, m_g)
        k_out = k_out.at[g].set(jnp.where(active, k_g, 0))
        pmode_sel = pmode_sel.at[g].set(
            jnp.where(active & has_g, pm_g, P_FIT))
        req = req + jnp.where(active, req_k[k_g], 0)
        borrow = jnp.maximum(borrow, jnp.where(active, borrow_k[k_g], 0))
        # flavor cursor (flavorassigner.go:843,939-947): next attempt
        # resumes after the break position; walking off the end resets.
        pos_brk = pos[jnp.minimum(k_brk, K - 1)]
        n_in_g = jnp.sum(in_g)
        nc = jnp.where((k_brk < K) & (pos_brk < n_in_g - 1), pos_brk + 1, 0)
        next_cursor = next_cursor.at[g].set(
            jnp.where(active, nc, 0).astype(jnp.int32))
    return mode, k_out, req, borrow, next_cursor, pmode_sel


# ---------------------------------------------------------------------------
# classical preemption search (one preemptor; vmapped over lanes)
# ---------------------------------------------------------------------------


def _within_nominal_frs(t, usage, node, frs_mask):
    """is_within_nominal over the masked FRs at one node."""
    return jnp.all(~frs_mask | (usage[node] <= t.subtree[node]))


def _workload_fits(t, usage, cq_node, req, allow_borrow):
    """_workload_fits (preemption.py:555): every requested fr must fit
    available(), and without allow_borrow must not push the CQ above its
    subtree quota."""
    avail = _avail_along_path(t, usage, cq_node)
    nz = req > 0
    fits_avail = jnp.all(~nz | (req <= avail))
    no_borrow_ok = jnp.all(
        ~nz | (usage[cq_node] + req <= t.subtree[cq_node]))
    return fits_avail & (allow_borrow | no_borrow_ok)


def build_candidate_table(t: FullTensors, admitted, admit_rank, wl_usage,
                          a_max: int):
    """Per-cohort-root admitted-candidate table, [N+1, A] int32.

    Victim candidates are always admitted workloads with nonzero usage in
    the preemptor's cohort tree (candidate_generator.go:34-160), and the
    candidate orderings' lane-independent suffix is shared: (priority
    asc, admit_rank desc = most recently admitted first, uid asc)
    (common/ordering.go). Building one table per round — rows keyed by
    root node, candidates in shared order — lets every victim search run
    on a small capacity-bounded axis instead of re-sorting the whole
    workload axis per lane. Rows pad with W_null.
    """
    W1 = t.wl_cqid.shape[0]
    W_null = W1 - 1
    N1 = t.parent.shape[0]
    C = t.cq_node.shape[0]
    root_of = t.cq_root[jnp.minimum(t.wl_cqid[:-1], C - 1)]   # [W]
    elig = admitted[:-1] & jnp.any(wl_usage[:-1] > 0, axis=1)
    order = jnp.lexsort((t.wl_uid[:-1], -admit_rank[:-1], t.wl_prio[:-1]))
    rank = jnp.zeros((W1 - 1,), dtype=jnp.int32).at[order].set(
        jnp.arange(W1 - 1, dtype=jnp.int32))
    root_eff = jnp.where(elig, root_of, N1)
    sorted_w = jnp.lexsort((rank, root_eff)).astype(jnp.int32)
    elig_s = elig[sorted_w]
    root_s = root_of[sorted_w]
    counts = jax.ops.segment_sum(
        elig.astype(jnp.int32), root_of, num_segments=N1)
    offsets = jnp.cumsum(counts) - counts
    pos = jnp.arange(W1 - 1, dtype=jnp.int32) - offsets[root_s]
    row = jnp.where(elig_s, root_s, N1)               # OOB row -> dropped
    col = jnp.where(elig_s, jnp.minimum(pos, a_max), a_max)
    table = jnp.full((N1, a_max), W_null, dtype=jnp.int32)
    return table.at[row, col].set(sorted_w, mode="drop")


def classical_search(t: FullTensors, usage0_round, wl_usage, admitted,
                     evicted_f, ts, head_w, req, avail_cq,
                     cands, p_max: int):
    """Victim search for ONE preemptor (vmap over lanes).

    ``cands`` is the preemptor root's row of build_candidate_table:
    round-start admitted workloads in the shared candidate order, W_null
    padded — the only workloads that can ever be victims, on an axis
    bounded by cohort capacity instead of cohort population.

    Returns (success, victim_w [P] int32 (W_null padded), victim_valid [P]
    bool, victim_reason [P] int8, any_same_cq bool, borrow_after int32).
    Mirrors Preemptor._classical_preemptions: candidate generation +
    ordering, two allow-borrowing attempts of the remove-until-fits walk,
    then fillBackWorkloads. The walk is a bulk-skip loop: pop-time
    validity (over-quota predicates, candidate_generator.go _valid) is
    monotone non-increasing under removals, so all currently-invalid
    candidates are skipped in one parallel step and each iteration
    removes exactly one true victim — the loop trips #victims times, not
    p_max times. ``borrow_after`` is the FindHeightOfLowestSubtreeThatFits
    level computed on the usage with the chosen victims removed
    (round-start usage when the search fails), maxed over the FRs needing
    preemption — simulate_preemption's borrow-after that ranks preempt
    flavors in the assigner's granular mode; ``any_same_cq`` distinguishes
    Preempt from Reclaim possibilities (preemption_oracle.go).
    """
    W1 = t.wl_cqid.shape[0]
    W_null = W1 - 1
    C_n = t.cq_node.shape[0]
    null_node = t.parent.shape[0] - 1
    D = t.path.shape[1]
    cqid = t.wl_cqid[head_w]
    cqi = jnp.minimum(cqid, C_n - 1)
    cq_node = t.cq_node[cqi]
    my_path = t.path[cq_node]                    # [D]

    # FRs needing preemption: requested and not fitting current avail
    frs_mask = (req > 0) & (req > avail_cq)      # [F]

    # ---- candidate legality (candidate_generator.go:34-160) -------------
    present = cands != W_null
    cand_cqid = t.wl_cqid[cands]                 # [P]
    cand_node = t.cq_node[jnp.minimum(cand_cqid, C_n - 1)]
    is_adm = present & admitted[cands] & (cands != head_w)
    uses = jnp.any(wl_usage[cands] * frs_mask[None, :] > 0, axis=1)
    same_cq = cand_cqid == cqid

    prio_p = t.wl_prio[head_w]
    ts_p = ts[head_w]
    prio_c = t.wl_prio[cands]
    lower = prio_p > prio_c
    # newer-equal: candidate rank beyond the preemptor's threshold
    # (wl_ts_buf == own rank normally; the last within-buffer rank under
    # SchedulerTimestampPreemptionBuffer). An in-drain-evicted preemptor
    # (ts re-stamped past ts_evict_base) was evicted "now", so nothing
    # pending can be newer by more than the buffer.
    buf_p = jnp.where(ts_p >= t.ts_evict_base, BIG, t.wl_ts_buf[head_w])
    newer_eq = (prio_p == prio_c) & (ts[cands] > buf_p)
    policy = jnp.where(same_cq, t.cq_within_policy[cqi],
                       t.cq_reclaim_policy[cqi])
    sat = jnp.where(
        policy == POLICY_NEVER, False,
        jnp.where(policy == POLICY_LOWER_PRIORITY, lower,
                  jnp.where(policy == POLICY_LOWER_OR_NEWER_EQUAL,
                            lower | newer_eq, policy == POLICY_ANY)))
    legal = is_adm & uses & sat

    # ---- LCA ring + hierarchical advantage ------------------------------
    # lca_d[a] = first index on MY path that is an ancestor of cand's CQ
    cand_path = t.path[cand_node]                # [P, D]
    anc = (cand_path[:, :, None] == my_path[None, None, :])  # [P, Dc, Dp]
    is_anc = jnp.any(anc, axis=1)                # [P, Dp]
    is_anc = is_anc & (my_path[None, :] != null_node)
    d_idx = jnp.arange(D, dtype=jnp.int32)[None, :]
    lca_d = jnp.min(jnp.where(is_anc, d_idx, D), axis=1)  # [P]
    other_ok = (lca_d >= 1) & (lca_d < D)        # shares a cohort tree

    # advantage chain along my path (hierarchical_preemption.go);
    # QuantitiesFitInQuota iterates the REQUESTED frs only — an unrelated
    # over-subtree column must not kill the advantage
    nz_req = req > 0
    adv_at = jnp.zeros((D,), dtype=bool)
    adv = jnp.all(~nz_req
                  | (usage0_round[cq_node] + req <= t.subtree[cq_node]))
    rem = jnp.maximum(
        0, req - jnp.maximum(0, t.local_quota[cq_node]
                             - usage0_round[cq_node]))
    for d in range(1, D):
        node = my_path[d]
        ok = node != null_node
        adv_at = adv_at.at[d].set(adv)
        fits_d = jnp.all(
            ~nz_req | (usage0_round[node] + rem <= t.subtree[node])) & ok
        rem = jnp.maximum(
            0, rem - jnp.maximum(0, t.local_quota[node]
                                 - usage0_round[node]))
        adv = adv | fits_d
    hier_adv = adv_at[jnp.minimum(lca_d, D - 1)]  # [W]

    # collection-time within-nominal pruning (round-start usage): the
    # candidate's CQ and every cohort strictly below the LCA must be
    # over nominal for some needed fr (_collect_in_subtree)
    cand_over = ~jnp.all(
        ~frs_mask[None, :]
        | (usage0_round[cand_node] <= t.subtree[cand_node]), axis=1)
    # cohorts on cand's path strictly below the LCA: path entries before
    # the one equal to my_path[lca_d]
    lca_node = my_path[jnp.minimum(lca_d, D - 1)]            # [P]
    seen_lca = jnp.cumsum(
        (cand_path == lca_node[:, None]).astype(jnp.int32), axis=1) > 0
    strictly_below = (~seen_lca) & (cand_path != null_node)
    # skip position 0 (the CQ itself, checked via cand_over)
    strictly_below = strictly_below.at[:, 0].set(False)
    path_over = jnp.all(
        ~strictly_below
        | ~jnp.all(~frs_mask[None, None, :]
                   | (usage0_round[cand_path]
                      <= t.subtree[cand_path]), axis=2),
        axis=1)                                   # [P]
    other_legal = legal & ~same_cq & other_ok & cand_over & path_over
    same_legal = legal & same_cq
    legal_all = other_legal | same_legal

    # ---- variants & groups ----------------------------------------------
    thr = t.cq_bwc_threshold[cqi]
    above_thr = (prio_c >= prio_p) | (
        (thr != NO_THRESHOLD) & (prio_c > thr))
    variant = jnp.where(
        same_cq, V_WITHIN_CQ,
        jnp.where(hier_adv, V_HIERARCHICAL_RECLAIM,
                  jnp.where(t.cq_bwc_forbidden[cqi] | above_thr,
                            V_RECLAIM_WITHOUT_BORROWING,
                            V_RECLAIM_WHILE_BORROWING)))
    group_rank = jnp.where(same_cq, 2, jnp.where(hier_adv, 0, 1))

    # ---- ordering (common/ordering.go CandidatesOrdering) ---------------
    # ``cands`` already carries the shared (priority, -admit_rank, uid)
    # suffix order, so the full ordering reduces to a stable 7-bucket
    # sort: legal first, evicted first, then candidate group.
    not_evicted = ~evicted_f[cands]
    bucket = jnp.where(
        legal_all,
        jnp.where(not_evicted, 3 + group_rank, group_rank), 6)
    p_idx = jnp.arange(p_max, dtype=jnp.int32)
    perm = jnp.argsort(bucket * p_max + p_idx).astype(jnp.int32)
    cand_ok = bucket[perm] < 6
    cand_w = jnp.where(cand_ok, cands[perm], W_null)
    cand_valid = cand_ok
    cand_variant = jnp.where(cand_valid, variant[perm], V_NEVER)
    cand_lca = jnp.where(cand_valid, lca_d[perm], 0)

    # per-candidate walk state on the permuted axis
    v_cqid = t.wl_cqid[cand_w]
    v_node = t.cq_node[jnp.minimum(v_cqid, C_n - 1)]
    v_path = t.path[v_node]                       # [P, D]
    v_usage = wl_usage[cand_w]                    # [P, F]
    v_same = cand_valid & (v_cqid == cqid)
    v_lnode = my_path[jnp.minimum(cand_lca, D - 1)]
    v_seen = jnp.cumsum((v_path == v_lnode[:, None]).astype(jnp.int32),
                        axis=1) > 0
    v_below = (~v_seen) & (v_path != null_node)
    v_below = v_below.at[:, 0].set(False)

    # ---- attempt schedule (preemption.py:508-515) -----------------------
    no_other = ~jnp.any(other_legal)
    no_hier = ~jnp.any(other_legal & hier_adv)
    under_nominal = jnp.all(
        ~frs_mask | (usage0_round[cq_node] < t.nominal[cq_node]))
    bwc_forbidden = t.cq_bwc_forbidden[cqi]
    single = no_other | (bwc_forbidden & ~under_nominal)
    f_then_t = ~single & bwc_forbidden & no_hier
    first_borrow = jnp.where(single, True, jnp.where(f_then_t, False, True))
    second_borrow = jnp.where(f_then_t, True, False)
    has_second = ~single

    # ---- the remove-until-fits walk (one attempt) -----------------------

    def attempt(allow_borrow, run):
        # Infeasibility precheck: remove EVERY candidate this attempt
        # could ever pop (a superset of what the sequential walk removes).
        # available() is monotone non-increasing in usage, so if the
        # preemptor does not fit even then, no subset of removals can
        # succeed — skip the walk entirely. This is what makes contended
        # large-scale rounds cheap: most searches fail, and they fail
        # here in O(tree) instead of any walk steps.
        vb_all = ~(allow_borrow
                   & (cand_variant == V_RECLAIM_WITHOUT_BORROWING))
        removable = cand_valid & vb_all
        rows0 = jnp.where(t.is_cq[:, None], usage0_round, 0)
        rows_min = rows0.at[v_node].add(
            -jnp.where(removable[:, None], v_usage, 0), mode="drop")
        usage_min = refresh_cohort_usage(t, rows_min)
        could_fit = _workload_fits(t, usage_min, cq_node, req, allow_borrow)
        run = run & could_fit

        def cond(carry):
            usage_l, victims, fitted, cursor = carry
            return run & ~fitted & (cursor < p_max)

        def body(carry):
            usage_l, victims, fitted, cursor = carry
            # bulk pop-time validity (_valid, candidate_generator.go)
            # under the current usage: the over-quota predicates only
            # flip true->false as removals shrink usage, and nothing is
            # removed between the cursor and the next valid slot, so
            # invalid-now candidates are invalid at their sequential pop
            # time too — skip them all in one step and remove exactly
            # one true victim.
            cq_over = jnp.any(
                frs_mask[None, :]
                & (usage_l[v_node] > t.subtree[v_node]), axis=1)
            wn = jnp.all(
                ~frs_mask[None, None, :]
                | (usage_l[v_path] <= t.subtree[v_path]), axis=2)
            path_ok = jnp.all(~v_below | ~wn, axis=1)
            valid_now = removable & (v_same | (cq_over & path_ok))
            j = jnp.min(jnp.where(valid_now & (p_idx >= cursor),
                                  p_idx, p_max))
            has = j < p_max
            jc = jnp.minimum(j, p_max - 1)
            u_row = jnp.where(has, v_usage[jc], 0)
            usage_l = _remove_usage_along_path(t, usage_l, v_node[jc],
                                               u_row)
            victims = victims.at[jc].set(victims[jc] | has)
            fitted = has & _workload_fits(
                t, usage_l, cq_node, req, allow_borrow)
            return (usage_l, victims, fitted, j + 1)

        # fresh init constants derive their type from head_w so the
        # carries stay consistent under shard_map's varying-axes check
        # (a no-op on the unsharded path)
        vzero = head_w.astype(jnp.int32) * 0
        vfalse = vzero != 0
        init = (usage0_round, jnp.zeros((p_max,), dtype=bool) | vfalse,
                vfalse, vzero)
        usage_l, victims, fitted, _cur = jax.lax.while_loop(
            cond, body, init)

        # fillBackWorkloads: re-add earlier victims (excluding the last
        # removed) newest-first while the preemptor still fits. Victims
        # were removed in slot order, so slot rank = removal sequence.
        vseq = jnp.cumsum(victims.astype(jnp.int32)) - 1   # [P]
        nv = jnp.max(jnp.where(victims, vseq + 1, 0))

        def fb_cond(carry):
            usage_l, vcur, s = carry
            return fitted & (s >= 0)

        def fb_body(carry):
            usage_l, vcur, s = carry
            match = victims & (vseq == s)
            slot = jnp.argmax(match).astype(jnp.int32)
            tryit = jnp.any(match)
            u_row = jnp.where(tryit, v_usage[slot], 0)
            usage_l = _add_usage_along_path(t, usage_l, v_node[slot],
                                            u_row)
            still = _workload_fits(t, usage_l, cq_node, req, allow_borrow)
            # fit held -> the candidate stays re-added (not a victim);
            # fit broke -> undo the re-add, it remains a victim
            usage_l = _remove_usage_along_path(
                t, usage_l, v_node[slot],
                jnp.where(tryit & ~still, u_row, 0))
            vcur = vcur.at[slot].set(vcur[slot] & ~(tryit & still))
            return (usage_l, vcur, s - 1)

        usage_l, victims, _ = jax.lax.while_loop(
            fb_cond, fb_body, (usage_l, victims, nv - 2))
        return fitted, victims, usage_l

    ok1, v1, u1 = attempt(first_borrow, jnp.ones((), dtype=bool))
    ok2, v2, u2 = attempt(second_borrow, has_second & ~ok1)
    success = ok1 | ok2
    victims = jnp.where(ok1, v1, jnp.where(ok2, v2, False))
    usage_after = jnp.where(ok1, u1, jnp.where(ok2, u2, usage0_round))
    level_f, _ = _height_along_path(t, usage_after, cq_node, req)
    borrow_after = jnp.max(jnp.where(frs_mask, level_f, 0))
    reason = jnp.where(victims, cand_variant, V_NEVER).astype(jnp.int8)
    victim_same = victims & (t.wl_cqid[cand_w] == cqid)
    any_same_cq = jnp.any(victim_same & cand_valid)
    return success, cand_w, victims, reason, any_same_cq, borrow_after


# ---------------------------------------------------------------------------
# round scan: entry processing with preemption issue (scheduler.go:337-467)
# ---------------------------------------------------------------------------


def _quota_to_reserve(t, usage, cq_node, req, borrow):
    """scheduler.go quotaResourcesToReserve for Preempt/NoCandidates."""
    usage_cq = usage[cq_node]
    nominal_cq = t.nominal[cq_node]
    bl = t.borrow_limit[cq_node]
    reserve_borrowing = jnp.where(
        t.has_borrow[cq_node],
        jnp.minimum(req, nominal_cq + bl - usage_cq), req)
    reserve_nominal = jnp.minimum(req, nominal_cq - usage_cq)
    return jnp.maximum(
        0, jnp.where(borrow > 0, reserve_borrowing, reserve_nominal))


def full_round_scan(t: FullTensors, state, cand_w, mode, k_chosen, req_c,
                    borrow, lane_of_entry, lane_success, lane_cand_w,
                    lane_victims, lane_reason, p_max: int,
                    fs_enabled: bool = False, lendable_r=None):
    """Process the round's entries in order; returns updated state parts.

    Entry order is the classical sort (borrow, -priority, timestamp) or,
    under fair sharing, the dynamic per-pop DRS tournament
    (fair_sharing_iterator.go — each pop re-evaluates shares on the
    mutated usage).

    state: (usage_full, usage_net, cq_rows, admitted, parked, wl_usage,
            victims_all, victim_reason)
    """
    C = cand_w.shape[0]
    W1 = t.wl_cqid.shape[0]
    W_null = W1 - 1

    prio = t.wl_prio[cand_w]
    ts_o = state["ts"][cand_w]
    uid = t.wl_uid[cand_w]
    active = (cand_w != W_null) & (mode != M_NOFIT)
    sort_borrow = jnp.where(active, borrow, BIG)
    order = jnp.lexsort((uid, ts_o, -prio, sort_borrow))

    def step(carry, slot):
        (usage_full, usage_net, cq_rows, admitted, parked, wl_usage,
         victims_all, victim_reason, lq_pen, any_adm, any_evict) = carry
        w, cqid, m, req, brw, lane = slot
        cq_node = t.cq_node[jnp.minimum(cqid, C - 1)]
        is_active = (w != W_null) & (m != M_NOFIT)
        searched = lane >= 0
        lane_i = jnp.maximum(lane, 0)
        has_targets = searched & lane_success[lane_i]

        # --- Preempt / NoCandidates: reserve entitled capacity & park ----
        is_reserve = is_active & (m == M_PREEMPT) & searched & ~has_targets
        reserve = jnp.where(
            is_reserve,
            _quota_to_reserve(t, usage_full, cq_node, req, brw), 0)
        usage_full = _add_usage_along_path(t, usage_full, cq_node, reserve)
        usage_net = _add_usage_along_path(t, usage_net, cq_node, reserve)
        parked = parked.at[w].set(
            parked[w] | (is_reserve & ~t.cq_strict[jnp.minimum(cqid, C - 1)]))

        # --- overlap check (one conflicting preemption per cycle) --------
        vm = lane_victims[lane_i]                       # [P]
        vw = lane_cand_w[lane_i]                        # [P]
        overlap = jnp.any(vm & victims_all[vw])
        is_preempt = is_active & (m == M_PREEMPT) & has_targets & ~overlap

        # --- fits re-check under removal of own targets (the preempted
        # set is already excluded from usage_net by earlier steps); the
        # loop is bounded by the lane's last victim slot, not p_max ------
        n_slots = jnp.max(jnp.where(
            vm, jnp.arange(p_max, dtype=jnp.int32) + 1, 0))

        def remove_victims(u, flag):
            def rv_cond(carry):
                _, i = carry
                return flag & (i < n_slots)

            def rv_body(carry):
                u_c, i = carry
                a = vw[i]
                a_node = t.cq_node[jnp.minimum(t.wl_cqid[a], C - 1)]
                row = jnp.where(vm[i], wl_usage[a], 0)
                return (_remove_usage_along_path(t, u_c, a_node, row),
                        i + 1)

            u, _ = jax.lax.while_loop(
                rv_cond, rv_body, (u, jnp.zeros((), dtype=jnp.int32)))
            return u

        usage_probe = remove_victims(usage_net, is_preempt)
        avail_now = _avail_along_path(t, usage_probe, cq_node)
        still_fits = jnp.all((req == 0) | (req <= avail_now))

        # --- issue preemptions (scheduler.go issuePreemptions) -----------
        do_preempt = is_preempt & still_fits
        usage_net = jnp.where(do_preempt, usage_probe, usage_net)
        evict_now = do_preempt & vm                     # [P]
        victims_all = victims_all.at[vw].max(evict_now, mode="drop")
        victims_all = victims_all.at[W_null].set(False)
        # record each victim's candidate variant (preemption reason)
        victim_reason = victim_reason.at[vw].max(
            jnp.where(evict_now, lane_reason[lane_i], 0), mode="drop")
        victim_reason = victim_reason.at[W_null].set(0)
        admitted = admitted.at[vw].min(~evict_now, mode="drop")
        # durable rows: victims' usage leaves their CQ row (P-sized scatter)
        v_nodes = t.cq_node[jnp.minimum(t.wl_cqid[vw], C - 1)]
        cq_rows = cq_rows.at[v_nodes].add(
            -jnp.where(evict_now[:, None], wl_usage[vw], 0), mode="drop")
        # the preemptor charges its assignment usage for the rest of the
        # round (scheduler.go:434 cq.add_usage before issuePreemptions)
        entry_usage = jnp.where(do_preempt, req, 0)
        usage_full = _add_usage_along_path(t, usage_full, cq_node, entry_usage)
        usage_net = _add_usage_along_path(t, usage_net, cq_node, entry_usage)
        any_evict = any_evict | do_preempt

        # --- Fit: re-check then admit ------------------------------------
        avail_fit = _avail_along_path(t, usage_net, cq_node)
        fit_ok = jnp.all((req == 0) | (req <= avail_fit))
        do_admit = is_active & (m == M_FIT) & fit_ok
        admit_vec = jnp.where(do_admit, req, 0)
        usage_full = _add_usage_along_path(t, usage_full, cq_node, admit_vec)
        usage_net = _add_usage_along_path(t, usage_net, cq_node, admit_vec)
        cq_rows = cq_rows.at[cq_node].add(admit_vec)
        admitted = admitted.at[w].set(admitted[w] | do_admit)
        wl_usage = wl_usage.at[w].set(
            jnp.where(do_admit, req, wl_usage[w]))
        # AFS entry penalty: charge the admitted usage to the LocalQueue
        # (afs/entry_penalties.go; scheduler record_admission hook)
        afs_cq = t.cq_afs[jnp.minimum(cqid, C - 1)]
        lq_pen = lq_pen.at[t.wl_lq[w]].add(
            jnp.where(do_admit & afs_cq, t.wl_afs_penalty[w], 0.0))
        any_adm = any_adm | do_admit
        return (usage_full, usage_net, cq_rows, admitted, parked, wl_usage,
                victims_all, victim_reason, lq_pen, any_adm, any_evict), (
            do_admit, do_preempt)

    init = (state["usage_full"], state["usage_net"], state["cq_rows"],
            state["admitted"], state["parked"], state["wl_usage"],
            state["victims_all"], state["victim_reason"],
            state["lq_penalty"],
            jnp.zeros((), dtype=bool), jnp.zeros((), dtype=bool))

    if not fs_enabled:
        slots = (cand_w[order], jnp.arange(C, dtype=jnp.int32)[order],
                 mode[order], req_c[order], borrow[order],
                 lane_of_entry[order])
        (usage_full, usage_net, cq_rows, admitted, parked, wl_usage,
         victims_all, victim_reason, lq_pen, any_adm, any_evict), (
            admitted_slot, preempted_slot) = (
            jax.lax.scan(step, init, slots))
        # map per-slot flags back to entry order
        adm_entry = jnp.zeros((C,), dtype=bool).at[order].set(admitted_slot)
        pre_entry = jnp.zeros((C,), dtype=bool).at[order].set(preempted_slot)
    else:
        from kueue_oss_tpu.solver.fair_kernels import fair_entry_pick

        def fs_cond(carry):
            _inner, act, _adm, _pre, i = carry
            return jnp.any(act) & (i < C)

        def fs_body(carry):
            inner, act, adm_e, pre_e, i = carry
            usage_net_cur = inner[1]
            e = fair_entry_pick(t, lendable_r, usage_net_cur, cand_w,
                                req_c, state["ts"], act)
            ec = jnp.minimum(e, C - 1)
            slot = (cand_w[ec], ec, mode[ec], req_c[ec], borrow[ec],
                    lane_of_entry[ec])
            inner2, (da, dp) = step(inner, slot)
            picked = e < C
            inner = jax.tree_util.tree_map(
                lambda a, b: jnp.where(picked, b, a), inner, inner2)
            adm_e = adm_e.at[ec].set(adm_e[ec] | (picked & da))
            pre_e = pre_e.at[ec].set(pre_e[ec] | (picked & dp))
            act = act.at[ec].set(act[ec] & ~picked)
            return (inner, act, adm_e, pre_e, i + 1)

        fs_init = (init, active,
                   jnp.zeros((C,), dtype=bool), jnp.zeros((C,), dtype=bool),
                   jnp.zeros((), dtype=jnp.int32))
        (inner, _act, adm_entry, pre_entry, _i) = jax.lax.while_loop(
            fs_cond, fs_body, fs_init)
        (usage_full, usage_net, cq_rows, admitted, parked, wl_usage,
         victims_all, victim_reason, lq_pen, any_adm, any_evict) = inner

    return {
        "usage_full": usage_full, "usage_net": usage_net,
        "cq_rows": cq_rows, "admitted": admitted, "parked": parked,
        "wl_usage": wl_usage, "victims_all": victims_all,
        "victim_reason": victim_reason, "lq_penalty": lq_pen,
    }, adm_entry, pre_entry, any_adm, any_evict


# ---------------------------------------------------------------------------
# the drain loop
# ---------------------------------------------------------------------------


def _run_searches(t, usage, wl_usage, admitted, evicted, ts,
                  flat_w, flat_req, flat_avail, flat_cands, p_max,
                  fs_enabled, lendable_r, mesh, axis):
    """Run the per-lane victim searches, optionally SPMD over a mesh.

    The victim search is the round's dominant cost and lanes are
    independent, so multi-chip scaling shards the LANE axis: each
    device searches its slice of (head, option) lanes against the
    replicated round state, and the [L]-shaped results concatenate
    back. Per-round collective volume is the lane results only
    (L x p_max ints over ICI); the tree/usage state never moves.
    """
    def vsearch(hw, rq, av, cd, t_, usage_, wl_usage_, admitted_,
                evicted_, ts_, lendable_):
        if fs_enabled:
            from kueue_oss_tpu.solver.fair_kernels import fair_search

            return jax.vmap(
                lambda a, b, c, d: fair_search(
                    t_, lendable_, usage_, wl_usage_, admitted_,
                    evicted_, ts_, a, b, c, d, p_max))(hw, rq, av, cd)
        return jax.vmap(
            lambda a, b, c, d: classical_search(
                t_, usage_, wl_usage_, admitted_, evicted_, ts_,
                a, b, c, d, p_max))(hw, rq, av, cd)

    if mesh is None:
        return vsearch(flat_w, flat_req, flat_avail, flat_cands, t, usage,
                       wl_usage, admitted, evicted, ts, lendable_r)

    from jax.sharding import PartitionSpec as P

    from kueue_oss_tpu.solver.meshutil import pvary, shard_map

    W_null = t.wl_cqid.shape[0] - 1
    n_dev = mesh.shape[axis]
    L = flat_w.shape[0]
    pad = (-L) % n_dev
    if pad:
        flat_w = jnp.concatenate(
            [flat_w, jnp.full((pad,), W_null, dtype=flat_w.dtype)])
        flat_req = jnp.concatenate(
            [flat_req, jnp.zeros((pad,) + flat_req.shape[1:],
                                 dtype=flat_req.dtype)])
        flat_avail = jnp.concatenate(
            [flat_avail, jnp.zeros((pad,) + flat_avail.shape[1:],
                                   dtype=flat_avail.dtype)])
        flat_cands = jnp.concatenate(
            [flat_cands, jnp.full((pad,) + flat_cands.shape[1:], W_null,
                                  dtype=flat_cands.dtype)])
    lend = lendable_r if lendable_r is not None else jnp.zeros((1,))

    def shard_body(hw, rq, av, cd, *rep):
        # mark the replicated state varying-over-mesh so while_loop
        # carries inside the search have consistent manual-axes types
        rep = jax.tree_util.tree_map(lambda x: pvary(x, axis), rep)
        return vsearch(hw, rq, av, cd, *rep)

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis),
                  P(), P(), P(), P(), P(), P(), P()),
        out_specs=(P(axis),) * 6,
    )
    out = sharded(flat_w, flat_req, flat_avail, flat_cands, t, usage,
                  wl_usage, admitted, evicted, ts, lend)
    if pad:
        out = tuple(o[:L] for o in out)
    return out


def round_body(t: FullTensors, state, pot, g_max: int, h_max: int,
               p_max: int, fs_enabled: bool = False, lendable_r=None,
               mesh=None, axis: str = "wl"):
    """One reference cycle (shared by the jitted loop and debug_drain)."""
    W1 = t.wl_cqid.shape[0]
    C = t.cq_node.shape[0]
    N1 = t.parent.shape[0]
    W_null = W1 - 1

    rounds = state["rounds"]
    admitted = state["admitted"]
    parked = state["parked"]
    ts = state["ts"]
    usage = state["usage"]          # round-start (victims charged)
    wl_usage = state["wl_usage"]
    class_nofit = state["class_nofit"]
    # scheduling-equivalence dedup (cluster_queue.go:371): anything whose
    # class is known NoFit parks before head selection — this catches
    # evicted workloads re-entering the pending set (the host's
    # push(check_no_fit=True) path)
    parked = parked | (~admitted & class_nofit[t.wl_class])
    parked = parked.at[t.wl_cqid.shape[0] - 1].set(False)
    parked_before = parked
    cursor_before = state["cursor"]

    cand_w = select_heads_full(t, admitted, parked, ts,
                               lq_penalty=state["lq_penalty"])
    avail = available_all(t, usage)
    (mode, k_chosen, req_c, borrow, next_cursor,
     opt_fit, opt_preempt, opt_level, group_active, opt_valid) = (
        nominate_full(t, usage, avail, pot, cand_w, state["cursor"], g_max,
                      fs_enabled))

    is_head = cand_w != W_null
    K = t.cq_opt_group.shape[1]

    # ---- which heads need victim-search simulation? ------------------
    # A head with preempt-capable options needs per-option simulation to
    # pick its flavor (the granular-mode walk depends on NoCandidates /
    # Preempt / Reclaim and borrow-after, preemption_oracle.go) — except
    # when the provisional choice is a Fit under default fungibility
    # (whenCanPreempt=TryNextFlavor, BorrowingOverPreemption): there a
    # fit option always beats every preempt option in the walk.
    any_preemptish = jnp.any(opt_preempt & ~opt_fit, axis=1)  # [C]
    fit_wins = (mode == M_FIT) & t.cq_preempt_try_next & ~t.cq_pref_pob
    needs_search = (is_head & any_preemptish & ~fit_wins
                    & (mode != M_NOFIT))

    # ---- compact searching heads into H_MAX lanes (entry order) ------
    ekey = jnp.lexsort((
        t.wl_uid[cand_w], ts[cand_w], -t.wl_prio[cand_w],
        jnp.where(needs_search, borrow, BIG), ~needs_search))
    pe_sorted = needs_search[ekey]
    pos = jnp.cumsum(pe_sorted.astype(jnp.int32)) - 1
    lane_cq = jnp.full((h_max,), C, dtype=jnp.int32)
    lane_cq = lane_cq.at[jnp.where(pe_sorted, pos, h_max)].set(
        ekey.astype(jnp.int32), mode="drop")
    lane_valid = lane_cq < C
    lane_cqc = jnp.minimum(lane_cq, C - 1)
    lane_w = jnp.where(lane_valid, cand_w[lane_cqc], W_null)
    lane_avail = avail[t.cq_node[lane_cqc]]
    lane_of_entry = jnp.full((C,), -1, dtype=jnp.int32)
    lane_of_entry = lane_of_entry.at[
        jnp.where(lane_valid, lane_cq, C)].set(
        jnp.arange(h_max, dtype=jnp.int32), mode="drop")

    # ---- per-option victim-search simulation over [H, K] -------------
    # One search per (lane, option): SimulatePreemption parity (the host
    # runs _get_targets per flavor during assignment; the Preemptor
    # dispatches to the fair-sharing search when enabled). With a mesh,
    # the lane axis shards across devices (_run_searches). Candidates
    # come from the per-root round-start table (build_candidate_table).
    cand_table = build_candidate_table(t, admitted, state["admit_rank"],
                                       wl_usage, p_max)
    lane_cands = cand_table[t.cq_root[lane_cqc]]   # [H, P]

    def search(hw, rq, av, cd):
        return _run_searches(
            t, usage, wl_usage, admitted, state["evicted"], ts,
            hw, rq, av, cd, p_max, fs_enabled, lendable_r, mesh, axis)

    flat_w = jnp.repeat(lane_w, K)
    flat_req = t.wl_req[lane_w].reshape(h_max * K, -1)
    flat_avail = jnp.repeat(lane_avail, K, axis=0)
    flat_cands = jnp.repeat(lane_cands, K, axis=0)
    (s_succ, s_cand_w, s_victims, s_reason, s_same, s_borrow) = search(
        flat_w, flat_req, flat_avail, flat_cands)

    # granular-mode table per (lane, option)
    sim_pmode = jnp.where(
        s_succ, jnp.where(s_same, P_PREEMPT, P_RECLAIM),
        P_NO_CANDIDATES).reshape(h_max, K)
    sim_borrow = s_borrow.reshape(h_max, K)
    fit_l = opt_fit[lane_cqc]                     # [H, K]
    pre_l = (opt_preempt & ~opt_fit)[lane_cqc]
    pmode_k = jnp.where(fit_l, P_FIT,
                        jnp.where(pre_l, sim_pmode, P_NOFIT))
    borrow_k = jnp.where(fit_l, opt_level[lane_cqc],
                         jnp.where(pre_l, sim_borrow, 0))

    # ---- the assigner's walk picks each lane's final assignment ------
    walk = jax.vmap(
        lambda hw, pm, bo, va, ga: walk_assign(t, hw, pm, bo, va, ga,
                                               g_max))
    (l_mode, l_k, l_req, l_borrow, l_next_cursor, l_pmode_sel) = walk(
        lane_w, pmode_k, borrow_k, opt_valid[lane_cqc],
        group_active[lane_cqc])
    l_req = jnp.where(lane_valid[:, None], l_req, 0)

    lane_target = jnp.where(lane_valid, lane_cq, C)
    mode = mode.at[lane_target].set(l_mode, mode="drop")
    k_chosen = k_chosen.at[lane_target].set(l_k, mode="drop")
    req_c = req_c.at[lane_target].set(l_req, mode="drop")
    borrow = borrow.at[lane_target].set(l_borrow, mode="drop")
    next_cursor = next_cursor.at[lane_target].set(
        l_next_cursor, mode="drop")

    # ---- final victim set for each preempting lane -------------------
    if g_max == 1:
        # single group: the chosen option's simulation IS the final
        # search (same request vector, same FRs)
        idx = jnp.arange(h_max, dtype=jnp.int32) * K + l_k[:, 0]
        lane_success = s_succ[idx]
        lane_cand_w = s_cand_w[idx]
        lane_victims = s_victims[idx]
        lane_reason = s_reason[idx]
    else:
        # multi-group: GetTargets re-runs on the combined assignment
        # usage (preemption.py get_targets with all preempt-mode frs)
        (lane_success, lane_cand_w, lane_victims, lane_reason,
         _s, _b) = search(lane_w, l_req, lane_avail, lane_cands)
    lane_success = (lane_success & lane_valid & (l_mode == M_PREEMPT))

    # compact victims to the front of each lane's slot axis: the entry
    # scan's removal loops run `last victim slot + 1` iterations, and a
    # victim sitting at slot 3000 of a long candidate list would turn
    # them into thousands of sequential steps per entry
    def _compact(vw_row, vm_row, re_row):
        key = jnp.where(vm_row, jnp.arange(p_max, dtype=jnp.int32), p_max)
        order = jnp.argsort(key)
        return vw_row[order], vm_row[order], re_row[order]

    lane_cand_w, lane_victims, lane_reason = jax.vmap(_compact)(
        lane_cand_w, lane_victims, lane_reason)

    # park NoFit heads of BestEffortFIFO queues (post-walk modes)
    park_now = is_head & (mode == M_NOFIT) & ~t.cq_strict
    parked = parked.at[cand_w].set(parked[cand_w] | park_now)

    # ---- entry scan ---------------------------------------------
    scan_state = {
        "usage_full": usage, "usage_net": usage,
        "cq_rows": state["cq_rows"], "admitted": admitted,
        "parked": parked, "wl_usage": wl_usage,
        "victims_all": jnp.zeros((W1,), dtype=bool),
        "victim_reason": state["victim_reason"], "ts": ts,
        "lq_penalty": state["lq_penalty"],
    }
    out, adm_entry, pre_entry, any_adm, any_evict = full_round_scan(
        t, scan_state, cand_w, mode, k_chosen, req_c, borrow,
        lane_of_entry, lane_success, lane_cand_w, lane_victims,
        lane_reason, p_max, fs_enabled=fs_enabled, lendable_r=lendable_r)
    admitted = out["admitted"]
    parked = out["parked"]
    wl_usage = out["wl_usage"]
    victims = out["victims_all"]

    # ---- bookkeeping for evicted victims ------------------------
    ts = jnp.where(victims, t.ts_evict_base + rounds, ts)
    evicted_f = state["evicted"] | victims
    admit_rank = jnp.where(victims, 0, state["admit_rank"])
    # re-admissions: clear Evicted, stamp reservation rank; the ordering
    # timestamp reverts to creation (the host clears the Evicted
    # condition, so queue_order_timestamp falls back to creation_time)
    newly = adm_entry & (cand_w != W_null)
    adm_w = jnp.where(newly, cand_w, W_null)
    ts = ts.at[adm_w].set(
        jnp.where(newly, t.wl_ts0[adm_w], ts[adm_w]), mode="drop")
    evicted_f = evicted_f.at[adm_w].set(
        jnp.where(newly, False, evicted_f[adm_w]), mode="drop")
    admit_rank = admit_rank.at[adm_w].set(
        jnp.where(newly, t.admit_rank_base + rounds,
                  admit_rank[adm_w]), mode="drop")
    evicted_f = evicted_f.at[W_null].set(False)

    # record chosen options + admit round for decode
    opt = state["opt"]
    admit_round = state["admit_round"]
    opt = opt.at[adm_w].set(
        jnp.where(newly[:, None], k_chosen, opt[adm_w]), mode="drop")
    admit_round = admit_round.at[adm_w].set(
        jnp.where(newly, rounds, admit_round[adm_w]), mode="drop")

    # flavor cursors: heads still pending resume their walk; an entry
    # that ISSUED preemptions restarts from flavor 0 next round (the
    # host clears last_assignment in _issue_preemptions,
    # scheduler.go:447 area)
    keep = is_head & ~admitted[cand_w]
    new_cur = jnp.where(pre_entry[:, None], 0, next_cursor)
    cursor = state["cursor"].at[cand_w].set(
        jnp.where(keep[:, None], new_cur,
                  state["cursor"][cand_w]), mode="drop")
    # an evicted workload restarts its flavor walk
    cursor = jnp.where(victims[:, None], 0, cursor)

    # ---- NoFit equivalence classes (handleInadmissibleHash): a head
    # parked this round marks its class NoFit; every pending equivalent
    # parks with it until the capacity-freed flush clears the class
    newly_parked = parked & ~parked_before
    class_nofit = class_nofit.at[
        jnp.where(newly_parked, t.wl_class,
                  class_nofit.shape[0] - 1)].max(newly_parked, mode="drop")
    class_nofit = class_nofit.at[class_nofit.shape[0] - 1].set(False)
    parked = parked | (~admitted & class_nofit[t.wl_class])
    parked = parked.at[W_null].set(False)

    # ---- capacity-freed flush: unpark cohort roots with evictions
    freed_root = jnp.zeros((N1,), dtype=bool)
    victim_roots = t.cq_root[jnp.minimum(t.wl_cqid[:-1], C - 1)]
    freed_root = freed_root.at[victim_roots].max(victims[:-1])
    wl_root = t.cq_root[jnp.minimum(t.wl_cqid, C - 1)]
    parked = parked & ~freed_root[wl_root]
    class_nofit = class_nofit & ~freed_root[t.class_root]

    # ---- durable usage for next round ---------------------------
    usage_next = refresh_cohort_usage(t, out["cq_rows"])

    progress = (any_adm | any_evict
                | jnp.any(parked & ~parked_before)
                | jnp.any(cursor != cursor_before))
    new_state = {
        "usage": usage_next, "cq_rows": out["cq_rows"],
        "admitted": admitted, "parked": parked, "ts": ts,
        "evicted": evicted_f, "admit_rank": admit_rank,
        "wl_usage": wl_usage, "cursor": cursor, "opt": opt,
        "admit_round": admit_round, "class_nofit": class_nofit,
        "victim_reason": out["victim_reason"],
        "lq_penalty": out["lq_penalty"], "progress": progress,
        "rounds": rounds + 1,
    }
    debug = {
        "cand_w": cand_w, "mode": mode, "req_c": req_c,
        "victims": victims, "adm_entry": adm_entry,
        "lane_w": lane_w, "lane_success": lane_success,
        "lane_cand_w": lane_cand_w, "lane_victims": lane_victims,
    }
    return new_state, debug


def _init_state(t: FullTensors, g_max: int):
    W1 = t.wl_cqid.shape[0]
    return {
        "usage": t.usage0,
        "cq_rows": jnp.where(t.is_cq[:, None], t.usage0, 0),
        "admitted": t.wl_admitted0,
        "parked": t.wl_parked0,
        "ts": t.wl_ts0,
        "evicted": t.wl_evicted0,
        "admit_rank": t.wl_admit_rank0,
        "wl_usage": t.ad_usage,
        "cursor": jnp.zeros((W1, g_max), dtype=jnp.int32),
        "opt": jnp.zeros((W1, g_max), dtype=jnp.int32),
        "admit_round": jnp.full((W1,), -1, dtype=jnp.int32),
        "victim_reason": jnp.zeros((W1,), dtype=jnp.int8),
        "lq_penalty": t.lq_penalty0,
        "class_nofit": jnp.zeros((t.class_root.shape[0],), dtype=bool),
        "progress": jnp.ones((), dtype=bool),
        "rounds": jnp.zeros((), dtype=jnp.int32),
    }


def _solve_full_impl(t: FullTensors, g_max: int, h_max: int, p_max: int,
                     fs_enabled: bool = False, round_cap: int = 0,
                     mesh=None, axis: str = "wl"):
    """The drain body shared by the single-problem jit
    (:func:`make_full_solver`) and the scenario-batched vmap
    (:func:`solve_backlog_full_batched`). Pure traced jnp code — the
    static caps select the program, the tensors are the only inputs."""
    W1 = t.wl_cqid.shape[0]
    C = t.cq_node.shape[0]
    W_null = W1 - 1
    pot = potential_available_all(t)
    if fs_enabled:
        from kueue_oss_tpu.solver.fair_kernels import (
            lendable_by_resource,
        )

        lendable_r = lendable_by_resource(t, pot)
    else:
        lendable_r = None
    bound = 2 * W1 + C + 5
    if round_cap:
        bound = min(bound, round_cap)

    def cond(state):
        return state["progress"] & (state["rounds"] < bound)

    def body(state):
        new_state, _ = round_body(t, state, pot, g_max, h_max, p_max,
                                  fs_enabled, lendable_r, mesh, axis)
        return new_state

    final = jax.lax.while_loop(cond, body, _init_state(t, g_max))
    admitted = final["admitted"].at[W_null].set(False)
    parked = final["parked"].at[W_null].set(False)
    return (admitted, final["opt"], final["admit_round"], parked,
            final["rounds"], final["usage"], final["wl_usage"],
            final["victim_reason"])


def make_full_solver(g_max: int, h_max: int, p_max: int,
                     fs_enabled: bool = False, round_cap: int = 0,
                     mesh=None, axis: str = "wl"):
    """Build the jitted preemption-capable drain for static caps.

    ``round_cap`` > 0 bounds the drain's rounds below the quiescence
    bound (benchmarks use it to terminate preemption ping-pong shapes
    the way the reference's wall-clock limits do). ``mesh`` shards the
    victim-search lane axis across devices (see _run_searches)."""

    @jax.jit
    def solve(t: FullTensors):
        return _solve_full_impl(t, g_max, h_max, p_max, fs_enabled,
                                round_cap, mesh, axis)

    return solve


def debug_drain(problem: SolverProblem, g_max: int, h_max: int = 8,
                p_max: int = 32, max_rounds: int = 64, verbose: bool = True,
                fs_enabled: bool = False):
    """Python-loop drain printing per-round events (development aid)."""
    import numpy as np

    t = to_device_full(problem)
    pot = potential_available_all(t)
    if fs_enabled:
        from kueue_oss_tpu.solver.fair_kernels import lendable_by_resource

        lendable_r = lendable_by_resource(t, pot)
    else:
        lendable_r = None
    state = _init_state(t, g_max)
    W_null = t.wl_cqid.shape[0] - 1
    step = jax.jit(lambda tt, st: round_body(tt, st, pot, g_max, h_max,
                                             p_max, fs_enabled, lendable_r))

    def name(w):
        w = int(w)
        return problem.wl_keys[w] if w < W_null else "-"

    for r in range(max_rounds):
        state, dbg = step(t, state)
        if verbose:
            heads = [(name(w), int(m), int(b))
                     for w, m, b in zip(np.asarray(dbg["cand_w"]),
                                        np.asarray(dbg["mode"]),
                                        np.asarray(dbg["req_c"]).sum(1))
                     if int(w) != W_null]
            evs = [name(i) for i, v in
                   enumerate(np.asarray(dbg["victims"])[:-1]) if v]
            adms = [name(w) for w, a in zip(np.asarray(dbg["cand_w"]),
                                            np.asarray(dbg["adm_entry"]))
                    if a and int(w) != W_null]
            print(f"round {r}: heads(mode,req)={heads} "
                  f"admitted={adms} evicted={evs}")
        if not bool(state["progress"]):
            break
    return state


_solver_cache: dict = {}


def solve_backlog_full(t: FullTensors, g_max: int, h_max: int = 32,
                       p_max: int = 128, fs_enabled: bool = False,
                       mesh=None, axis: str = "wl"):
    """Cached-jit entry point; (g_max, h_max, p_max, fs) are compile-time.

    The fair-sharing gates are baked in at trace time, so they join the
    cache key — a gate flip must not serve a stale compilation. With a
    ``mesh``, the victim-search lanes shard across its devices
    (_run_searches); the mesh joins the key so single-chip and mesh
    programs coexist."""
    from kueue_oss_tpu import features

    gates = ()
    if fs_enabled:
        gates = (features.enabled("FairSharingPreemptWithinNominal"),
                 features.enabled("FairSharingPrioritizeNonBorrowing"),
                 features.enabled("PrioritySortingWithinCohort"))
    key = (g_max, h_max, p_max, fs_enabled, gates, mesh, axis)
    fn = _solver_cache.get(key)
    if fn is None:
        fn = make_full_solver(g_max, h_max, p_max, fs_enabled,
                              mesh=mesh, axis=axis)
        _solver_cache[key] = fn
    return fn(t)


#: FullTensors fields the scenario overlay layer varies — the FULL
#: twins of kernels.BATCHABLE_FIELDS (lean ``wl_ts`` is ``wl_ts0``
#: here; the lean ``wl_rank`` has no FULL twin: the full kernel
#: selects heads by (priority, ts, uid) and masked rows drop out of
#: the per-CQ segment reductions through ``wl_cqid = C``).
FULL_BATCHABLE_FIELDS = frozenset({
    "nominal", "subtree", "local_quota", "has_borrow", "borrow_limit",
    "usage0", "wl_cqid", "wl_prio", "wl_ts0", "wl_valid", "wl_req",
})

#: Every FullTensors field. Like the lean kernel, the drain body is
#: shape-static gather/scatter arithmetic with no host-side dependence
#: on array content, so any field may carry the scenario axis;
#: FULL_BATCHABLE_FIELDS remains the documented overlay subset.
ALL_FULL_FIELDS = frozenset(FullTensors._fields)


def solve_backlog_full_batched(t: FullTensors, overrides: dict,
                               g_max: int, h_max: int = 32,
                               p_max: int = 128,
                               fs_enabled: bool = False,
                               round_cap: int = 0):
    """Solve S counterfactual variants of one FULL problem in ONE
    device dispatch: ``jit(vmap)`` of the preemption-capable drain.

    ``overrides`` maps FullTensors field names to stacked [S, ...]
    scenario variants; unnamed fields broadcast unbatched (the large
    ``wl_req`` tensor on quota-only sweeps costs one copy, not S).
    Returns the solve_backlog_full 8-tuple with a leading scenario
    axis on every output. The victim-search lane memory scales as
    S x h_max x K x p_max — callers size S from a
    :class:`~kueue_oss_tpu.sim.batch.LaneBudget`, not from the sweep
    width. Mesh lane-sharding never composes with the scenario axis
    (the batched path is single-program; chunking IS the scale story).
    """
    if not overrides:
        raise ValueError("batched full solve needs at least one "
                         "scenario-varying field (use "
                         "solve_backlog_full otherwise)")
    bad = set(overrides) - ALL_FULL_FIELDS
    if bad:
        raise ValueError(
            f"fields {sorted(bad)} are not FullTensors fields; "
            f"batchable: {sorted(ALL_FULL_FIELDS)}")
    from kueue_oss_tpu import features

    gates = ()
    if fs_enabled:
        gates = (features.enabled("FairSharingPreemptWithinNominal"),
                 features.enabled("FairSharingPrioritizeNonBorrowing"),
                 features.enabled("PrioritySortingWithinCohort"))
    key = ("batched", frozenset(overrides), g_max, h_max, p_max,
           fs_enabled, gates, round_cap)
    fn = _solver_cache.get(key)
    if fn is None:
        axes = FullTensors(
            **{f: (0 if f in overrides else None)
               for f in FullTensors._fields})
        fn = jax.jit(jax.vmap(
            partial(_solve_full_impl, g_max=g_max, h_max=h_max,
                    p_max=p_max, fs_enabled=fs_enabled,
                    round_cap=round_cap),
            in_axes=(axes,)))
        _solver_cache[key] = fn
    return fn(t._replace(**{k: jnp.asarray(v)
                            for k, v in overrides.items()}))
