"""Convex-relaxation fast path for the batched admission problem.

The exact lean kernel (kernels._solve_backlog_impl) replays
priority-ordered rounds whose count grows with per-CQ backlog depth and
contention; on huge contended backlogs the round loop dominates the
drain wall. CvxCluster (arXiv 2605.01614) shows that large granular
allocation problems admit convex relaxations solved orders of magnitude
faster as dense matrix iterations — exactly the shape that ``jit``,
``vmap``, and mesh sharding love. This module is that arm:

1. **Relaxation** — the admission LP over a fractional admit vector
   x ∈ [0, 1]^W maximizing priority-weighted admission subject to one
   capacity row per (hierarchy node, flavor-resource):

       max  Σ_w s_w x_w
       s.t. Σ_{w under n}  req_w,f · x_w  ≤  slack_n,f      ∀ (n, f)

   ``slack`` is the node's aggregate headroom: subtree quota plus its
   borrowing allowance, minus the full-charge total of current CQ
   usage. Solved by fixed-iteration projected gradient ascent on a
   quadratic penalty (pure ``jax.numpy``: one fori_loop of segment-sum
   + ancestor-accumulate + clip per iteration — it jits, vmaps, and
   shards over the ``wl`` mesh axis trivially; sharded variant in
   solver/sharded.py:make_sharded_relax_lp).

2. **Rounding** — deterministic support selection on the host: rows
   with x above the threshold, per-CQ slack rows by relaxed score
   (ties broken by FIFO rank, so symmetric contention rounds to the
   exact kernel's FIFO prefix), every live row of StrictFIFO CQs
   (their heads may never be skipped), and a per-CQ allowance sized by
   the CQ's fractional mass so the repair pass can fill capacity the
   threshold underestimated.

3. **Repair** — the EXACT lean kernel, run on the support rows
   compacted into a small padded subproblem (same node/CQ tensors,
   gathered workload rows). Whatever it admits is exactly feasible by
   construction; results scatter back to full workload indices and the
   emitted plan passes ``SolverEngine._check_plan`` unchanged. Rows
   outside the support park (BestEffortFIFO) exactly like the exact
   kernel's quiescent state; StrictFIFO rows never park.

The plan is therefore ALWAYS exactly feasible — approximation error
can only show up as a different (usually identical, see the router's
audit in solver/engine.py) admitted set, never as overcommitted quota.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from dataclasses import dataclass, field
from typing import NamedTuple, Optional

import numpy as np

from kueue_oss_tpu.solver.tensors import BIG, SolverProblem, pow2

#: projected-gradient constants: step size, score (objective) weight,
#: and the quadratic-penalty ramp rho0 * (1 + growth * i / iters). The
#: LP only has to CONCENTRATE mass and ORDER candidates — the repair
#: pass is exact — so these favor robustness over last-digit optimality.
ETA = 0.5
ALPHA = 0.05
RHO0 = 1.0
RHO_GROWTH = 3.0

#: effectively-unbounded capacity for constraint rows that only bind at
#: an ancestor (non-root nodes without a borrowing limit)
UNBOUNDED = np.float32(1 << 30)


class RelaxLP(NamedTuple):
    """Device inputs of the relaxed admission LP (jit pytree).

    Workload-axis fields (``r``, ``s``, ``live``, ``wl_cqid``) shard
    over the mesh ``wl`` axis; node/CQ fields replicate.
    """

    r: np.ndarray        # [W+1, F] float32 request under the first valid option
    s: np.ndarray        # [W+1] float32 priority-major, FIFO-minor score
    live: np.ndarray     # [W+1] bool
    wl_cqid: np.ndarray  # [W+1] int32
    cq_node: np.ndarray  # [C] int32
    path_cq: np.ndarray  # [C, D] int32 ancestor chain of each CQ's node
    parent: np.ndarray   # [N+1] int32
    depth: np.ndarray    # [N+1] int32
    slack: np.ndarray    # [N+1, F] float32 aggregate headroom per node
    scale: np.ndarray    # [N+1, F] float32 max(slack, 1) normalizer


@dataclass
class RelaxStats:
    """Diagnostics for one relaxed solve (bench/metrics/ledger)."""

    live: int = 0
    support: int = 0
    support_padded: int = 0
    iters: int = 0
    lp_seconds: float = 0.0
    repair_seconds: float = 0.0
    repair_rounds: int = 0
    #: final fractional solution (tests/diagnostics; [W+1] float32)
    x: Optional[np.ndarray] = field(default=None, repr=False)


def lp_step_body(lp: RelaxLP, x, i, iters: int, psum_axis=None):
    """One projected-gradient iteration (shared by the single-chip jit
    and the shard_map variant, which psums the per-CQ loads over the
    mesh axis)."""
    import jax
    import jax.numpy as jnp

    from kueue_oss_tpu.solver.kernels import accumulate_full_charge

    C = lp.cq_node.shape[0]
    N1 = lp.parent.shape[0]
    F = lp.r.shape[1]
    d_max = lp.path_cq.shape[1]
    load_cq = jax.ops.segment_sum(lp.r * x[:, None], lp.wl_cqid,
                                  num_segments=C + 1)[:C]
    if psum_axis is not None:
        load_cq = jax.lax.psum(load_cq, psum_axis)
    u = jnp.zeros((N1, F), lp.r.dtype).at[lp.cq_node].add(load_cq)
    u = accumulate_full_charge(lp.parent, lp.depth, u, d_max)
    # RELATIVE violation, clipped: scale-invariant pricing. Normalizing
    # by scale**2 (the literal quadratic-penalty gradient) crushes the
    # price on large-capacity rows (a cohort with slack ~10^3 would
    # price a 5x oversubscription below the score term) — relative
    # overflow prices a 2x-oversubscribed 8-cpu CQ and a 2x
    # oversubscribed 2000-cpu cohort identically.
    over = jnp.clip((u - lp.slack) / lp.scale, 0.0, 1.0)
    price = over[lp.path_cq].sum(axis=1)              # [C, F]
    rho = RHO0 * (1.0 + RHO_GROWTH * i / iters)
    # per-row request normalized by its own largest component, so the
    # downstep stays O(rho) for any request magnitude (no overshoot
    # for 100-unit rows, no stall for 1-unit rows)
    rnorm = lp.r / jnp.maximum(lp.r.max(axis=1, keepdims=True), 1.0)
    g = ALPHA * lp.s - rho * (rnorm * price[lp.wl_cqid]).sum(axis=1)
    x = jnp.clip(x + ETA * g, 0.0, 1.0)
    return jnp.where(lp.live, x, 0.0)


def lp_loop(lp: RelaxLP, iters: int, psum_axis=None):
    """The full fixed-iteration LP solve (trace-time body)."""
    import jax
    import jax.numpy as jnp

    x0 = jnp.where(lp.live, jnp.float32(0.5), jnp.float32(0.0))
    return jax.lax.fori_loop(
        0, iters,
        lambda i, x: lp_step_body(lp, x, i, iters, psum_axis), x0)


@functools.lru_cache(maxsize=None)
def _single_lp(iters: int):
    import jax

    return jax.jit(functools.partial(lp_loop, iters=iters))


# ---------------------------------------------------------------------------
# LP assembly (host)
# ---------------------------------------------------------------------------


def _full_charge_np(parent: np.ndarray, depth: np.ndarray,
                    values: np.ndarray, d_max: int) -> np.ndarray:
    """Numpy twin of kernels.accumulate_full_charge for the per-drain
    constant headroom tensors."""
    u = values.copy()
    for d in range(d_max - 1, 0, -1):
        rows = depth == d
        np.add.at(u, parent[rows], u[rows])
    return u


def build_lp(problem: SolverProblem) -> RelaxLP:
    """Assemble the LP tensors from a (padded) lean export."""
    C = problem.n_cqs
    W1 = problem.wl_cqid.shape[0]
    cqid = np.asarray(problem.wl_cqid)
    valid = np.asarray(problem.wl_valid)
    live = np.zeros(W1, dtype=bool)
    live[:-1] = (cqid[:-1] < C) & valid[:-1].any(axis=1)

    # request under the FIRST valid flavor option; the repair pass
    # re-runs the exact fungibility policy, so the relaxation only
    # needs one representative request vector per row
    k0 = np.argmax(valid, axis=1).astype(np.int64)
    r = np.asarray(problem.wl_req)[np.arange(W1), k0].astype(np.float32)
    r[~live] = 0.0

    prio = np.asarray(problem.wl_prio).astype(np.float32)
    ts = np.asarray(problem.wl_ts).astype(np.float32)
    p_lo = float(prio[live].min()) if live.any() else 0.0
    p_hi = float(prio[live].max()) if live.any() else 0.0
    t_hi = float(ts[live].max()) if live.any() else 0.0
    s = ((prio - p_lo) / max(1.0, p_hi - p_lo)
         + 0.25 * (1.0 - ts / max(1.0, t_hi))).astype(np.float32)
    s[~live] = 0.0

    # capacity rows: subtree quota + borrowing allowance (non-root
    # nodes without a limit only bind at their ancestors), minus the
    # full-charge total of current CQ usage under the node
    subtree = np.asarray(problem.subtree).astype(np.float32)
    extra = np.where(
        np.asarray(problem.has_borrow),
        np.asarray(problem.borrow_limit).astype(np.float32),
        np.where(np.asarray(problem.has_parent)[:, None],
                 UNBOUNDED, np.float32(0.0)))
    cap = np.minimum(subtree + extra, UNBOUNDED)
    is_cq = np.zeros(problem.parent.shape[0], dtype=bool)
    is_cq[problem.cq_node] = True
    usage_cq = np.where(is_cq[:, None],
                        np.asarray(problem.usage0), 0).astype(np.float32)
    d_max = problem.path.shape[1]
    base = _full_charge_np(np.asarray(problem.parent),
                           np.asarray(problem.depth), usage_cq, d_max)
    slack = np.maximum(cap - base, 0.0).astype(np.float32)
    scale = np.maximum(slack, 1.0).astype(np.float32)

    return RelaxLP(
        r=r, s=s, live=live, wl_cqid=cqid.astype(np.int32),
        cq_node=np.asarray(problem.cq_node).astype(np.int32),
        path_cq=np.asarray(problem.path)[problem.cq_node].astype(np.int32),
        parent=np.asarray(problem.parent).astype(np.int32),
        depth=np.asarray(problem.depth).astype(np.int32),
        slack=slack, scale=scale)


# ---------------------------------------------------------------------------
# Rounding: deterministic support selection (host)
# ---------------------------------------------------------------------------


def strict_rows(problem: SolverProblem) -> np.ndarray:
    """[W+1] mask of rows whose CQ is StrictFIFO — the ONE definition
    of the strict-semantics rule both the rounding (strict rows always
    join the support) and the plan assembly (strict rows never park)
    share."""
    cq = np.asarray(problem.wl_cqid)
    strict = np.zeros(cq.shape[0], dtype=bool)
    m = cq < problem.n_cqs
    strict[m] = np.asarray(problem.cq_strict)[cq[m]].astype(bool)
    return strict


def rounded_support(x: np.ndarray, problem: SolverProblem,
                    live: np.ndarray, threshold: float = 0.5,
                    slack_frac: float = 0.25, slack_min: int = 4,
                    strict: Optional[np.ndarray] = None) -> np.ndarray:
    """Boolean support mask over the real workload rows [W].

    Selected: live rows with x >= threshold; every live StrictFIFO row
    (a strict head must never be skipped — admitting past it would
    diverge from the reference's blocking semantics); and per CQ, extra
    rows by (-x, FIFO rank) up to an allowance of
    ``slack_min + ceil(slack_frac * selected + unselected fractional
    mass)`` — the mass term sizes the allowance to the capacity the LP
    thinks is still fillable, so a diffuse symmetric solution still
    rounds to the exact kernel's FIFO prefix.
    """
    C = problem.n_cqs
    W = problem.wl_cqid.shape[0] - 1
    cq = np.asarray(problem.wl_cqid)[:W]
    livew = np.asarray(live)[:W]
    xw = np.asarray(x)[:W]
    if strict is None:
        strict = strict_rows(problem)
    sel = livew & ((xw >= threshold) | strict[:W])
    cand = np.nonzero(livew & ~sel)[0]
    if cand.size:
        rank = np.asarray(problem.wl_rank)[:W]
        order = cand[np.lexsort((rank[cand], -xw[cand], cq[cand]))]
        cqs = cq[order]
        starts = np.r_[True, cqs[1:] != cqs[:-1]]
        idx = np.arange(order.size)
        gi = idx - np.maximum.accumulate(np.where(starts, idx, 0))
        n_sel = np.bincount(cq[sel], minlength=C + 1)
        mass = np.bincount(cq[cand], weights=xw[cand], minlength=C + 1)
        allow = (slack_min
                 + np.ceil(slack_frac * n_sel + mass)).astype(np.int64)
        sel[order[gi < allow[cqs]]] = True
    return sel


# ---------------------------------------------------------------------------
# Repair: the exact lean kernel on the compacted support
# ---------------------------------------------------------------------------


def restrict_problem(problem: SolverProblem, sel_idx: np.ndarray,
                     target_w: int) -> SolverProblem:
    """Compact a padded lean problem to the support rows (+ inert null
    fills up to ``target_w`` and the trailing null row). Node/CQ
    tensors are untouched; per-CQ FIFO rank ORDER is preserved because
    the gather keeps ascending row order and ranks ride along."""
    W1 = problem.wl_cqid.shape[0]
    rows = np.concatenate([
        np.asarray(sel_idx, dtype=np.int64),
        np.full(target_w + 1 - len(sel_idx), W1 - 1, dtype=np.int64),
    ])
    return dataclasses.replace(
        problem,
        wl_cqid=np.ascontiguousarray(problem.wl_cqid[rows]),
        wl_rank=np.ascontiguousarray(problem.wl_rank[rows]),
        wl_prio=np.ascontiguousarray(problem.wl_prio[rows]),
        wl_ts=np.ascontiguousarray(problem.wl_ts[rows]),
        wl_uid=np.ascontiguousarray(problem.wl_uid[rows]),
        wl_req=np.ascontiguousarray(problem.wl_req[rows]),
        wl_valid=np.ascontiguousarray(problem.wl_valid[rows]),
    )


def repair(problem: SolverProblem, sel: np.ndarray, live: np.ndarray,
           pad_to: int = 0,
           strict: Optional[np.ndarray] = None) -> tuple[tuple, RelaxStats]:
    """Run the exact lean kernel on the rounded support and scatter the
    plan back to full workload indices.

    Returns the full ``solve_backlog`` contract — (admitted, opt,
    admit_round, parked, rounds, usage), numpy, [W+1]-shaped — plus
    stats. ``pad_to`` is the caller's sticky support pad target so
    steady-state drains reuse one compiled repair program.
    """
    from kueue_oss_tpu.solver.kernels import solve_backlog, to_device

    W1 = problem.wl_cqid.shape[0]
    sel_idx = np.nonzero(sel)[0]
    S = len(sel_idx)
    target = max(pow2(S + 1) - 1, pad_to)
    stats = RelaxStats(live=int(np.asarray(live)[:-1].sum()), support=S,
                       support_padded=target)

    t0 = time.monotonic()
    sub = restrict_problem(problem, sel_idx, target)
    out = solve_backlog(to_device(sub))
    out = tuple(np.asarray(a) for a in out)
    stats.repair_seconds = time.monotonic() - t0

    adm_s, opt_s, round_s, parked_s, rounds, usage = out
    admitted = np.zeros(W1, dtype=bool)
    opt = np.zeros(W1, dtype=np.int32)
    admit_round = np.zeros(W1, dtype=np.int32)
    admitted[sel_idx] = adm_s[:S].astype(bool)
    opt[sel_idx] = opt_s[:S]
    admit_round[sel_idx] = np.where(adm_s[:S].astype(bool),
                                    round_s[:S], 0)
    # rows the plan leaves unadmitted park exactly like the exact
    # kernel's quiescent state: every live BestEffortFIFO row; never a
    # StrictFIFO row (their heads block in place)
    if strict is None:
        strict = strict_rows(problem)
    parked = np.asarray(live, dtype=bool) & ~admitted & ~strict
    parked[-1] = False
    admitted[-1] = False
    stats.repair_rounds = int(rounds)
    return (admitted, opt, admit_round, parked, rounds, usage), stats


# ---------------------------------------------------------------------------
# The whole arm
# ---------------------------------------------------------------------------


def solve_relaxed(problem: SolverProblem, *, iters: int = 32,
                  threshold: float = 0.5, mesh=None,
                  pad_to: int = 0) -> tuple[tuple, RelaxStats]:
    """Relax → round → repair one padded lean problem.

    With a ``mesh`` (whose width divides the padded axis) the LP
    iterations run sharded over the ``wl`` axis; the repair subproblem
    is small by construction and stays single-chip. The emitted plan is
    exactly feasible (it IS a lean-kernel plan over the support) and
    passes the engine's ``_check_plan`` unchanged.
    """
    lp = build_lp(problem)
    t0 = time.monotonic()
    if mesh is not None:
        from kueue_oss_tpu.solver import meshutil

        if meshutil.mesh_divisible(mesh, lp.r.shape[0]):
            fn = meshutil.relax_mesh_lp(mesh, iters)
        else:
            fn = _single_lp(iters)
    else:
        fn = _single_lp(iters)
    x = np.asarray(fn(lp))
    lp_seconds = time.monotonic() - t0

    strict = strict_rows(problem)
    sel = rounded_support(x, problem, lp.live, threshold=threshold,
                          strict=strict)
    out, stats = repair(problem, sel, lp.live, pad_to=pad_to,
                        strict=strict)
    stats.iters = iters
    stats.lp_seconds = lp_seconds
    stats.x = x
    return out, stats


def plans_agree(plan_a: tuple, plan_b: tuple, n_workloads: int) -> bool:
    """Semantic plan equality over the real rows: same admitted set,
    same parked set, same chosen flavor option per admitted row.
    ``admit_round``/``rounds`` are NOT compared — the relaxed arm's
    repair runs over a compacted axis, so its round numbering differs
    while the decisions (and the per-round apply order they induce
    within a CQ) do not.
    """
    W = n_workloads
    adm_a = np.asarray(plan_a[0])[:W].astype(bool)
    adm_b = np.asarray(plan_b[0])[:W].astype(bool)
    if not np.array_equal(adm_a, adm_b):
        return False
    if not np.array_equal(np.asarray(plan_a[3])[:W].astype(bool),
                          np.asarray(plan_b[3])[:W].astype(bool)):
        return False
    return bool(np.array_equal(np.asarray(plan_a[1])[:W][adm_a],
                               np.asarray(plan_b[1])[:W][adm_b]))
