"""Synthetic cluster + workload generator.

Config shape mirrors the reference's generator.yaml
(test/performance/scheduler/configs/*/generator.yaml): cohorts ×
queue-sets × workload-sets, each workload class with request size,
priority, and runtime.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import (
    ClusterQueue,
    Cohort,
    FlavorQuotas,
    LocalQueue,
    PodSet,
    PreemptionPolicy,
    PreemptionPolicyValue,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_oss_tpu.core.store import Store


@dataclass
class WorkloadClass:
    class_name: str
    count: int
    request: int            # cpu units per workload
    priority: int
    runtime_ms: int
    creation_interval_ms: int = 0
    #: optional heterogeneous shape: explicit podsets as
    #: [(pod_count, {resource: per-pod quantity}), ...]; overrides
    #: ``request`` when set
    podsets: list | None = None


@dataclass
class GeneratorConfig:
    """Reference parity: baseline/generator.yaml shape."""

    n_cohorts: int = 5
    cqs_per_cohort: int = 6
    nominal_quota: int = 20
    borrowing_limit: int | None = 100
    reclaim_within_cohort: str = PreemptionPolicyValue.ANY
    within_cluster_queue: str = PreemptionPolicyValue.LOWER_PRIORITY
    #: heterogeneous mode: two fungible flavors over cpu+memory plus an
    #: accelerator resource group (see GeneratorConfig.heterogeneous)
    hetero: bool = False
    classes: list[WorkloadClass] = field(default_factory=lambda: [
        WorkloadClass("small", 350, 1, 50, 200, 100),
        WorkloadClass("medium", 100, 5, 100, 500, 500),
        WorkloadClass("large", 50, 20, 200, 1000, 1200),
    ])

    @classmethod
    def baseline(cls) -> "GeneratorConfig":
        """test/performance/scheduler/configs/baseline: 5x6 CQs, 15k wl."""
        return cls()

    @classmethod
    def heterogeneous(cls, n_cohorts: int = 10,
                      cqs_per_cohort: int = 50) -> "GeneratorConfig":
        """Contended multi-flavor / multi-resource-group / multi-podset
        shape: two fungible flavors (on-demand, spot) over cpu+memory in
        one resource group, an accelerator resource group, pod-group
        workloads (driver + workers), and preemption enabled — the
        option-group axis and flavor walk the degenerate large-scale
        shape never exercises.
        """
        return cls(
            n_cohorts=n_cohorts,
            cqs_per_cohort=cqs_per_cohort,
            hetero=True,
            classes=[
                WorkloadClass("small", 25, 1, 50, 150, 60, podsets=[
                    (1, {"cpu": 1, "memory": 100})]),
                WorkloadClass("group", 10, 0, 100, 350, 300, podsets=[
                    (1, {"cpu": 2, "memory": 200}),
                    (3, {"cpu": 2, "memory": 200})]),
                WorkloadClass("accel", 5, 0, 150, 500, 500, podsets=[
                    (1, {"cpu": 2, "memory": 200, "gpu": 2}),
                    (2, {"cpu": 4, "memory": 400})]),
                WorkloadClass("large", 5, 0, 200, 700, 700, podsets=[
                    (1, {"cpu": 10, "memory": 1000})]),
            ],
        )

    @classmethod
    def large_scale(cls, preemption: bool = True) -> "GeneratorConfig":
        """configs/large-scale: 10 cohorts x 100 CQs = 1000 CQs, 50k wl."""
        return cls(
            n_cohorts=10,
            cqs_per_cohort=100,
            reclaim_within_cohort=(PreemptionPolicyValue.ANY if preemption
                                   else PreemptionPolicyValue.NEVER),
            within_cluster_queue=(PreemptionPolicyValue.LOWER_PRIORITY
                                  if preemption
                                  else PreemptionPolicyValue.NEVER),
            classes=[
                WorkloadClass("small", 35, 1, 50, 150, 60),
                WorkloadClass("medium", 11, 5, 100, 350, 300),
                WorkloadClass("large", 4, 20, 200, 700, 700),
            ],
        )


@dataclass
class GeneratedWorkload:
    workload: Workload
    class_name: str
    runtime_ms: int
    arrival_ms: float


def generate(config: GeneratorConfig) -> tuple[Store, list[GeneratedWorkload]]:
    """Build the store (CQs/cohorts/LQs/flavor) and the arrival schedule.

    Workloads are NOT added to the store; the simulator feeds them in at
    their arrival times (or all at once for backlog-drain benchmarks).
    """
    store = Store()
    if config.hetero:
        for fl in ("on-demand", "spot", "accel"):
            store.upsert_resource_flavor(ResourceFlavor(name=fl))
        q = config.nominal_quota
        bl = config.borrowing_limit

        def make_groups():
            return [
                ResourceGroup(
                    covered_resources=["cpu", "memory"],
                    flavors=[
                        FlavorQuotas(name="on-demand", resources=[
                            ResourceQuota(name="cpu", nominal=q,
                                          borrowing_limit=bl),
                            ResourceQuota(name="memory", nominal=q * 100,
                                          borrowing_limit=(
                                              None if bl is None
                                              else bl * 100)),
                        ]),
                        FlavorQuotas(name="spot", resources=[
                            ResourceQuota(name="cpu", nominal=2 * q,
                                          borrowing_limit=bl),
                            ResourceQuota(name="memory",
                                          nominal=2 * q * 100,
                                          borrowing_limit=(
                                              None if bl is None
                                              else bl * 100)),
                        ]),
                    ],
                ),
                ResourceGroup(
                    covered_resources=["gpu"],
                    flavors=[FlavorQuotas(name="accel", resources=[
                        ResourceQuota(name="gpu", nominal=4,
                                      borrowing_limit=8)])],
                ),
            ]
    else:
        store.upsert_resource_flavor(ResourceFlavor(name="default"))
        make_groups = None
    schedule: list[GeneratedWorkload] = []
    for ci in range(config.n_cohorts):
        store.upsert_cohort(Cohort(name=f"cohort-{ci}"))
        for qi in range(config.cqs_per_cohort):
            cq_name = f"cq-{ci}-{qi}"
            store.upsert_cluster_queue(ClusterQueue(
                name=cq_name,
                cohort=f"cohort-{ci}",
                preemption=PreemptionPolicy(
                    reclaim_within_cohort=config.reclaim_within_cohort,
                    within_cluster_queue=config.within_cluster_queue,
                ),
                resource_groups=(make_groups() if make_groups
                                 else [ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="default", resources=[
                        ResourceQuota(
                            name="cpu",
                            nominal=config.nominal_quota,
                            borrowing_limit=config.borrowing_limit)])],
                )]),
            ))
            store.upsert_local_queue(
                LocalQueue(name=f"lq-{cq_name}", cluster_queue=cq_name))
            for wc in config.classes:
                for i in range(wc.count):
                    arrival = i * wc.creation_interval_ms
                    if wc.podsets is not None:
                        podsets = [
                            PodSet(name=f"ps{j}", count=cnt,
                                   requests=dict(reqs))
                            for j, (cnt, reqs) in enumerate(wc.podsets)]
                    else:
                        podsets = [PodSet(count=1,
                                          requests={"cpu": wc.request})]
                    wl = Workload(
                        name=f"{wc.class_name}-{cq_name}-{i}",
                        queue_name=f"lq-{cq_name}",
                        priority=wc.priority,
                        creation_time=arrival / 1000.0,
                        podsets=podsets,
                    )
                    schedule.append(GeneratedWorkload(
                        workload=wl, class_name=wc.class_name,
                        runtime_ms=wc.runtime_ms, arrival_ms=arrival))
    schedule.sort(key=lambda g: g.arrival_ms)
    return store, schedule
