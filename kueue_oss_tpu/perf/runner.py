"""Simulator: arrival → admission → fake execution → finish.

Reference parity: test/performance/scheduler/runner — drives the scheduler
against generated workloads with a simulated clock, marks admitted
workloads Finished after their runtime, and collects the rangespec
metrics (total wall time, per-class avg time-to-admission, min CQ usage,
admission throughput).
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.perf.generator import GeneratedWorkload
from kueue_oss_tpu.scheduler.scheduler import Scheduler


@dataclass
class SimStats:
    total_workloads: int = 0
    admitted: int = 0
    finished: int = 0
    sim_wall_ms: float = 0.0       # simulated makespan
    real_seconds: float = 0.0      # host wall-clock spent scheduling
    cycles: int = 0
    tta_ms_by_class: dict[str, float] = field(default_factory=dict)
    admissions_per_real_second: float = 0.0
    preemptions: int = 0

    def summary(self) -> str:
        ttas = ", ".join(f"{k}={v:.0f}ms"
                         for k, v in sorted(self.tta_ms_by_class.items()))
        return (f"workloads={self.total_workloads} admitted={self.admitted} "
                f"finished={self.finished} cycles={self.cycles} "
                f"sim_makespan={self.sim_wall_ms / 1000:.1f}s "
                f"real={self.real_seconds:.2f}s "
                f"throughput={self.admissions_per_real_second:.0f}/s "
                f"avg_tta[{ttas}]")


class Simulator:
    """Event-driven simulation around the oracle scheduler.

    The simulated clock jumps between events (arrivals, finishes); each
    event batch is followed by scheduler cycles until quiescence. This is
    the e2e slice: workloads flow queue → snapshot → assign → admit →
    finish, releasing quota that wakes parked workloads.
    """

    def __init__(self, store: Store, schedule: list[GeneratedWorkload],
                 enable_fair_sharing: bool = False, solver=None,
                 timed_hooks=None) -> None:
        self.store = store
        self.schedule = schedule
        #: [(at_ms, fn(simulator, now_ms))] — virtual-time injection
        #: points (the sim/ what-if engine schedules chaos node flaps
        #: here); hooks run inside the event loop at their timestamp,
        #: before the scheduler runs to quiescence at that instant
        self.timed_hooks = list(timed_hooks or [])
        self.queues = QueueManager(store)
        self.scheduler = Scheduler(store, self.queues,
                                   enable_fair_sharing=enable_fair_sharing,
                                   solver=solver)
        if solver is not None:
            # one compiled program for every drain of the run: pad the
            # workload axis to the schedule's peak instead of
            # recompiling at each power-of-two crossing as the backlog
            # grows
            engine = self.scheduler._solver_engine()
            if engine is not None:
                engine.pad_to = len(schedule)
        self.by_key = {g.workload.key: g for g in schedule}
        #: workload keys touched since the last admission/eviction sweep —
        #: keeps the sweep O(changed) instead of O(all workloads)
        self._dirty: set[str] = set()
        store.watch(self._on_event)

    def _on_event(self, event) -> None:
        verb, kind, obj = event
        if kind == "Workload":
            self._dirty.add(obj.key)

    def run(self, max_events: int = 10_000_000) -> SimStats:
        stats = SimStats(total_workloads=len(self.schedule))
        t_real0 = time.monotonic()
        now_ms = 0.0
        #: (time_ms, seq, kind, payload)
        events: list = []
        seq = 0
        for g in self.schedule:
            events.append((g.arrival_ms, seq, "arrive", g))
            seq += 1
        for at_ms, fn in self.timed_hooks:
            events.append((float(at_ms), seq, "hook", fn))
            seq += 1
        heapq.heapify(events)
        admitted_at: dict[str, float] = {}
        tta_sum: dict[str, float] = {}
        tta_n: dict[str, int] = {}

        processed = 0
        pending_wake: set[float] = set()
        while events and processed < max_events:
            now_ms, _, kind, payload = heapq.heappop(events)
            processed += 1
            batch = [(kind, payload)]
            # absorb events at the same timestamp
            while events and events[0][0] <= now_ms:
                _, _, k2, p2 = heapq.heappop(events)
                batch.append((k2, p2))
                processed += 1
            for k, g in batch:
                if k == "arrive":
                    self.store.add_workload(g.workload)
                elif k == "hook":
                    g(self, now_ms)
                elif k == "finish":
                    g, admit_ts = g
                    # stale if the workload was preempted since admission
                    if admitted_at.get(g.workload.key) != admit_ts:
                        continue
                    self.scheduler.finish_workload(g.workload.key,
                                                   now=now_ms / 1000.0)
                    stats.finished += 1
                # "wake": no payload action; requeue_due below handles it

            # eviction backoffs that expired become schedulable now
            self.scheduler.requeue_due(now_ms / 1000.0)

            # run scheduler to quiescence at this instant
            cycles = self.scheduler.run_until_quiet(now=now_ms / 1000.0)
            stats.cycles += cycles

            # record admissions/evictions, schedule finish + wake events
            dirty, self._dirty = self._dirty, set()
            for key in dirty:
                wl = self.store.workloads.get(key)
                if wl is None:
                    continue
                if wl.is_quota_reserved and key not in admitted_at:
                    admitted_at[key] = now_ms
                    g = self.by_key[key]
                    tta = now_ms - g.arrival_ms
                    tta_sum[g.class_name] = tta_sum.get(g.class_name, 0) + tta
                    tta_n[g.class_name] = tta_n.get(g.class_name, 0) + 1
                    stats.admitted += 1
                    heapq.heappush(
                        events,
                        (now_ms + g.runtime_ms, seq, "finish", (g, now_ms)))
                    seq += 1
                elif not wl.is_quota_reserved and key in admitted_at:
                    # evicted/preempted: track re-admission afresh
                    del admitted_at[key]
                    stats.admitted -= 1
                    stats.preemptions += 1
            next_requeue = self.scheduler.next_requeue_at()
            if next_requeue is not None:
                wake_ms = next_requeue * 1000.0
                if wake_ms not in pending_wake:
                    pending_wake.add(wake_ms)
                    heapq.heappush(events, (wake_ms, seq, "wake", None))
                    seq += 1

        stats.sim_wall_ms = now_ms
        stats.real_seconds = time.monotonic() - t_real0
        stats.tta_ms_by_class = {
            k: tta_sum[k] / tta_n[k] for k in tta_sum}
        if stats.real_seconds > 0:
            stats.admissions_per_real_second = (
                stats.admitted / stats.real_seconds)
        return stats


def drain_benchmark(store: Store, schedule: list[GeneratedWorkload],
                    ) -> dict:
    """Backlog-drain benchmark through the TPU solver: all workloads
    pending at t0, one solver invocation computes the full plan.

    Returns a dict with solver timing and throughput. The store must not
    have preemption-enabled CQs (use GeneratorConfig(..., preemption
    disabled) shapes).
    """
    for g in schedule:
        store.add_workload(g.workload)
    queues = QueueManager(store)
    from kueue_oss_tpu.solver.engine import SolverEngine
    from kueue_oss_tpu.solver.kernels import solve_backlog, to_device

    import jax

    engine = SolverEngine(store, queues)
    problem, _ = engine.export()
    tensors = to_device(problem)
    jax.block_until_ready(tensors)
    # AOT-compile without executing, then time the FIRST real execution.
    # (Remote-tunneled platforms can serve repeat executions on identical
    # inputs from a result cache, so only the first run is trustworthy.)
    compiled = solve_backlog.lower(tensors).compile()
    t0 = time.monotonic()
    out = compiled(tensors)
    jax.block_until_ready(out)
    solve_s = time.monotonic() - t0
    admitted, opt, admit_round, parked, rounds, usage = out
    n_admitted = int(admitted.sum())
    return {
        "workloads": problem.n_workloads,
        "cluster_queues": problem.n_cqs,
        "admitted": n_admitted,
        "rounds": int(rounds),
        "solve_seconds": solve_s,
        "admissions_per_second": n_admitted / solve_s if solve_s else 0.0,
        "cycle_ms": solve_s * 1000.0 / max(int(rounds), 1),
    }
