"""Performance harness: synthetic cluster/workload generator + simulator.

Reference parity: test/performance/scheduler/{minimalkueue,runner} — a
generator builds cohorts/CQs/workload classes from a config, a runner
fakes workload execution (finish after runtime_ms) and collects admission
stats (wall time, per-class time-to-admission, throughput, CQ usage).
"""
