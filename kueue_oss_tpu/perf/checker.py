"""Performance-run checker.

Reference parity: test/performance/scheduler/checker + the rangespec.yaml
threshold files — asserts a perf run's stats against recorded thresholds
(max wall time, per-class time-to-admission ceilings, minimum throughput)
and reports violations instead of pass/fail booleans so CI logs show every
breach at once.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.perf.runner import SimStats


@dataclass
class RangeSpec:
    """Threshold file analog (configs/*/rangespec.yaml)."""

    #: total simulated wall-clock ceiling (cmd.maxWallMs)
    max_wall_ms: Optional[float] = None
    #: class name -> average time-to-admission ceiling (ms)
    max_tta_ms_by_class: dict[str, float] = field(default_factory=dict)
    #: every generated workload must finish admitted
    require_all_admitted: bool = True
    #: minimum real-time admission throughput (admissions/s)
    min_admissions_per_second: Optional[float] = None


def check(stats: SimStats, spec: RangeSpec) -> list[str]:
    """Returns the list of threshold violations (empty = pass)."""
    violations: list[str] = []
    if spec.max_wall_ms is not None and stats.sim_wall_ms > spec.max_wall_ms:
        violations.append(
            f"wall time {stats.sim_wall_ms:.0f}ms exceeds "
            f"{spec.max_wall_ms:.0f}ms")
    for cls, ceiling in spec.max_tta_ms_by_class.items():
        tta = stats.tta_ms_by_class.get(cls)
        if tta is None:
            violations.append(f"class {cls!r}: no TTA recorded")
        elif tta > ceiling:
            violations.append(
                f"class {cls!r}: avg TTA {tta:.0f}ms exceeds {ceiling:.0f}ms")
    if spec.require_all_admitted and stats.admitted < stats.total_workloads:
        violations.append(
            f"only {stats.admitted}/{stats.total_workloads} admitted")
    if (spec.min_admissions_per_second is not None
            and stats.admissions_per_real_second
            < spec.min_admissions_per_second):
        violations.append(
            f"throughput {stats.admissions_per_real_second:.1f}/s below "
            f"{spec.min_admissions_per_second:.1f}/s")
    return violations


#: thresholds derived from the reference's baseline rangespec
#: (test/performance/scheduler/configs/baseline/rangespec.yaml, scaled to
#: the generator's default 5x6x500 = 15k-workload shape — wall 425s,
#: TTA ceilings 11s/90s/260s for large/medium/small)
BASELINE_SPEC = RangeSpec(
    max_wall_ms=425_000,
    max_tta_ms_by_class={"large": 11_000, "medium": 90_000,
                         "small": 260_000},
    require_all_admitted=True,
    # the reference implies ~43 adm/s; we require at least parity in
    # real time on the simulator
    min_admissions_per_second=43.0,
)
