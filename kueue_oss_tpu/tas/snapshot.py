"""TAS flavor snapshot: topology tree + two-phase placement.

Reference parity: pkg/cache/scheduler/tas_flavor_snapshot.go (KEP-2724).
The snapshot holds the topology domain tree for one TAS ResourceFlavor:
leaves carry free capacity (node allocatable minus non-TAS usage) and
TAS usage; placement runs in two phases:

  1. fill counts — per-leaf pod/slice/leader capacity from the podset's
     per-pod requests (after taint/selector/affinity filtering), rolled
     up the tree (tas_flavor_snapshot.go:1568-1719);
  2. placement — find the best level/domain set at or above the
     requested level (findLevelWithFitDomains, :1236-1321), then walk
     down level-by-level minimizing the number of domains used
     (updateCountsToMinimumGeneric, :1405-1469), finally emitting the
     lowest-level assignment (buildAssignment, :1490-1501).

Supported: required/preferred/unconstrained levels, slice grouping
(podset_slice_required_topology + size) including MULTI-LAYER nested
slice constraints (gate TASMultiLayerTopology; buildSliceSizeAtLevel,
tas_flavor_snapshot.go:1001-1060 + the per-level slice sizing in the
descent :938-971), BALANCED placement (gate TASBalancedPlacement;
tas_balanced_placement.go — greedy evaluation, balance threshold,
DP optimal-domain-set selection, threshold pruning, even distribution
with leader-first extras), leader/worker podset groups, BestFit and
LeastFreeCapacity profiles, unhealthy-node replacement
(findReplacementAssignment, :614-656).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from kueue_oss_tpu.api.types import (
    HOSTNAME_LABEL,
    Node,
    PodSet,
    Toleration,
    TopologyAssignment,
    TopologyDomainAssignment,
    Workload,
)

Requests = dict[str, int]


def count_in(requests: Requests, capacity: Requests) -> int:
    """How many pods with `requests` fit into `capacity`."""
    fit = 1 << 30
    for r, q in requests.items():
        if q <= 0:
            continue
        fit = min(fit, capacity.get(r, 0) // q)
    return max(fit, 0)


def _limiting_resource(requests: Requests, capacity: Requests) -> str:
    for r, q in requests.items():
        if q > 0 and capacity.get(r, 0) // q <= 0:
            return r
    return ""


def _add(dst: Requests, src: Requests, scale: int = 1) -> None:
    for r, q in src.items():
        dst[r] = dst.get(r, 0) + q * scale


def _sub(dst: Requests, src: Requests) -> None:
    for r, q in src.items():
        dst[r] = dst.get(r, 0) - q


class Domain:
    """One topology domain (tas_flavor_snapshot.go:51-89).

    `state`/`slice_state`/`leader_state` (+ with-leader variants) are
    scratch fields of the placement algorithm: in phase 1 they hold how
    many pods/slices/leaders *can* fit; in phase 2 they are overwritten
    with how many *are* assigned.
    """

    __slots__ = ("id", "level_values", "parent", "children", "state",
                 "slice_state", "state_with_leader",
                 "slice_state_with_leader", "leader_state")

    def __init__(self, domain_id: tuple[str, ...],
                 level_values: tuple[str, ...]) -> None:
        self.id = domain_id
        self.level_values = level_values
        self.parent: Optional[Domain] = None
        self.children: list[Domain] = []
        self.state = 0
        self.slice_state = 0
        self.state_with_leader = 0
        self.slice_state_with_leader = 0
        self.leader_state = 0


class LeafDomain(Domain):
    __slots__ = ("free_capacity", "tas_usage", "node", "_remaining")

    def __init__(self, domain_id, level_values) -> None:
        super().__init__(domain_id, level_values)
        self.free_capacity: Requests = {}
        self.tas_usage: Requests = {}
        self.node: Optional[Node] = None
        #: per-call scratch for the device fill path (remaining capacity
        #: after host-side filtering; None between calls)
        self._remaining: Optional[Requests] = None


@dataclass
class TASPodSetRequest:
    """Placement request for one podset on one TAS flavor
    (reference: TASPodSetRequests, tas_flavor_snapshot.go:356-367)."""

    podset: PodSet
    single_pod_requests: Requests
    count: int
    flavor: str
    implied: bool = False
    podset_group_name: Optional[str] = None


@dataclass
class TASAssignmentResult:
    assignment: Optional[TopologyAssignment] = None
    failure: str = ""


class TASFlavorSnapshot:
    """Topology tree for one TAS ResourceFlavor."""

    def __init__(self, topology_name: str, levels: list[str],
                 tolerations: Optional[list[Toleration]] = None,
                 profile_mixed: bool = False) -> None:
        self.topology_name = topology_name
        self.levels = list(levels)
        self.tolerations = list(tolerations or [])
        #: LeastFreeCapacity for unconstrained podsets (TASProfileMixed gate)
        self.profile_mixed = profile_mixed
        self.leaves: dict[tuple[str, ...], LeafDomain] = {}
        self.domains: dict[tuple[str, ...], Domain] = {}
        self.roots: dict[tuple[str, ...], Domain] = {}
        self.domains_per_level: list[dict[tuple[str, ...], Domain]] = [
            {} for _ in levels]
        self.is_lowest_level_node = (
            bool(levels) and levels[-1] == HOSTNAME_LABEL)
        #: round-5 hybrid: run phase 1 (fill-in counts — the per-leaf
        #: capacity division and the per-level roll-up) on the
        #: accelerator via solver/tas_kernels.fill_counts_ext, keeping
        #: host-side leaf filtering and EVERY phase-2 tie-break
        #: (balanced DP included) — see the TASDeviceFillCounts gate
        self.use_device_fill = False
        self._device_tree = None  # (parents, lex-ordered domain lists)

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    def add_node(self, node: Node) -> Optional[tuple[str, ...]]:
        """Register a ready node's capacity under its leaf domain."""
        values = tuple(node.labels.get(k, "") for k in self.levels)
        if any(v == "" for v in values):
            return None  # node not part of this topology
        leaf = self.leaves.get(values)
        if leaf is None:
            leaf = LeafDomain(values, values)
            self.leaves[values] = leaf
        if self.is_lowest_level_node:
            leaf.node = node
        _add(leaf.free_capacity, node.allocatable)
        return values

    def initialize(self) -> None:
        """Connect leaves to parent domains up to the roots."""
        for leaf in self.leaves.values():
            self.domains[leaf.id] = leaf
            self.domains_per_level[len(leaf.level_values) - 1][leaf.id] = leaf
            self._link_ancestors(leaf)

    def _link_ancestors(self, dom: Domain) -> None:
        if len(dom.level_values) == 1:
            self.roots[dom.id] = dom
            return
        parent_values = dom.level_values[:-1]
        parent = self.domains.get(parent_values)
        if parent is None:
            parent = Domain(parent_values, parent_values)
            self.domains[parent_values] = parent
            self.domains_per_level[len(parent_values) - 1][parent_values] = parent
            self._link_ancestors(parent)
        dom.parent = parent
        parent.children.append(dom)

    def add_non_tas_usage(self, domain_id: tuple[str, ...],
                          usage: Requests) -> None:
        leaf = self.leaves.get(domain_id)
        if leaf is not None:
            _sub(leaf.free_capacity, usage)

    def add_tas_usage(self, domain_values: Iterable[str],
                      single_pod_requests: Requests, count: int) -> None:
        leaf = self._leaf_for_values(tuple(domain_values))
        if leaf is None:
            return  # backing node deleted / not ready
        _add(leaf.tas_usage, single_pod_requests, scale=count)
        leaf.tas_usage["pods"] = leaf.tas_usage.get("pods", 0) + count

    def remove_tas_usage(self, domain_values: Iterable[str],
                         single_pod_requests: Requests, count: int) -> None:
        leaf = self._leaf_for_values(tuple(domain_values))
        if leaf is None:
            return
        _add(leaf.tas_usage, single_pod_requests, scale=-count)
        leaf.tas_usage["pods"] = leaf.tas_usage.get("pods", 0) - count

    def _leaf_for_values(self, values: tuple[str, ...]) -> Optional[LeafDomain]:
        """Resolve assignment values (hostname-only or full path) to a leaf."""
        leaf = self.leaves.get(values)
        if leaf is not None:
            return leaf
        if len(values) == 1 and self.is_lowest_level_node:
            for candidate in self.leaves.values():
                if candidate.level_values[-1] == values[0]:
                    return candidate
        return None

    def has_node(self, hostname: str) -> bool:
        return any(leaf.level_values[-1] == hostname
                   for leaf in self.leaves.values())

    # ------------------------------------------------------------------
    # Level helpers
    # ------------------------------------------------------------------

    def level_index(self, key: str) -> Optional[int]:
        try:
            return self.levels.index(key)
        except ValueError:
            return None

    def has_level(self, podset: PodSet) -> bool:
        tr = podset.topology_request
        key = self._level_key(podset)
        if key is None:
            return False
        if self.level_index(key) is None:
            return False
        if tr is not None and tr.podset_slice_required_topology is not None:
            if self.level_index(tr.podset_slice_required_topology) is None:
                return False
        return True

    def _level_key(self, podset: PodSet,
                   implied: bool = False) -> Optional[str]:
        tr = podset.topology_request
        if tr is not None:
            if tr.required is not None:
                return tr.required
            if tr.preferred is not None:
                return tr.preferred
            if tr.podset_slice_required_topology is not None and not (
                    tr.required or tr.preferred):
                return self.levels[0]
            if tr.unconstrained:
                return self.levels[-1]
        if implied:
            return self.levels[-1]
        return None

    # ------------------------------------------------------------------
    # Fit re-check (clusterqueue_snapshot Fits analog)
    # ------------------------------------------------------------------

    def fits(self, domain_values: Iterable[str],
             single_pod_requests: Requests, count: int) -> bool:
        remaining = self.remaining_capacity(domain_values)
        if remaining is None:
            return False
        req = dict(single_pod_requests)
        req["pods"] = req.get("pods", 0) + 1
        return count_in(req, remaining) >= count

    def remaining_capacity(self, domain_values: Iterable[str]) -> Optional[Requests]:
        """Free capacity minus assumed TAS usage for one leaf domain; None
        if the domain is unknown (e.g. the node left the snapshot)."""
        leaf = self._leaf_for_values(tuple(domain_values))
        if leaf is None:
            return None
        remaining = dict(leaf.free_capacity)
        _sub(remaining, leaf.tas_usage)
        return remaining

    # ------------------------------------------------------------------
    # Main entry: grouped placement over podsets
    # ------------------------------------------------------------------

    def find_topology_assignments(
        self,
        requests: list[TASPodSetRequest],
        simulate_empty: bool = False,
        workload: Optional[Workload] = None,
    ) -> dict[str, TASAssignmentResult]:
        """Place all podset requests, respecting group co-location and
        accumulating assumed usage between groups
        (FindTopologyAssignmentsForFlavor, tas_flavor_snapshot.go:519-594).
        """
        result: dict[str, TASAssignmentResult] = {}
        assumed: dict[tuple[str, ...], Requests] = {}

        groups: dict[str, list[TASPodSetRequest]] = {}
        order: list[str] = []
        for idx, tr in enumerate(requests):
            key = tr.podset_group_name or f"__solo_{idx}"
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(tr)

        unhealthy = list(workload.status.unhealthy_nodes) if workload else []
        # Replacement only applies to a workload that still holds a topology
        # assignment; a requeued workload with a stale unhealthy list is
        # placed from scratch. More than one failed node is beyond repair —
        # fail so the caller evicts (reference: single-node replacement,
        # tas_flavor_snapshot.go:614).
        from kueue_oss_tpu import features

        has_prior = (
            workload is not None and workload.status.admission is not None
            and any(psa.topology_assignment is not None
                    for psa in workload.status.admission.podset_assignments))
        if (unhealthy and has_prior
                and not features.enabled("TASFailedNodeReplacement")):
            reason = (f"node(s) {sorted(unhealthy)} in the topology "
                      "assignment are unhealthy (replacement disabled)")
            for key in order:
                for tr in groups[key]:
                    result[tr.podset.name] = TASAssignmentResult(failure=reason)
            return result
        if unhealthy and has_prior and len(unhealthy) > 1:
            reason = (f"nodes {sorted(unhealthy)} in the topology assignment "
                      "are unhealthy; only a single node can be replaced")
            for key in order:
                for tr in groups[key]:
                    result[tr.podset.name] = TASAssignmentResult(failure=reason)
            return result
        for key in order:
            trs = groups[key]
            if unhealthy and has_prior:
                for tr in trs:
                    res = self._replace_unhealthy(tr, workload, unhealthy[0],
                                                  assumed)
                    result[tr.podset.name] = res
                    if res.failure:
                        return result
                continue

            if len(trs) > 2:
                reason = (f"podset group {key!r} has {len(trs)} podsets; "
                          "at most 2 (leader + workers) are supported")
                for tr in trs:
                    result[tr.podset.name] = TASAssignmentResult(
                        failure=reason)
                return result
            leader, workers = self._split_leader(trs)
            if leader is not None and leader.count != 1:
                reason = (f"leader podset {leader.podset.name!r} must have "
                          f"count 1, got {leader.count}")
                for tr in trs:
                    result[tr.podset.name] = TASAssignmentResult(
                        failure=reason)
                return result
            assignments, reason = self._place(workers, leader, assumed,
                                              simulate_empty)
            for tr in trs:
                result[tr.podset.name] = TASAssignmentResult(
                    assignment=assignments.get(tr.podset.name),
                    failure=reason)
            if reason:
                return result
            for tr in trs:
                self._assume(assumed, assignments.get(tr.podset.name), tr)
        return result

    @staticmethod
    def _split_leader(trs: list[TASPodSetRequest]):
        """Two grouped podsets = (leader, workers), leader has the lower
        count (findLeaderAndWorkers, tas_flavor_snapshot.go:596-609)."""
        workers = trs[0]
        leader = None
        if len(trs) > 1:
            leader = trs[1]
            if leader.count > workers.count:
                leader, workers = workers, leader
        return leader, workers

    def _assume(self, assumed, ta: Optional[TopologyAssignment],
                tr: TASPodSetRequest) -> None:
        if ta is None:
            return
        for dom in ta.domains:
            leaf = self._leaf_for_values(tuple(dom.values))
            if leaf is None:
                continue
            bucket = assumed.setdefault(leaf.id, {})
            _add(bucket, tr.single_pod_requests, scale=dom.count)
            bucket["pods"] = bucket.get("pods", 0) + dom.count

    # ------------------------------------------------------------------
    # Unhealthy-node replacement
    # ------------------------------------------------------------------

    def _replace_unhealthy(self, tr: TASPodSetRequest,
                           workload: Workload, unhealthy_node: str,
                           assumed) -> TASAssignmentResult:
        """Re-place only the pods that sat on the unhealthy node, keeping
        the rest of the assignment (findReplacementAssignment,
        tas_flavor_snapshot.go:614-656)."""
        psa = None
        if workload.status.admission is not None:
            for cand in workload.status.admission.podset_assignments:
                if cand.name == tr.podset.name:
                    psa = cand
        if psa is None or psa.topology_assignment is None:
            # Inconsistent state: the workload holds a prior assignment for
            # some podsets but not this one — fail so the caller evicts
            # rather than silently admitting without a placement.
            return TASAssignmentResult(failure=(
                f"podset {tr.podset.name!r} has no prior topology assignment "
                "to repair"))
        existing = TopologyAssignment(
            levels=list(psa.topology_assignment.levels),
            domains=[TopologyDomainAssignment(list(d.values), d.count)
                     for d in psa.topology_assignment.domains
                     if d.values[-1] != unhealthy_node],
        )
        missing = sum(
            d.count for d in psa.topology_assignment.domains
            if d.values[-1] == unhealthy_node)
        for dom in existing.domains:
            if self._leaf_for_values(tuple(dom.values)) is None:
                return TASAssignmentResult(failure=(
                    f"existing topology assignment contains stale domain "
                    f"{dom.values}"))
        if missing == 0:
            return TASAssignmentResult(assignment=existing)

        required_domain = self._required_replacement_domain(
            tr, existing, missing)
        sub = TASPodSetRequest(
            podset=tr.podset, single_pod_requests=tr.single_pod_requests,
            count=missing, flavor=tr.flavor, implied=tr.implied)
        assignments, reason = self._place(
            sub, None, assumed, False,
            required_replacement_domain=required_domain,
            excluded_node=unhealthy_node)
        if reason:
            return TASAssignmentResult(failure=reason)
        replacement = assignments.get(tr.podset.name)
        if replacement is None or not replacement.domains:
            return TASAssignmentResult(failure=(
                f"cannot find replacement assignment for unhealthy node "
                f"{unhealthy_node}"))
        merged = self._merge_assignments(existing, replacement)
        self._assume(assumed, replacement, sub)
        return TASAssignmentResult(assignment=merged)

    def _required_replacement_domain(
        self, tr: TASPodSetRequest, existing: TopologyAssignment,
        missing: int
    ) -> Optional[tuple[str, ...]]:
        """Confine the replacement to the domain whose required-level or
        slice grouping the failure broke (requiredReplacementDomain,
        tas_flavor_snapshot.go:680-731)."""
        key = self._level_key(tr.podset, tr.implied)
        if key is None:
            return None
        level_idx = self.level_index(key)
        if level_idx is None or not existing.domains:
            return None
        tr_req = tr.podset.topology_request

        slice_size = 1
        if tr_req is not None and tr_req.podset_slice_required_topology:
            slice_size = tr_req.podset_slice_size or 1
        if slice_size > 1 and missing % slice_size != 0:
            slice_level = self.level_index(
                tr_req.podset_slice_required_topology)
            if slice_level is None:
                return None
            per_domain: dict[tuple[str, ...], int] = {}
            for dom in existing.domains:
                leaf = self._leaf_for_values(tuple(dom.values))
                if leaf is None:
                    continue
                anc = leaf.level_values[:slice_level + 1]
                per_domain[anc] = per_domain.get(anc, 0) + dom.count
            for domain_id, cnt in per_domain.items():
                if (cnt + missing) % slice_size == 0:
                    return domain_id
            return None

        if tr_req is None or tr_req.required is None:
            return None
        leaf = self._leaf_for_values(tuple(existing.domains[0].values))
        if leaf is None:
            return None
        return leaf.level_values[:level_idx + 1]

    def _merge_assignments(self, a: TopologyAssignment,
                           b: TopologyAssignment) -> TopologyAssignment:
        by_values: dict[tuple[str, ...], int] = {}
        for dom in list(a.domains) + list(b.domains):
            key = tuple(dom.values)
            by_values[key] = by_values.get(key, 0) + dom.count

        def sort_key(values: tuple[str, ...]):
            leaf = self._leaf_for_values(values)
            return leaf.level_values if leaf is not None else values

        return TopologyAssignment(
            levels=list(a.levels),
            domains=[
                TopologyDomainAssignment(list(v), by_values[v])
                for v in sorted(by_values, key=sort_key)
            ],
        )

    # ------------------------------------------------------------------
    # Phase 1: capacity counting
    # ------------------------------------------------------------------

    def _fill_in_counts(
        self,
        tr: TASPodSetRequest,
        leader: Optional[TASPodSetRequest],
        assumed,
        simulate_empty: bool,
        slice_size: int,
        slice_level_idx: int,
        required_replacement_domain: Optional[tuple[str, ...]],
        excluded_node: Optional[str] = None,
    ) -> dict:
        """Compute per-leaf pod/leader capacity and roll it up
        (fillInCounts, tas_flavor_snapshot.go:1568-1646)."""
        for dom in self.domains.values():
            dom.state = dom.state_with_leader = 0
            dom.slice_state = dom.slice_state_with_leader = 0
            dom.leader_state = 0

        req = dict(tr.single_pod_requests)
        req["pods"] = req.get("pods", 0) + 1
        leader_req = None
        if leader is not None:
            leader_req = dict(leader.single_pod_requests)
            leader_req["pods"] = leader_req.get("pods", 0) + 1

        tolerations = list(tr.podset.tolerations) + self.tolerations
        stats = {"taints": 0, "selector": 0, "domain": 0, "resources": {},
                 "total": 0}
        for leaf in self.leaves.values():
            stats["total"] += 1
            if excluded_node is not None and (
                    leaf.level_values[-1] == excluded_node):
                stats["domain"] += 1
                continue
            if self.is_lowest_level_node and leaf.node is not None:
                taint = self._untolerated(leaf.node, tolerations)
                if taint is not None:
                    stats["taints"] += 1
                    continue
                if not all(leaf.node.labels.get(k) == v
                           for k, v in tr.podset.node_selector.items()):
                    stats["selector"] += 1
                    continue
            if required_replacement_domain is not None and (
                    leaf.level_values[:len(required_replacement_domain)]
                    != required_replacement_domain):
                stats["domain"] += 1
                continue
            remaining = dict(leaf.free_capacity)
            if not simulate_empty:
                _sub(remaining, leaf.tas_usage)
            if leaf.id in assumed:
                _sub(remaining, assumed[leaf.id])
            if self.use_device_fill:
                leaf._remaining = remaining  # device path consumes below
                continue
            leaf.state = count_in(req, remaining)
            if leaf.state == 0:
                limiting = _limiting_resource(req, remaining)
                if limiting:
                    stats["resources"][limiting] = (
                        stats["resources"].get(limiting, 0) + 1)
            leaf.leader_state = 0
            if leader_req is not None and count_in(leader_req, remaining) > 0:
                leaf.leader_state = 1
                _sub(remaining, leader_req)
            leaf.state_with_leader = count_in(req, remaining)
        leader_required = leader is not None
        if self.use_device_fill:
            self._device_fill(req, leader_req, slice_size, slice_level_idx,
                              stats)
            return stats
        for root in self.roots.values():
            self._roll_up(root, slice_size, slice_level_idx, 0,
                          leader_required)
        return stats

    def _device_fill(self, req: Requests, leader_req: Optional[Requests],
                     slice_size: int, slice_level_idx: int,
                     stats: dict) -> None:
        """Phase 1 on the accelerator: one fill_counts_ext invocation
        computes every domain's (state, state_with_leader, leader_state,
        slice_state, slice_state_with_leader) — the division and
        per-level segment-sum roll-up the host otherwise does
        recursively (_roll_up). Leaves the host loop's filter decisions
        intact: a filtered leaf never set ``_remaining`` and exports
        zero capacity."""
        import jax.numpy as jnp
        import numpy as np

        from kueue_oss_tpu.solver.tas_kernels import fill_counts_ext

        parents, per_level = self._device_tree_arrays()
        leaves = per_level[-1]
        vocab = sorted({r for r in req}
                       | ({r for r in leader_req} if leader_req else set())
                       | {r for leaf in leaves
                          for r in (leaf._remaining or {})})
        R = max(1, len(vocab))
        ridx = {r: j for j, r in enumerate(vocab)}
        cap = np.zeros((len(leaves), R), dtype=np.int64)
        unfiltered = np.zeros((len(leaves),), dtype=bool)
        for i, leaf in enumerate(leaves):
            remaining = leaf._remaining
            if remaining is None:
                continue  # filtered out: zero capacity
            unfiltered[i] = True
            for r, q in remaining.items():
                cap[i, ridx[r]] = max(0, q)
            leaf._remaining = None
        per_pod = np.zeros((R,), dtype=np.int32)
        for r, q in req.items():
            per_pod[ridx[r]] = q
        leader_pp = np.zeros((R,), dtype=np.int32)
        if leader_req is not None:
            for r, q in leader_req.items():
                leader_pp[ridx[r]] = q
        out = fill_counts_ext(
            [jnp.asarray(p) for p in parents],
            jnp.asarray(np.minimum(cap, 1 << 30).astype(np.int32)),
            jnp.asarray(per_pod), jnp.asarray(leader_pp),
            jnp.asarray(leader_req is not None),
            jnp.asarray(np.int32(slice_size)),
            jnp.asarray(np.int32(slice_level_idx)))
        for l, doms in enumerate(per_level):
            st = np.asarray(out[l]["st"])
            swl = np.asarray(out[l]["swl"])
            ls = np.asarray(out[l]["ls"])
            ss = np.asarray(out[l]["ss"])
            sswl = np.asarray(out[l]["sswl"])
            for i, dom in enumerate(doms):
                dom.state = int(st[i])
                dom.state_with_leader = int(swl[i])
                dom.leader_state = int(ls[i])
                dom.slice_state = int(ss[i])
                dom.slice_state_with_leader = int(sswl[i])
        # limiting-resource stats for zero-capacity leaves (host parity:
        # taint/selector/domain-filtered leaves were already counted
        # under their own stats keys by the host filter loop and must
        # not double-count as resource-limited)
        for i, leaf in enumerate(leaves):
            if unfiltered[i] and leaf.state == 0:
                remaining = {r: int(cap[i, j])
                             for r, j in ridx.items()}
                limiting = _limiting_resource(req, remaining)
                if limiting:
                    stats["resources"][limiting] = (
                        stats["resources"].get(limiting, 0) + 1)

    def _device_tree_arrays(self):
        """Lex-ordered per-level domain lists + parent index arrays
        (build_levels' layout, cached per snapshot)."""
        if self._device_tree is None:
            import numpy as np

            per_level = [sorted(self.domains_per_level[l].values(),
                                key=lambda d: d.level_values)
                         for l in range(len(self.levels))]
            index = [{d.id: i for i, d in enumerate(doms)}
                     for doms in per_level]
            parents = []
            for l, doms in enumerate(per_level):
                if l == 0:
                    parents.append(np.zeros(len(doms), dtype=np.int32))
                else:
                    parents.append(np.asarray(
                        [index[l - 1][d.id[:-1]] for d in doms],
                        dtype=np.int32))
            self._device_tree = (parents, per_level)
        return self._device_tree

    @staticmethod
    def _untolerated(node: Node, tolerations: list[Toleration]):
        for taint in node.taints:
            if taint.effect not in ("NoSchedule", "NoExecute"):
                continue
            if not any(t.tolerates(taint) for t in tolerations):
                return taint
        return None

    def _roll_up(self, dom: Domain, slice_size: int, slice_level_idx: int,
                 level: int, leader_required: bool) -> None:
        """fillInCountsHelper (tas_flavor_snapshot.go:1658-1719)."""
        if not dom.children:
            if level == slice_level_idx:
                dom.slice_state = dom.state // slice_size
                dom.slice_state_with_leader = (
                    dom.state_with_leader // slice_size)
            return
        total = 0
        slice_total = 0
        has_leader_contributor = False
        min_state_diff = 1 << 30
        min_slice_diff = 1 << 30
        leader_state = 0
        for child in dom.children:
            self._roll_up(child, slice_size, slice_level_idx, level + 1,
                          leader_required)
            total += child.state
            slice_total += child.slice_state
            if not leader_required or child.leader_state > 0:
                has_leader_contributor = True
                min_state_diff = min(
                    min_state_diff, child.state - child.state_with_leader)
                min_slice_diff = min(
                    min_slice_diff,
                    child.slice_state - child.slice_state_with_leader)
            leader_state = max(leader_state, child.leader_state)
        dom.state = total
        dom.leader_state = leader_state
        slice_with_leader = 0
        if has_leader_contributor:
            dom.state_with_leader = total - min_state_diff
            slice_with_leader = slice_total - min_slice_diff
        else:
            dom.state_with_leader = 0
        if level == slice_level_idx:
            slice_total = dom.state // slice_size
            slice_with_leader = dom.state_with_leader // slice_size
        dom.slice_state = slice_total
        dom.slice_state_with_leader = slice_with_leader

    # ------------------------------------------------------------------
    # Phase 2: placement
    # ------------------------------------------------------------------

    def _place(
        self,
        tr: TASPodSetRequest,
        leader: Optional[TASPodSetRequest],
        assumed,
        simulate_empty: bool,
        required_replacement_domain: Optional[tuple[str, ...]] = None,
        excluded_node: Optional[str] = None,
    ) -> tuple[dict[str, TopologyAssignment], str]:
        """findTopologyAssignment (tas_flavor_snapshot.go:804-999)."""
        tr_req = tr.podset.topology_request
        required = tr_req is not None and tr_req.required is not None
        unconstrained = (
            (tr_req is not None and tr_req.unconstrained) or tr.implied
            or (tr_req is not None
                and tr_req.podset_slice_required_topology is not None
                and tr_req.required is None and tr_req.preferred is None))

        key = self._level_key(tr.podset, tr.implied)
        if key is None:
            return {}, "topology level not specified"
        level_idx = self.level_index(key)
        if level_idx is None:
            return {}, f"no requested topology level: {key}"

        slice_size = 1
        slice_level_idx = len(self.levels) - 1
        if tr_req is not None and tr_req.podset_slice_required_topology:
            idx = self.level_index(tr_req.podset_slice_required_topology)
            if idx is None:
                return {}, (
                    "no requested topology level for slices: "
                    f"{tr_req.podset_slice_required_topology}")
            slice_level_idx = idx
            if tr_req.podset_slice_size is None:
                return {}, "slice topology requested, but slice size not provided"
            slice_size = tr_req.podset_slice_size
            if level_idx > slice_level_idx:
                return {}, (
                    f"podset slice topology "
                    f"{tr_req.podset_slice_required_topology} is above the "
                    f"podset topology {key}")
            if tr.count % slice_size != 0:
                return {}, (
                    f"pod count {tr.count} not divisible by slice size "
                    f"{slice_size}")

        slice_size_at_level, reason = self._build_slice_size_at_level(
            tr_req, slice_size, slice_level_idx)
        if reason:
            return {}, reason

        leader_count = 1 if leader is not None else 0
        stats = self._fill_in_counts(
            tr, leader, assumed, simulate_empty, slice_size, slice_level_idx,
            required_replacement_domain, excluded_node=excluded_node)

        least_free = unconstrained and self.profile_mixed

        # balanced placement (gate TASBalancedPlacement; preferred-level
        # requests only — tas_flavor_snapshot.go:906-917)
        from kueue_oss_tpu import features

        use_balanced = False
        fit_domains = None
        fit_level = level_idx
        if (features.enabled("TASBalancedPlacement") and not required
                and not unconstrained):
            cand, threshold = self._find_best_balanced(
                level_idx, slice_level_idx, tr.count, leader_count,
                slice_size)
            if threshold > 0:
                fit_domains, fit_level, reason = self._apply_balanced(
                    cand, level_idx, slice_level_idx, tr.count,
                    leader_count, slice_size, threshold)
                if reason:
                    return {}, reason
                use_balanced = True

        if not use_balanced:
            fit_level, fit_domains, reason = self._find_level_with_fit(
                level_idx, tr.count, leader_count, slice_size, required,
                unconstrained, least_free, stats)
            if reason:
                return {}, reason
            fit_domains = self._consume_minimum(
                fit_domains, tr.count, leader_count, slice_size, least_free,
                slices=True)
        cur_level = fit_level
        while (cur_level < min(len(self.levels) - 1, slice_level_idx)
               and not use_balanced):
            lower = [c for d in fit_domains for c in d.children]
            fit_domains = self._consume_minimum(
                self._sorted(lower, least_free), tr.count, leader_count,
                slice_size, least_free, slices=True)
            cur_level += 1
        while cur_level < len(self.levels) - 1:
            # below (or, after balanced placement, possibly still above)
            # the outermost slice level: per-parent assignment, with inner
            # slice layers re-grouping children at their own size
            # (tas_flavor_snapshot.go:938-971)
            if cur_level < slice_level_idx:
                size_on_level = slice_size
            else:
                size_on_level = slice_size_at_level.get(cur_level + 1, 1)
            new_fit: list[Domain] = []
            for dom in fit_domains:
                if size_on_level > 1:
                    # the pre-filled sliceState was computed for the
                    # outermost slice level; inner layers re-derive it
                    # BEFORE sorting (the sort keys on slice_state)
                    for d in dom.children:
                        d.slice_state = d.state // size_on_level
                        d.slice_state_with_leader = (
                            d.state_with_leader // size_on_level)
                children = self._sorted(dom.children, least_free)
                new_fit.extend(self._consume_minimum(
                    children, dom.state, dom.leader_state, size_on_level,
                    least_free, slices=size_on_level > 1))
            fit_domains = new_fit
            cur_level += 1

        assignments: dict[str, TopologyAssignment] = {}
        if leader is not None:
            leader_domains = []
            worker_domains = []
            for dom in fit_domains:
                if dom.leader_state > 0:
                    copy = Domain(dom.id, dom.level_values)
                    copy.state = dom.leader_state
                    leader_domains.append(copy)
                if dom.state > 0:
                    worker_domains.append(dom)
            assignments[leader.podset.name] = self._build(leader_domains)
            fit_domains = worker_domains
        assignments[tr.podset.name] = self._build(fit_domains)
        return assignments, ""

    # ------------------------------------------------------------------
    # multi-layer slice constraints (buildSliceSizeAtLevel,
    # tas_flavor_snapshot.go:1001-1060)
    # ------------------------------------------------------------------

    def _build_slice_size_at_level(self, tr_req, slice_size: int,
                                   slice_level_idx: int):
        """Level index -> inner slice size for nested slice layers.

        The first constraint mirrors the outer slice (skipped); each
        inner layer must sit strictly below its parent layer and evenly
        divide its size; intermediate levels inherit the layer's size so
        they distribute in multiples of it."""
        from kueue_oss_tpu import features

        out: dict[int, int] = {}
        if (not features.enabled("TASMultiLayerTopology") or tr_req is None
                or not tr_req.podset_slice_constraints):
            return out, ""
        layers = tr_req.podset_slice_constraints
        inner = layers[1:] if len(layers) > 1 else []
        prev_size = slice_size
        prev_idx = slice_level_idx
        for layer in inner:
            idx = self.level_index(layer.topology)
            if idx is None:
                return None, ("no requested topology level for additional "
                              f"slice layer: {layer.topology}")
            if idx <= prev_idx:
                return None, (
                    f"additional slice layer topology {layer.topology} must "
                    f"be at a lower level than {self.levels[prev_idx]}")
            if prev_size % layer.size != 0:
                return None, (
                    f"additional slice layer size {layer.size} must evenly "
                    f"divide parent layer size {prev_size}")
            for lvl in range(prev_idx + 1, idx + 1):
                out[lvl] = layer.size
            prev_size = layer.size
            prev_idx = idx
        return out, ""

    # ------------------------------------------------------------------
    # balanced placement (tas_balanced_placement.go)
    # ------------------------------------------------------------------

    def _clone_domain(self, d: Domain) -> Domain:
        c = Domain(d.id, d.level_values)
        c.state = d.state
        c.state_with_leader = d.state_with_leader
        c.slice_state = d.slice_state
        c.slice_state_with_leader = d.slice_state_with_leader
        c.leader_state = d.leader_state
        c.children = [self._clone_domain(ch) for ch in d.children]
        return c

    @staticmethod
    def _clear_state(d: Domain) -> None:
        d.state = d.slice_state = 0
        d.state_with_leader = d.slice_state_with_leader = 0
        d.leader_state = 0
        for c in d.children:
            TASFlavorSnapshot._clear_state(c)

    @staticmethod
    def _clear_leader_capacity(d: Domain) -> None:
        d.state_with_leader = d.slice_state_with_leader = 0
        d.leader_state = 0
        for c in d.children:
            TASFlavorSnapshot._clear_leader_capacity(c)

    def _evaluate_greedy(self, domains: list[Domain], slice_count: int,
                         leader_count: int):
        """evaluateGreedyAssignment: (fits, #domains used, last domain
        with leader, last domain without)."""
        selected = 0
        last = last_with_leader = None
        rem_slices = slice_count
        rem_leaders = leader_count
        idx = 0
        if leader_count > 0:
            sorted_wl = self._sorted_with_leader(domains, False)
            while (rem_leaders > 0 and idx < len(sorted_wl)
                   and sorted_wl[idx].leader_state > 0):
                selected += 1
                last_with_leader = sorted_wl[idx]
                rem_leaders -= sorted_wl[idx].leader_state
                rem_slices -= sorted_wl[idx].slice_state_with_leader
                idx += 1
            rest = self._sorted(sorted_wl[idx:], False)
        else:
            rest = self._sorted(domains, False)
        if rem_leaders > 0:
            return False, 0, None, None
        i = 0
        while rem_slices > 0 and i < len(rest) and rest[i].slice_state > 0:
            selected += 1
            last = rest[i]
            rem_slices -= rest[i].slice_state
            i += 1
        if rem_slices > 0:
            return False, 0, None, None
        return True, selected, last_with_leader, last

    @staticmethod
    def _balance_threshold(slice_count: int, selected: int,
                           last_with_leader, last) -> int:
        """Max possible minimum slices per domain in a balanced plan."""
        threshold = slice_count // selected
        if last_with_leader is not None:
            threshold = min(threshold,
                            last_with_leader.slice_state_with_leader)
        if last is not None:
            threshold = min(threshold, last.slice_state)
        return threshold

    def _prune_below_threshold(self, domains: list[Domain], threshold: int,
                               slice_size: int, slice_level_idx: int,
                               level: int, leader_required: bool) -> None:
        """pruneDomainsBelowThreshold: drop capacity of subtrees that
        cannot hold `threshold` slices, then re-roll counts."""
        def prune_node(d: Domain) -> None:
            if d.slice_state < threshold:
                self._clear_state(d)
                return
            if (leader_required and d.leader_state > 0
                    and d.slice_state_with_leader < threshold):
                self._clear_leader_capacity(d)

        for d in domains:
            for c in d.children:
                prune_node(c)
        for d in domains:
            self._roll_up(d, slice_size, slice_level_idx, level,
                          leader_required)
            prune_node(d)

    @staticmethod
    def _entropy(sizes: list[int]) -> float:
        import math

        total = sum(sizes)
        if total <= 0:
            return 0.0
        e = 0.0
        for s in sizes:
            if s > 0:
                p = s / total
                e += -p * math.log2(p)
        return e

    def _select_optimal_set(self, domains: list[Domain], slice_count: int,
                            leader_count: int, slice_size: int,
                            by_entropy: bool) -> Optional[list[Domain]]:
        """selectOptimalDomainSetToFit: DP over domains finding a set of
        exactly the greedy-minimal cardinality that fits leaders+slices,
        preferring the tightest total capacity."""
        fits, optimal_n, _, _ = self._evaluate_greedy(
            domains, slice_count, leader_count)
        if not fits:
            return None
        if by_entropy:
            domains = sorted(domains, key=lambda d: (
                -d.leader_state, -d.slice_state_with_leader,
                -self._entropy([c.state for c in d.children])))
        # placements[i][(leaders_left, capacity_left)] -> domain list
        placements: list[dict[tuple[int, int], list[Domain]]] = [
            {} for _ in range(optimal_n + 1)]
        placements[0][(leader_count, slice_count * slice_size)] = []
        for d in domains:
            for i in range(optimal_n, 0, -1):
                for (lead, cap) in sorted(placements[i - 1]):
                    if lead <= 0 and cap <= 0:
                        continue
                    before = placements[i - 1][(lead, cap)]
                    nxt = before + [d]
                    if lead > 0 and d.leader_state > 0:
                        k = (lead - d.leader_state,
                             cap - d.state_with_leader)
                        placements[i].setdefault(k, nxt)
                    if d.slice_state > 0:
                        k = (lead, cap - d.state)
                        placements[i].setdefault(k, nxt)
        best_cap = None
        best = None
        for (lead, cap), doms in placements[optimal_n].items():
            if lead == 0 and cap <= 0 and (best_cap is None
                                           or cap > best_cap):
                best_cap = cap
                best = doms
        return best

    def _place_slices_balanced(self, domains: list[Domain],
                               slice_count: int, leader_count: int,
                               slice_size: int, threshold: int):
        """placeSlicesOnDomainsBalanced: give every selected domain
        `threshold` slices, distributing the remainder (and leaders)
        front-first."""
        result = self._select_optimal_set(domains, slice_count,
                                          leader_count, slice_size, False)
        if result is None:
            return None, ("TAS Balanced Placement: Cannot find optimal "
                          "domain set to fit the request")
        if slice_count < len(result) * threshold:
            return None, ("TAS Balanced Placement: Not enough slices to "
                          "meet the threshold")
        result = self._sorted_with_leader(result, False)
        extra_left = slice_count - len(result) * threshold
        leaders_left = leader_count
        for dom in result:
            if leaders_left > 0:
                take = min(dom.slice_state_with_leader - threshold,
                           extra_left)
                dom.leader_state = 1
                leaders_left -= 1
            elif extra_left > 0:
                take = min(dom.slice_state - threshold, extra_left)
                dom.leader_state = 0
            else:
                dom.leader_state = 0
                take = 0
            dom.state = (threshold + take) * slice_size
            dom.slice_state = threshold + take
            dom.slice_state_with_leader = dom.slice_state
            dom.state_with_leader = dom.state - dom.leader_state
            extra_left -= take
        if extra_left > 0 or leaders_left > 0:
            return None, ("TAS Balanced Placement: Not all slices or "
                          "leaders could be placed")
        return result, ""

    def _find_best_balanced(self, level_idx: int, slice_level_idx: int,
                            count: int, leader_count: int,
                            slice_size: int):
        """findBestDomainsForBalancedPlacement: per sibling group at the
        requested level, compute the balance threshold, prune, and keep
        the best (highest threshold, then fewest domains)."""
        slice_count = count // slice_size

        def lower(doms):
            if level_idx < slice_level_idx:
                return [c for d in doms for c in d.children]
            return doms

        if level_idx == 0:
            groups = [list(self.domains_per_level[0].values())]
        else:
            groups = [list(d.children)
                      for d in self.domains_per_level[level_idx - 1].values()]

        best_threshold = 0
        best_count = 0
        best: Optional[list[Domain]] = None
        for siblings in groups:
            cand = [self._clone_domain(d) for d in siblings]
            fits, selected, lwl, last = self._evaluate_greedy(
                lower(cand), slice_count, leader_count)
            if not fits:
                continue
            threshold = self._balance_threshold(slice_count, selected,
                                                lwl, last)
            thr_leader = threshold
            if leader_count > 0 and last is not None:
                thr_leader = min(threshold, last.slice_state_with_leader)
            if threshold < best_threshold:
                continue
            self._prune_below_threshold(
                cand, threshold, slice_size, slice_level_idx, level_idx,
                leader_count > 0)
            ok, n_doms, _, _ = self._evaluate_greedy(
                cand, slice_count, leader_count)
            if not ok and thr_leader < threshold:
                # retry at the lower threshold that reserves leader room
                if thr_leader <= 0 or thr_leader < best_threshold:
                    continue
                threshold = thr_leader
                cand = [self._clone_domain(d) for d in siblings]
                self._prune_below_threshold(
                    cand, threshold, slice_size, slice_level_idx,
                    level_idx, leader_count > 0)
                ok, n_doms, _, _ = self._evaluate_greedy(
                    cand, slice_count, leader_count)
            if not ok:
                continue
            if threshold > best_threshold or (
                    threshold == best_threshold
                    and (best is None or n_doms < best_count)):
                best_threshold = threshold
                best_count = n_doms
                best = cand
        return best, best_threshold

    def _apply_balanced(self, cand: list[Domain], level_idx: int,
                        slice_level_idx: int, count: int,
                        leader_count: int, slice_size: int,
                        threshold: int):
        """applyBalancedPlacementAlgorithm: select the optimal set (one
        level down when the request sits above the slice level) and
        distribute slices evenly."""
        slice_count = count // slice_size
        if level_idx < slice_level_idx:
            result = self._select_optimal_set(
                cand, slice_count, leader_count, slice_size, True)
            if result is None:
                return None, 0, ("TAS Balanced Placement: Cannot find "
                                 "optimal domain set to fit the request")
            cand = [c for d in result for c in d.children]
            fit_level = level_idx + 1
        else:
            fit_level = level_idx
        cand, reason = self._place_slices_balanced(
            cand, slice_count, leader_count, slice_size, threshold)
        if reason:
            return None, 0, reason
        return cand, fit_level, ""

    def _find_level_with_fit(self, level_idx: int, count: int,
                             leader_count: int, slice_size: int,
                             required: bool, unconstrained: bool,
                             least_free: bool, stats) -> tuple:
        """findLevelWithFitDomains (tas_flavor_snapshot.go:1236-1321)."""
        domains = list(self.domains_per_level[level_idx].values())
        if not domains:
            return 0, None, f"no topology domains at level: {self.levels[level_idx]}"
        sorted_doms = self._sorted_with_leader(domains, least_free)
        top = sorted_doms[0]
        slice_count = count // slice_size

        if (not least_free and top.slice_state_with_leader >= slice_count
                and top.leader_state >= leader_count):
            top = self._best_fit_slices(sorted_doms, slice_count, leader_count)

        if least_free:
            for cand in sorted_doms:
                if cand.slice_state >= slice_count:
                    return level_idx, [cand], ""
            if required:
                return 0, None, self._not_fit_message(
                    sorted_doms[-1].state, slice_count, slice_size, stats)

        if top.slice_state_with_leader < slice_count or (
                top.leader_state < leader_count):
            if required:
                return 0, None, self._not_fit_message(
                    top.slice_state, slice_count, slice_size, stats)
            if level_idx > 0 and not unconstrained:
                return self._find_level_with_fit(
                    level_idx - 1, count, leader_count, slice_size, required,
                    unconstrained, least_free, stats)
            # accumulate multiple domains greedily, leaders first
            results: list[Domain] = []
            remaining_slices = slice_count
            remaining_leaders = leader_count
            idx = 0
            while (remaining_leaders > 0 and idx < len(sorted_doms)
                   and sorted_doms[idx].leader_state > 0):
                dom = sorted_doms[idx]
                if (not least_free
                        and dom.slice_state_with_leader >= remaining_slices):
                    dom = self._best_fit_slices(
                        sorted_doms[idx:], remaining_slices, remaining_leaders)
                results.append(dom)
                remaining_leaders -= dom.leader_state
                remaining_slices -= dom.slice_state_with_leader
                idx += 1
            if remaining_leaders > 0:
                return 0, None, self._not_fit_message(
                    leader_count - remaining_leaders, slice_count, slice_size,
                    stats)
            rest = self._sorted(sorted_doms[idx:], least_free)
            for i in range(len(rest)):
                if remaining_slices <= 0:
                    break
                dom = rest[i]
                if not least_free and dom.slice_state >= remaining_slices:
                    dom = self._best_fit_slices(rest[i:], remaining_slices, 0)
                results.append(dom)
                remaining_slices -= dom.slice_state
            if remaining_slices > 0:
                return 0, None, self._not_fit_message(
                    slice_count - remaining_slices, slice_count, slice_size,
                    stats)
            return level_idx, results, ""
        return level_idx, [top], ""

    @staticmethod
    def _best_fit_slices(domains: list[Domain], needed: int,
                         leader_count: int) -> Domain:
        """First domain with the smallest sufficient capacity
        (findBestFitDomainBy, tas_flavor_snapshot.go:1216-1231)."""
        def state(d: Domain) -> int:
            return (d.slice_state_with_leader if leader_count > 0
                    else d.slice_state)

        best = domains[0]
        for dom in domains:
            if needed <= state(dom) < state(best):
                best = dom
        return best

    @staticmethod
    def _best_fit_pods(domains: list[Domain], needed: int,
                       leader_count: int) -> Domain:
        def state(d: Domain) -> int:
            return d.state_with_leader if leader_count > 0 else d.state

        best = domains[0]
        for dom in domains:
            if needed <= state(dom) < state(best):
                best = dom
        return best

    def _consume_minimum(self, domains: list[Domain], count: int,
                         leader_count: int, slice_size: int,
                         least_free: bool, slices: bool) -> list[Domain]:
        """Assign `count` pods (or count/slice_size slices) onto the fewest
        domains, leaders first (updateCountsToMinimumGeneric,
        tas_flavor_snapshot.go:1405-1469)."""
        result: list[Domain] = []
        remaining = count // slice_size if slices else count
        remaining_leaders = leader_count
        for i, dom in enumerate(domains):
            if remaining_leaders > 0:
                dom, done = self._consume_with_leader(
                    dom, domains[i:], remaining, remaining_leaders,
                    least_free, slice_size, slices)
                if done:
                    result.append(dom)
                    return result
                if slices:
                    remaining -= dom.slice_state_with_leader
                    remaining_leaders -= dom.leader_state
                else:
                    remaining -= dom.state_with_leader
                    remaining_leaders -= dom.leader_state
                result.append(dom)
                continue
            if slices:
                if not least_free and dom.slice_state >= remaining:
                    dom = self._best_fit_slices(domains[i:], remaining, 0)
                dom.leader_state = 0
                if dom.slice_state >= remaining:
                    dom.state = remaining * slice_size
                    dom.slice_state = remaining
                    result.append(dom)
                    return result
                dom.state = dom.slice_state * slice_size
                remaining -= dom.slice_state
                result.append(dom)
            else:
                if not least_free and dom.state >= remaining:
                    dom = self._best_fit_pods(domains[i:], remaining, 0)
                dom.leader_state = 0
                if dom.state >= remaining:
                    dom.state = remaining
                    result.append(dom)
                    return result
                remaining -= dom.state
                result.append(dom)
        # all domains consumed; remaining should be 0 when callers sized
        # the domain set correctly
        return result

    def _consume_with_leader(self, dom: Domain, rest: list[Domain],
                             remaining: int, remaining_leaders: int,
                             least_free: bool, slice_size: int,
                             slices: bool) -> tuple[Domain, bool]:
        """consumeWithLeadersGeneric (tas_flavor_snapshot.go:1348-1403)."""
        def with_leader(d: Domain) -> int:
            return d.slice_state_with_leader if slices else d.state_with_leader

        if (not least_free and with_leader(dom) >= remaining
                and dom.leader_state >= remaining_leaders):
            if slices:
                dom = self._best_fit_slices(rest, remaining, remaining_leaders)
            else:
                dom = self._best_fit_pods(rest, remaining, remaining_leaders)
        if with_leader(dom) >= remaining and dom.leader_state >= remaining_leaders:
            if slices:
                dom.slice_state = remaining
                dom.slice_state_with_leader = remaining
            else:
                dom.state_with_leader = remaining
            dom.leader_state = remaining_leaders
            dom.state = remaining * slice_size if slices else remaining
            return dom, True
        if slices:
            dom.slice_state_with_leader = min(
                dom.slice_state_with_leader, remaining)
            dom.leader_state = min(dom.leader_state, remaining_leaders)
            dom.state = dom.slice_state_with_leader * slice_size
        else:
            dom.state_with_leader = min(dom.state_with_leader, remaining)
            dom.leader_state = min(dom.leader_state, remaining_leaders)
            dom.state = dom.state_with_leader
        return dom, False

    # -- sorting (sortedDomains / sortedDomainsWithLeader) ------------------

    def _sorted(self, domains: list[Domain], least_free: bool) -> list[Domain]:
        if least_free:
            return sorted(domains, key=lambda d: (
                d.slice_state, d.state, d.level_values))
        return sorted(domains, key=lambda d: (
            -d.slice_state, d.state, d.level_values))

    def _sorted_with_leader(self, domains: list[Domain],
                            least_free: bool) -> list[Domain]:
        if least_free:
            return sorted(domains, key=lambda d: (
                -d.leader_state, d.slice_state_with_leader,
                d.state_with_leader, d.level_values))
        return sorted(domains, key=lambda d: (
            -d.leader_state, -d.slice_state_with_leader,
            d.state_with_leader, d.level_values))

    # -- output -------------------------------------------------------------

    def _build(self, domains: list[Domain]) -> TopologyAssignment:
        """buildAssignment (tas_flavor_snapshot.go:1490-1501): lex order;
        hostname-only values when the lowest level is the hostname."""
        domains = sorted(domains, key=lambda d: d.level_values)
        level_idx = len(self.levels) - 1 if self.is_lowest_level_node else 0
        return TopologyAssignment(
            levels=self.levels[level_idx:],
            domains=[
                TopologyDomainAssignment(
                    values=list(d.level_values[level_idx:]), count=d.state)
                for d in domains if d.state > 0
            ],
        )

    def _not_fit_message(self, fit, total, slice_size, stats) -> str:
        unit = "pod" if slice_size == 1 else "slice"
        if fit <= 0:
            msg = (f"topology {self.topology_name!r} doesn't allow to fit any "
                   f"of {total} {unit}(s)")
        else:
            msg = (f"topology {self.topology_name!r} allows to fit only "
                   f"{fit} out of {total} {unit}(s)")
        exclusions = []
        if stats["taints"]:
            exclusions.append(f"taints: {stats['taints']}")
        if stats["selector"]:
            exclusions.append(f"nodeSelector: {stats['selector']}")
        if stats["domain"]:
            exclusions.append(f"topologyDomain: {stats['domain']}")
        for res, cnt in sorted(stats["resources"].items()):
            exclusions.append(f"resource {res!r}: {cnt}")
        if exclusions:
            msg += (f". Total nodes: {stats['total']}; excluded: "
                    + ", ".join(exclusions))
        return msg


def build_tas_flavor_snapshot(
    topology_name: str,
    levels: list[str],
    nodes: Iterable[Node],
    flavor_node_labels: Optional[dict[str, str]] = None,
    tolerations: Optional[list[Toleration]] = None,
    profile_mixed: Optional[bool] = None,
) -> TASFlavorSnapshot:
    """Build and initialize a snapshot from ready nodes matching the
    flavor's nodeLabels (tas_flavor.go / tas_nodes_cache.go analog).
    profile_mixed defaults from the TASProfileMixed gate."""
    from kueue_oss_tpu import features

    if profile_mixed is None:
        profile_mixed = features.enabled("TASProfileMixed")
    snap = TASFlavorSnapshot(topology_name, levels, tolerations,
                             profile_mixed=profile_mixed)
    # round-5 hybrid: phase-1 fill-in counts on the accelerator, every
    # phase-2 tie-break (balanced DP, multilayer descent) host-side
    snap.use_device_fill = features.enabled("TASDeviceFillCounts")
    selector = flavor_node_labels or {}
    for node in nodes:
        if not node.ready:
            continue
        if all(node.labels.get(k) == v for k, v in selector.items()):
            snap.add_node(node)
    snap.initialize()
    return snap
