"""Topology-aware scheduling (TAS).

Reference parity: pkg/cache/scheduler/tas_*.go + pkg/controller/tas
(KEP-2724). Places podsets onto a topology tree (e.g. block > rack > host)
honoring required/preferred/unconstrained levels, slice grouping, leader
co-location, and unhealthy-node replacement.
"""

from kueue_oss_tpu.core.workload_info import effective_per_pod_requests
from kueue_oss_tpu.tas.snapshot import (
    TASAssignmentResult,
    TASFlavorSnapshot,
    TASPodSetRequest,
    build_tas_flavor_snapshot,
)


def requests_from_admission(wl, cq_snapshot,
                            only_pending: bool = False):
    """Per-flavor TASPodSetRequests rebuilt from a recorded admission
    (used by the second pass and node-failure repair, where no live
    Assignment object exists). With only_pending, limits to podsets whose
    DelayedTopologyRequest is still Pending."""
    podsets = {ps.name: ps for ps in wl.podsets}
    out: dict[str, list[TASPodSetRequest]] = {}
    if wl.status.admission is None:
        return out
    for psa in wl.status.admission.podset_assignments:
        if only_pending:
            if (psa.delayed_topology_request != "Pending"
                    or psa.topology_assignment is not None):
                continue
        elif psa.topology_assignment is None:
            continue
        ps = podsets.get(psa.name)
        if ps is None:
            continue
        tas_flavor = next((f for f in psa.flavors.values()
                           if f in cq_snapshot.tas_flavors), None)
        if tas_flavor is None:
            continue
        out.setdefault(tas_flavor, []).append(TASPodSetRequest(
            podset=ps,
            single_pod_requests=effective_per_pod_requests(
                ps, wl.namespace),
            count=psa.count,
            flavor=tas_flavor,
            implied=ps.topology_request is None,
            podset_group_name=(
                ps.topology_request.podset_group_name
                if ps.topology_request is not None else None),
        ))
    return out


__all__ = [
    "TASAssignmentResult",
    "TASFlavorSnapshot",
    "TASPodSetRequest",
    "build_tas_flavor_snapshot",
    "requests_from_admission",
]
