"""Topology-aware scheduling (TAS).

Reference parity: pkg/cache/scheduler/tas_*.go + pkg/controller/tas
(KEP-2724). Places podsets onto a topology tree (e.g. block > rack > host)
honoring required/preferred/unconstrained levels, slice grouping, leader
co-location, and unhealthy-node replacement.
"""

from kueue_oss_tpu.tas.snapshot import (
    TASAssignmentResult,
    TASFlavorSnapshot,
    TASPodSetRequest,
    build_tas_flavor_snapshot,
)

__all__ = [
    "TASAssignmentResult",
    "TASFlavorSnapshot",
    "TASPodSetRequest",
    "build_tas_flavor_snapshot",
]
