"""The scheduling cycle.

Reference parity: pkg/scheduler/scheduler.go — one cycle = pop queue heads,
snapshot the cache, nominate (flavor assignment + preemption targets), order
entries (classical sort or fair-sharing tournament), then admit/preempt with
at most one cohort-conflicting admission per cycle, requeueing the rest.
"""

from __future__ import annotations

import functools
import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import (
    Admission,
    PodSetAssignment,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.queue_manager import QueueManager, RequeueReason
from kueue_oss_tpu.core.snapshot import (
    ClusterQueueSnapshot,
    Snapshot,
    build_snapshot,
)
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu import metrics, obs
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_per_pod_requests,
    effective_priority,
    queue_order_timestamp,
)
from kueue_oss_tpu.scheduler import flavor_assigner as fa
from kueue_oss_tpu.scheduler.flavor_assigner import (
    Assignment,
    FlavorAssigner,
    PodSetReducer,
)
from kueue_oss_tpu.scheduler.preemption import Preemptor, Target
from kueue_oss_tpu.util.events import NORMAL, WARNING, recorder as events

# entry status (scheduler.go entryStatus)
NOT_NOMINATED = ""
NOMINATED = "nominated"
ASSUMED = "assumed"
SKIPPED = "skipped"
EVICTED = "evicted"


@dataclass
class Entry:
    info: WorkloadInfo
    assignment: Assignment = field(default_factory=Assignment)
    status: str = NOT_NOMINATED
    inadmissible_msg: str = ""
    requeue_reason: str = RequeueReason.GENERIC
    preemption_targets: list[Target] = field(default_factory=list)
    cq_snapshot: Optional[ClusterQueueSnapshot] = None

    def assignment_usage(self):
        if self.info.obj.is_quota_reserved:
            return {}
        return dict(self.assignment.usage_quota)


@dataclass
class CycleStats:
    cycle: int = 0
    heads: int = 0
    admitted: int = 0
    preempted: int = 0
    skipped: int = 0
    inadmissible: int = 0
    duration_s: float = 0.0


class Scheduler:
    """One-process scheduler over the in-memory store."""

    def __init__(
        self,
        store: Store,
        queues: QueueManager,
        enable_fair_sharing: bool = False,
        enable_partial_admission: bool = True,
        clock=time.monotonic,
        solver=None,
        solver_min_backlog: int = 256,
        solver_reengage_fraction: float = 0.05,
        solver_config=None,
        eviction_backoff_max_s: float = 3600.0,
        streaming=None,
    ) -> None:
        self.store = store
        self.queues = queues
        self.enable_fair_sharing = enable_fair_sharing
        self.enable_partial_admission = enable_partial_admission
        self.clock = clock
        self.preemptor = Preemptor(enable_fair_sharing=enable_fair_sharing)
        self.cycle_count = 0
        #: batched TPU solver backend: None (host-only cycles), "auto"
        #: (build a SolverEngine over this store/queues), or a
        #: SolverEngine instance. When set, run_until_quiet() drains
        #: solver-supported backlogs on-device with verify-then-assume
        #: (each admission re-checked against the scalar oracle before
        #: committing — scheduler.go:427 fits re-check parity) and falls
        #: back to host cycles for unsupported shapes / rejected entries.
        self.solver = solver
        self._solver_instance = None
        #: config.SolverBackendConfig for the "auto" engine: remote
        #: socket, client deadlines/retries, and breaker thresholds.
        #: None = built-in defaults (+ KUEUE_SOLVER_* env overrides).
        self.solver_config = solver_config
        #: skip the device drain below this many active pending
        #: workloads: a batched solve pays a fixed host-side export cost
        #: per invocation, so backlog FLOODS go to the device while
        #: trickles stay on the host cycle loop (the deployments' sweet
        #: spot; SURVEY.md §7 incrementality note). 0 = always drain.
        self.solver_min_backlog = solver_min_backlog
        #: benefit-aware re-engagement: after the flood drain, a batched
        #: solve re-walks the whole parked backlog (one kernel round per
        #: backlog-depth entry per CQ), so it only pays off when enough
        #: capacity has freed since the last drain to admit a flood-sized
        #: batch. Until freed-capacity events reach
        #: max(solver_min_backlog, fraction * backlog), trickle churn
        #: stays on the host cycle loop (which is O(heads) per cycle).
        #: 0 = re-engage on every pass (pre-round-5 behavior).
        self.solver_reengage_fraction = solver_reengage_fraction
        self._solver_drained_once = False
        self._solver_freed_since_drain = 0
        #: queues.new_pending_total at the last drain — diffed so a
        #: fresh arrival flood re-engages even with zero finishes
        self._solver_arrivals_mark = 0
        #: arrival-triggered drains back off exponentially while they
        #: admit nothing (arrivals behind a capacity-blocked backlog);
        #: any productive drain resets the multiplier
        self._solver_arrival_mult = 1
        self._solver_drain_trigger = None
        #: streaming micro-batched admission between full solves
        #: (scheduler/streaming.py, docs/ARCHITECTURE.md "Streaming
        #: dataflow"): None/False = off (the cycle-batch model,
        #: unchanged), True = defaults, or a config.StreamingConfig.
        #: Requires a solver backend — commits ride the engine's
        #: commit path so streamed admissions are indistinguishable
        #: in durable state from batched ones.
        self.streaming = streaming
        self._streaming_instance = None
        #: serializes cycle bodies and micro-drains across the serve
        #: loop and the watch-driven drain worker (reentrant: the
        #: serve loop's cycle body calls micro_drain itself)
        self._cycle_mu = threading.RLock()
        #: wall of the most recent full schedule() cycle; the serve
        #: loop refuses to skip host cycles longer than the streaming
        #: config's max_cycle_gap (SLO windows must roll, requeue
        #: backoffs must expire, even while micro-drains serve)
        self._last_full_cycle_wall = 0.0
        #: adaptive routing cost estimates (EMAs): drain wall PER
        #: EXPORTED WORKLOAD (drain cost scales with backlog) and the
        #: host cycle's per-admission cost; None until measured
        self._drain_cost_ema: Optional[float] = None
        self._host_s_per_adm: Optional[float] = None
        #: Preemption/generic evictions requeue immediately (ordered by
        #: eviction time, reference workload.Ordering). Only controller
        #: evictions that pass an explicit backoff_base_s (PodsReady
        #: timeouts, RequeuingStrategy) get a RequeueState gate; this cap
        #: bounds their exponential delay when no per-call cap is given.
        self.eviction_backoff_max_s = eviction_backoff_max_s
        #: min-heap of (requeue_at, workload key) pending backoff expiries
        self._requeue_heap: list[tuple[float, str]] = []
        #: CQs whose usage changed outside entry processing (evictions)
        self._cycle_touched_cqs: set[str] = set()
        #: per-cycle skip counts by bounded reason slug — feeds the
        #: cycle ledger row (reset at each cycle start)
        self._cycle_skip_slugs: dict[str, int] = {}
        #: cq -> (lq, ns) label sets last reported, for gauge zero-fill
        self._lq_reported: dict[str, set] = {}
        from kueue_oss_tpu.util import logging as klog

        #: structured logger (zap-via-controller-runtime analog)
        self.log = klog.root.with_name("scheduler")
        # metrics
        self.admitted_total: dict[str, int] = {}
        self.preempted_total: dict[str, int] = {}
        self.evicted_total: dict[str, int] = {}
        self.admission_attempt_durations: list[float] = []
        #: in-flight preemption tracking (pkg/util/expectations)
        from kueue_oss_tpu.util.expectations import ExpectationsStore

        self.preemption_expectations = ExpectationsStore()

    # ------------------------------------------------------------------
    # Cycle
    # ------------------------------------------------------------------

    def schedule(self, now: Optional[float] = None) -> CycleStats:
        start = self.clock()
        wall0 = time.monotonic()
        now = now if now is not None else start
        self.cycle_count += 1
        stats = CycleStats(cycle=self.cycle_count)
        self.queues.current_time = now  # AFS decay reference point
        obs.slo_engine.advance(now)  # windows roll on idle cycles too
        self._cycle_skip_slugs = {}
        self.requeue_due(now)
        self._run_second_pass(now)

        heads = self.queues.heads()
        stats.heads = len(heads)
        if not heads:
            # Still flush gauges for CQs touched by out-of-cycle evictions
            # or finishes, so an idle scheduler doesn't report stale usage.
            # Pending counts need no snapshot; build one only when usage
            # gauges actually have CQs to report. Empty cycles record no
            # ledger row either — the ledger is a record of work done,
            # and a serve loop's idle polls would churn the ring.
            for cq_name, counts in (
                    self.queues.drain_dirty_pending_counts().items()):
                metrics.report_pending_workloads(cq_name, *counts)
            if self._cycle_touched_cqs:
                self._flush_metrics(build_snapshot(self.store), entries=[])
            self._persist_flush()
            return stats

        # per-phase walls for the cycle ledger row — the same phase
        # vocabulary the Tracer spans use, measured on perf_counter so
        # a ledger row and a Chrome-trace span of the same cycle agree
        p0 = time.perf_counter()
        snapshot = build_snapshot(self.store)
        t_snapshot = time.perf_counter() - p0

        p1 = time.perf_counter()
        entries, inadmissible = self._nominate(heads, snapshot, now)
        t_nominate = time.perf_counter() - p1
        stats.inadmissible = len(inadmissible)
        for e in inadmissible:
            # flight recorder: the nomination-stage rejection reason
            # (inactive/missing CQ, namespace mismatch) is the answer to
            # "why is my job still pending?" for these workloads
            self._cycle_skip_slugs["inadmissible"] = (
                self._cycle_skip_slugs.get("inadmissible", 0) + 1)
            obs.recorder.record(
                obs.SKIPPED, e.info.key, cycle=self.cycle_count,
                cluster_queue=e.info.cluster_queue,
                reason=e.inadmissible_msg, reason_slug="inadmissible")

        p2 = time.perf_counter()
        iterator = self._make_iterator(entries, snapshot)
        preempted_workloads: dict[str, WorkloadInfo] = {}
        while iterator.has_next():
            self._process_entry(iterator.pop(), snapshot,
                                preempted_workloads, stats, now)

        for e in entries:
            if e.status not in (ASSUMED, EVICTED):
                self._requeue_and_update(e)
        for e in inadmissible:
            self._requeue_and_update(e)
        t_entries = time.perf_counter() - p2

        stats.duration_s = self.clock() - start
        if stats.admitted:
            # the adaptive solver gate compares against the drain's
            # time.monotonic wall — measure in the same time domain
            # (self.clock may be injected/simulated)
            per_adm = (time.monotonic() - wall0) / stats.admitted
            self._host_s_per_adm = (
                per_adm if self._host_s_per_adm is None
                else 0.7 * self._host_s_per_adm + 0.3 * per_adm)
        self.log.info("cycle finished", v=2, cycle=stats.cycle,
                      heads=stats.heads, admitted=stats.admitted,
                      preempted=stats.preempted,
                      inadmissible=stats.inadmissible,
                      duration_s=round(stats.duration_s, 6))
        self.admission_attempt_durations.append(stats.duration_s)
        result = (metrics.CycleResult.SUCCESS if stats.admitted or stats.preempted
                  else metrics.CycleResult.INADMISSIBLE)
        metrics.observe_admission_attempt(result, stats.duration_s)
        p3 = time.perf_counter()
        self._flush_metrics(snapshot, entries)
        self._persist_flush()
        ledger = obs.cycle_ledger
        if ledger.enabled:
            ledger.record(
                self.cycle_count, obs.HOST_CYCLE,
                breaker=obs.breaker_state_name(),
                duration_s=stats.duration_s,
                phases={"snapshot": round(t_snapshot, 6),
                        "nominate": round(t_nominate, 6),
                        "entries": round(t_entries, 6),
                        "flush": round(time.perf_counter() - p3, 6)},
                heads=stats.heads, admitted=stats.admitted,
                preempted=stats.preempted, skipped=stats.skipped,
                inadmissible=stats.inadmissible,
                skip_slugs=dict(self._cycle_skip_slugs))
        return stats

    def _persist_flush(self) -> None:
        """Cycle-end durability barrier: the WAL's group commit lands
        every record this cycle produced (docs/DURABILITY.md), and the
        checkpoint cadence gets its periodic look."""
        p = getattr(self.store, "persistence", None)
        if p is not None:
            p.flush()

    def _flush_metrics(self, snapshot: Snapshot, entries: list[Entry]) -> None:
        for cq_name, counts in self.queues.drain_dirty_pending_counts().items():
            metrics.report_pending_workloads(cq_name, *counts)
        touched = {e.info.cluster_queue for e in entries}
        touched.update(self._cycle_touched_cqs)
        self._cycle_touched_cqs.clear()
        self._report_snapshot_metrics(snapshot, touched)

    def _report_snapshot_metrics(self, snapshot: Snapshot,
                                 touched: set[str]) -> None:
        """Per-CQ usage/weighted-share gauges from the post-cycle snapshot,
        limited to CQs the cycle touched — the hot loop must not sweep all
        1k CQs (reference: cache usage reporting, metrics.go:733-830)."""
        touched_cohorts: set = set()
        for name in touched:
            cq = snapshot.cluster_queues.get(name)
            if cq is None:
                continue
            metrics.report_cluster_queue_usage(
                cq.name, cq.node.usage, spec_frs=cq.spec.flavor_resources())
            metrics.reserving_active_workloads.set(
                cq.name, value=len(cq.workloads))
            if self.enable_fair_sharing:
                drs = cq.dominant_resource_share()
                metrics.cluster_queue_weighted_share.set(
                    cq.name, value=drs.rounded_weighted_share())
            # per-LocalQueue usage/active gauges (local_queue_* series;
            # one pass over the CQ's workloads, gated like the rest of
            # the LQ family)
            if metrics._lq_metrics_enabled():
                by_lq: dict[tuple[str, str], dict] = {}
                active_by_lq: dict[tuple[str, str], int] = {}
                admitted_by_lq: dict[tuple[str, str], int] = {}
                for info in cq.workloads.values():
                    lqk = (info.obj.queue_name, info.obj.namespace)
                    active_by_lq[lqk] = active_by_lq.get(lqk, 0) + 1
                    if info.obj.is_admitted:
                        admitted_by_lq[lqk] = admitted_by_lq.get(lqk, 0) + 1
                    agg = by_lq.setdefault(lqk, {})
                    for fr, q in info.usage().items():
                        agg[fr] = agg.get(fr, 0) + q
                # zero-fill LQ samples whose last workload left this CQ
                # so drained queues report 0 instead of a stale value
                prev = self._lq_reported.get(name, set())
                stale = prev - set(active_by_lq)
                for lq, ns in stale:
                    metrics.local_queue_reserving_active_workloads.set(
                        lq, ns, value=0)
                    metrics.local_queue_admitted_active_workloads.set(
                        lq, ns, value=0)
                self._lq_reported[name] = set(active_by_lq)
                for (lq, ns), agg in by_lq.items():
                    metrics.local_queue_resource_usage.replace_prefix(
                        (lq, ns), {fr: q for fr, q in agg.items()})
                    metrics.local_queue_resource_reservation.replace_prefix(
                        (lq, ns), {fr: q for fr, q in agg.items()})
                for lq, ns in stale:
                    metrics.local_queue_resource_usage.replace_prefix(
                        (lq, ns), {})
                    metrics.local_queue_resource_reservation.replace_prefix(
                        (lq, ns), {})
                for (lq, ns), n in active_by_lq.items():
                    metrics.local_queue_reserving_active_workloads.set(
                        lq, ns, value=n)
                    metrics.local_queue_admitted_active_workloads.set(
                        lq, ns, value=admitted_by_lq.get((lq, ns), 0))
            # pending requested quantity per resource (totals maintained
            # incrementally by the queue — never walks the backlog)
            q = self.queues.queues.get(name)
            if q is not None:
                metrics.cluster_queue_resource_pending.replace_prefix(
                    (name,),
                    {(r,): v for r, v in q.pending_totals.items()})
            if cq.has_parent():
                touched_cohorts.update(cq.path_parent_to_root())
        # cohort subtree gauges (metrics.go cohort_subtree_*)
        for node in touched_cohorts:
            for (flavor, resource), v in node.node.subtree_quota.items():
                metrics.cohort_subtree_quota.set(
                    node.name, flavor, resource, value=v)
            for (flavor, resource), v in node.node.usage.items():
                metrics.cohort_subtree_resource_reservations.set(
                    node.name, flavor, resource, value=v)
            n_admitted = sum(
                len(c.workloads) for c in node.subtree_cluster_queues())
            metrics.cohort_subtree_admitted_active_workloads.set(
                node.name, value=n_admitted)

    def _solver_engine(self):
        if self.solver is None:
            return None
        if self.solver == "auto":
            if self._solver_instance is None:
                import os

                from kueue_oss_tpu.solver.engine import SolverEngine

                # solver_config.socket_path (programmatic, wins) or the
                # KUEUE_SOLVER_SOCKET env fallback routes the auto
                # engine's solves through the sidecar; the engine's
                # circuit breaker then governs remote health (a tripped
                # breaker degrades drains to the host cycle until a
                # probe succeeds)
                cfg = self.solver_config
                remote = None
                health = None
                sock = (cfg.socket_path
                        if cfg is not None and cfg.socket_path
                        else os.environ.get("KUEUE_SOLVER_SOCKET"))
                if sock:
                    from kueue_oss_tpu.solver.service import SolverClient

                    if cfg is not None:
                        import dataclasses

                        remote = SolverClient.from_config(
                            dataclasses.replace(cfg, socket_path=sock))
                    else:
                        remote = SolverClient(sock)
                if cfg is not None:
                    from kueue_oss_tpu.solver.resilience import (
                        SolverHealth,
                    )

                    health = SolverHealth(
                        cfg.breaker_failure_threshold,
                        cfg.breaker_cooldown_seconds)
                self._solver_instance = SolverEngine(
                    self.store, self.queues, scheduler=self,
                    enable_fair_sharing=self.enable_fair_sharing,
                    remote=remote, health=health,
                    mesh_mode=(cfg.mesh if cfg is not None else None))
                if cfg is not None:
                    # relaxed fast-path arm knobs (solver/relax.py)
                    eng = self._solver_instance
                    eng.relax_enabled = cfg.relax_enabled
                    eng.relax_min_workloads = cfg.relax_min_workloads
                    eng.relax_audit_every = cfg.relax_audit_every
                    eng.relax_iters = cfg.relax_iters
                    eng.relax_support_threshold = (
                        cfg.relax_support_threshold)
                    eng.relax_retry_cooldown_s = (
                        cfg.relax_retry_cooldown_seconds)
            self._ensure_streaming(self._solver_instance)
            return self._solver_instance
        self._ensure_streaming(self.solver)
        return self.solver

    def _streaming_on(self) -> bool:
        """Whether streaming is enabled: truthy value, AND — for a
        StreamingConfig — its ``enabled`` master switch."""
        cfg = self.streaming
        if not cfg:
            return False
        return cfg is True or getattr(cfg, "enabled", True)

    def _ensure_streaming(self, engine) -> None:
        """Wire the StreamingAdmitter onto a freshly resolved engine
        (idempotent; also the path that arms fences on the engine's
        full-solve boundaries via engine.streaming)."""
        if (not self._streaming_on() or engine is None
                or self._streaming_instance is not None):
            return
        from kueue_oss_tpu.scheduler.streaming import StreamingAdmitter

        cfg = self.streaming
        kwargs = {}
        if cfg is not True and cfg is not None:
            kwargs["max_batch"] = getattr(cfg, "max_batch", 512)
        self._streaming_instance = StreamingAdmitter(
            self.store, self.queues, engine, **kwargs)
        engine.streaming = self._streaming_instance

    def _streaming_admitter(self):
        """The lazily built StreamingAdmitter, or None (streaming off
        or no solver backend configured)."""
        if not self._streaming_on():
            return None
        if self._streaming_instance is None:
            self._ensure_streaming(self._solver_engine())
        return self._streaming_instance

    def _streaming_max_gap(self) -> float:
        cfg = self.streaming
        if cfg is True or cfg is None:
            return 1.0
        return getattr(cfg, "max_cycle_gap_seconds", 1.0)

    def _streaming_watch_driven(self) -> bool:
        """Whether serve() runs the watch-driven drain worker (on by
        default with streaming): arrivals signal the worker straight
        from the store watch stream, so micro-drain latency stays
        event-bound even while the serve loop sleeps on its SlowDown
        backoff or poll tick."""
        if not self._streaming_on():
            return False
        cfg = self.streaming
        if cfg is True:
            return True
        return getattr(cfg, "watch_driven", True)

    def _watch_drain_loop(self, sa, wake, stop, clock) -> None:
        """Watch-driven drain worker: blocks on the arrival signal
        (set by the admitter's store-watch classifier), coalesces
        whatever burst accumulated while a drain ran, and drains
        under the cycle lock. Full-solve requests are deferred to the
        serve loop — the worker only ever runs micro-drains."""
        while not stop.is_set():
            if not wake.wait(timeout=0.2):
                continue
            wake.clear()
            if stop.is_set():
                return
            n = sa.take_arrival_signals()
            if n <= 0:
                continue
            if n > 1:
                # burst backpressure: n arrival signals collapsed
                # into this one drain
                metrics.stream_demotions_total.inc(
                    "watch_coalesced", by=float(n - 1))
            with self._cycle_mu:
                sa.drain(clock())
            if sa.full_solve_pending:
                # spec edit observed mid-window: the HEAVY cycle is
                # the serve loop's job — nudge its condition wait
                self.queues.wakeup()

    def micro_drain(self, now: Optional[float] = None):
        """One streaming micro-batch: admit in-order arrivals for
        every uncontended fast-path CQ sub-cycle (between full
        solves). Returns the MicroDrainResult, or None when streaming
        is off/unarmed."""
        sa = self._streaming_admitter()
        if sa is None:
            return None
        with self._cycle_mu:
            return sa.drain(now if now is not None else self.clock())

    def _solver_drain(self, now: Optional[float]) -> bool:
        """Drain the backlog on-device when the solver supports it.

        Returns True if a drain ran. Unsupported shapes (TAS podset
        groups, admission-scope CQs, weighted fair sharing, oversized
        quantities) fall through to the host cycle loop.
        """
        engine = self._solver_engine()
        if engine is None or not self.queues.has_pending():
            return False
        from kueue_oss_tpu.solver.resilience import SolverUnavailable
        from kueue_oss_tpu.solver.tensors import UnsupportedProblem

        if not engine.supported():
            self.queues.materialize_stale_all()
            return False
        if self.solver_min_backlog > 0:
            # cheap heap-count heuristic (TAS entries may overcount; a
            # TAS-only export returns empty and costs ~nothing). Stale
            # parked entries count — they are owed a retry. Lazy
            # capacity-freed flushing engages only while the solver is
            # draining floods (eager flushes there are O(parked) per
            # finish — millions of heap pushes per run); at trickle
            # scale the host path runs with exact eager semantics.
            backlog = self.queues.solver_backlog_count()
            if backlog < self.solver_min_backlog:
                if self.queues.lazy_flush:
                    self.queues.set_lazy_flush(False)  # materializes
                # flood fully processed: the next crossing is a fresh
                # flood and re-engages the device drain unconditionally
                self._solver_drained_once = False
                return False
            if self._solver_drained_once and self.solver_reengage_fraction:
                # benefit gate: a re-drain re-walks the parked backlog,
                # so it must beat the host cycles it would replace. Once
                # both cost estimates exist the gate is ADAPTIVE — the
                # measured drain wall vs the host's per-admission cost
                # times the batch plausibly admittable now — so the same
                # default routes churn to the host on a slow backend
                # (1-core XLA:CPU: drains cost seconds) and to the
                # device on a fast one (local TPU: drains cost
                # milliseconds). Until estimates exist, fall back to the
                # flood-sized-batch rule.
                arrivals = (self.queues.new_pending_total
                            - self._solver_arrivals_mark)
                batch = min(self._solver_freed_since_drain + arrivals,
                            backlog)
                freed = self._solver_freed_since_drain
                if (self._drain_cost_ema is not None
                        and self._host_s_per_adm is not None):
                    # drain wall scales ~linearly with the exported
                    # backlog (per-round vmaps are O(W)), so predict
                    # from the per-workload EMA at the CURRENT size —
                    # a flat EMA lags badly while a flood ramps up.
                    # Arrival-assisted attempts pay the unproductive-
                    # drain backoff multiplier (a blocked head plus an
                    # arrival trickle must not re-drain at a fixed
                    # threshold forever); freed capacity alone never
                    # does.
                    predicted = self._drain_cost_ema * backlog
                    freed_ok = freed * self._host_s_per_adm >= predicted
                    arrivals_ok = (batch * self._host_s_per_adm
                                   >= predicted
                                   * self._solver_arrival_mult)
                else:
                    need = max(self.solver_min_backlog,
                               int(self.solver_reengage_fraction
                                   * backlog))
                    freed_ok = freed >= need
                    arrivals_ok = (arrivals
                                   >= need * self._solver_arrival_mult)
                if not (freed_ok or arrivals_ok):
                    # an over-estimated drain cost must not latch the
                    # gate shut (the EMA only resamples when a drain
                    # RUNS — e.g. a first-drain XLA compile or a GC
                    # pause inflates it): decay it slightly per skipped
                    # evaluation so outliers erode and a probe drain
                    # eventually re-measures
                    if self._drain_cost_ema is not None:
                        self._drain_cost_ema *= 0.99
                    if self.queues.lazy_flush:
                        self.queues.set_lazy_flush(False)
                    return False
                self._solver_drain_trigger = (
                    "freed" if freed_ok else "arrivals")
            if not self.queues.lazy_flush:
                self.queues.set_lazy_flush(True)
        try:
            backlog_now = max(1, self.queues.solver_backlog_count())
            t0 = time.monotonic()
            result = engine.drain(now=now if now is not None else 0.0,
                                  verify=True)
            per_wl = (time.monotonic() - t0) / backlog_now
            if self._drain_cost_ema is None:
                self._drain_cost_ema = per_wl
            else:
                self._drain_cost_ema = (0.7 * self._drain_cost_ema
                                        + 0.3 * per_wl)
        except UnsupportedProblem as e:
            self.queues.materialize_stale_all()
            self._solver_drain_trigger = None
            obs.recorder.record(
                obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE,
                cycle=self.cycle_count + 1, path=obs.SOLVER,
                reason=str(e) or "problem shape unsupported on-device",
                reason_slug="unsupported")
            return False
        except SolverUnavailable as e:
            # backend crashed/hung/returned garbage, or the breaker is
            # open: the admission round completes on the host cycle loop
            # below — never an exception, never a stall past the
            # client's deadline (engine.health un-trips via probes)
            self.queues.materialize_stale_all()
            self._solver_drain_trigger = None
            self.log.info("solver backend unavailable; host-cycle "
                          "fallback", v=1, error=str(e))
            return False
        self._solver_drained_once = True
        self._solver_freed_since_drain = 0
        self._solver_arrivals_mark = self.queues.new_pending_total
        if getattr(self, "_solver_drain_trigger", None) == "arrivals":
            if result.admitted < self.solver_min_backlog // 4:
                self._solver_arrival_mult = min(
                    64, self._solver_arrival_mult * 2)
            else:
                self._solver_arrival_mult = 1
        elif result.admitted:
            self._solver_arrival_mult = 1
        self._solver_drain_trigger = None
        for key in result.admitted_keys:
            wl = self.store.workloads.get(key)
            if wl is not None and wl.status.admission is not None:
                cq = wl.status.admission.cluster_queue
                self.admitted_total[cq] = self.admitted_total.get(cq, 0) + 1
                self._cycle_touched_cqs.add(cq)
        # progress = the plan changed something; a no-op drain (e.g. a
        # blocked StrictFIFO head holding the whole backlog) must NOT
        # reset serve()'s SlowDown backoff, or the loop would hot-spin
        # full export+solve cycles until capacity frees
        return bool(result.admitted or result.evicted)

    def run_until_quiet(self, max_cycles: int = 10_000,
                        now: Optional[float] = None,
                        tick: float = 0.0) -> int:
        """Run cycles until the pending state stops changing.

        With a solver backend configured, the backlog first drains through
        the TPU kernel (one batched invocation replacing many host
        cycles); host cycles then mop up anything the solver could not
        model or verify. ``tick`` advances the injected clock per cycle
        (a frozen clock collapses eviction/admission timestamps into
        ties, which real deployments never see).
        """
        cycles = 0
        prev_probe = None
        while cycles < max_cycles:
            self._solver_drain(None if now is None
                               else now + cycles * tick)
            stalled = False
            while cycles < max_cycles:
                pre = self._queue_fingerprint()
                n = None if now is None else now + cycles * tick
                stats = self.schedule(now=n)
                cycles += 1
                if stats.heads == 0:
                    break
                if (stats.admitted == 0 and stats.preempted == 0
                        and self._queue_fingerprint() == pre):
                    stalled = True
                    break
            # mid-loop evictions may have lazily flushed parked entries
            # (stale); loop back so the solver (or the host, via
            # materialization) retries them before declaring quiescence
            if stalled or not self.queues.any_stale():
                break
            # cross-iteration progress probe: if a full drain+cycle pass
            # changed neither queue membership nor the retryable backlog,
            # further passes are no-ops — quiesce instead of burning
            # export+solve until max_cycles
            probe = (self._queue_fingerprint(),
                     self.queues.solver_backlog_count())
            if probe == prev_probe:
                break
            prev_probe = probe
        return cycles

    def _queue_fingerprint(self):
        return self.queues.membership_fingerprint()

    def serve(self, stop, poll: float = 0.05,
              clock=None, backoff=None) -> int:
        """Event-driven scheduler loop for threaded deployments: block on
        the queue manager's condition until pending work arrives, run a
        cycle, repeat until `stop` is set (the reference scheduler
        blocks in manager.Heads() the same way, and wraps the cycle in
        untilWithBackoff). A cycle that makes NO progress — heads that
        immediately requeue (StrictFIFO blocked head, pending
        preemption) keep the queues non-empty — signals SlowDown: the
        loop sleeps on an exponential backoff instead of spinning, and
        any queue event resets it. Returns cycles run."""
        import time as _time

        from kueue_oss_tpu.util.primitives import Backoff

        from kueue_oss_tpu import features

        clock = clock or _time.monotonic
        backoff = backoff or Backoff(initial=0.002, cap=max(poll, 0.002),
                                     factor=2.0)
        # Watch-driven micro-drains: arrivals signal a dedicated
        # drain worker straight from the store watch stream, so the
        # sub-cycle path stays event-bound even while this loop
        # sleeps (poll timeout, SlowDown backoff). The worker and
        # this loop serialize through _cycle_mu.
        sa_watch = (self._streaming_admitter()
                    if self._streaming_watch_driven() else None)
        watch_wake = None
        watch_thread = None
        if sa_watch is not None:
            watch_wake = threading.Event()
            sa_watch.set_arrival_notifier(watch_wake.set)
            watch_thread = threading.Thread(
                target=self._watch_drain_loop,
                args=(sa_watch, watch_wake, stop, clock),
                name="stream-watch-drain", daemon=True)
            watch_thread.start()
        try:
            return self._serve_loop(stop, poll, clock, backoff, features)
        finally:
            if sa_watch is not None:
                sa_watch.set_arrival_notifier(None)
                watch_wake.set()
                watch_thread.join(timeout=1.0)

    def _serve_loop(self, stop, poll, clock, backoff, features) -> int:
        # requeue sweeps batch like the reference requeuer
        # (inadmissible_workloads.go:37-47): 1s normally, 10s under
        # SchedulerLongRequeueInterval (re-read per tick so live gate
        # flips take effect like every other gate)
        last_sweep = -1e18
        cycles = 0
        idle_rounds = 0
        while not stop.is_set():
            if not self.queues.wait_for_pending(timeout=poll):
                # timeout: re-check stop, serve due requeues/second pass
                # on the batch cadence
                now_c = clock()
                requeue_period = (10.0 if features.enabled(
                    "SchedulerLongRequeueInterval") else 1.0)
                if now_c - last_sweep >= requeue_period:
                    last_sweep = now_c
                    self.requeue_due(now_c)
                # a spec edit (quota/flavor change) landing while the
                # queues are idle must not sit fenced until the next
                # arrival: drain() observes the spec-gen bump even
                # with nothing pending, and the requested full solve
                # runs NOW so capacity changes propagate immediately
                sa = self._streaming_admitter()
                if sa is not None:
                    with self._cycle_mu:
                        sa.drain(now_c)
                        if sa.consume_full_solve_request():
                            metrics.stream_spec_solves_total.inc()
                            stats = self.schedule(now=clock())
                            self._last_full_cycle_wall = clock()
                            cycles += 1
                            if stats.admitted or stats.preempted:
                                idle_rounds = 0
                continue
            # Streaming fast path (scheduler/streaming.py): between
            # full solves, in-order arrivals to uncontended CQs admit
            # sub-cycle; when the micro-batch resolved everything
            # pending, the heavy cycle is skipped — p50 time-to-admit
            # decouples from the full-solve cadence. Host cycles still
            # run at least every max_cycle_gap (SLO windows, requeue
            # backoffs, metric flushes) and whenever fenced work waits.
            skip_heavy = False
            with self._cycle_mu:
                micro_admitted = 0
                sa = self._streaming_admitter()
                if sa is not None:
                    now_c = clock()
                    micro = sa.drain(now_c)
                    micro_admitted = micro.admitted
                    if sa.consume_full_solve_request():
                        # spec edit observed mid-window: fall through
                        # to the full cycle right now — never skip it
                        metrics.stream_spec_solves_total.inc()
                    elif ((micro.admitted or micro.parked)
                            and not self.queues.has_pending()
                            and (now_c - self._last_full_cycle_wall
                                 < self._streaming_max_gap())):
                        skip_heavy = True
                if not skip_heavy:
                    # Flood-to-solver routing (run_until_quiet
                    # parity): a backlog past solver_min_backlog
                    # drains through the device kernel in one batched
                    # invocation; the host cycle below mops up the
                    # trickle and anything the solver could not model
                    # or verify.
                    drained = (self._solver_drain(clock())
                               if self.solver else False)
                    pre = self._queue_fingerprint()
                    stats = self.schedule(now=clock())
                    self._last_full_cycle_wall = clock()
                    cycles += 1
            if skip_heavy:
                idle_rounds = 0
                continue
            if (drained or micro_admitted or stats.admitted
                    or stats.preempted
                    or self._queue_fingerprint() != pre):
                idle_rounds = 0  # KeepGoing
            else:
                idle_rounds += 1  # SlowDown
                stop.wait(backoff.wait_time(idle_rounds))
        return cycles

    # ------------------------------------------------------------------
    # Nomination
    # ------------------------------------------------------------------

    def _nominate(self, heads: list[WorkloadInfo], snapshot: Snapshot,
                  now: float) -> tuple[list[Entry], list[Entry]]:
        entries: list[Entry] = []
        inadmissible: list[Entry] = []
        for info in heads:
            e = Entry(info=info)
            e.cq_snapshot = snapshot.cluster_queue(info.cluster_queue)
            if info.cluster_queue in snapshot.inactive_cluster_queues:
                e.inadmissible_msg = (
                    f"ClusterQueue {info.cluster_queue} is inactive")
            elif e.cq_snapshot is None:
                e.inadmissible_msg = (
                    f"ClusterQueue {info.cluster_queue} not found")
            elif not self._namespace_matches(e.cq_snapshot, info.obj):
                e.inadmissible_msg = (
                    "Workload namespace doesn't match ClusterQueue selector")
                e.requeue_reason = RequeueReason.NAMESPACE_MISMATCH
            else:
                assignment, targets = self._get_assignments(info, snapshot, now)
                e.assignment = assignment
                e.preemption_targets = targets
                e.inadmissible_msg = assignment.message()
                info.last_assignment = assignment.last_state
                entries.append(e)
                continue
            inadmissible.append(e)
        return entries, inadmissible

    def _namespace_matches(self, cq: ClusterQueueSnapshot, wl: Workload) -> bool:
        selector = cq.spec.namespace_selector
        if selector is None:
            return True
        labels = self.store.namespaces.get(wl.namespace, {})
        return all(labels.get(k) == v for k, v in selector.items())

    def _get_assignments(self, info: WorkloadInfo, snapshot: Snapshot,
                         now: float) -> tuple[Assignment, list[Target]]:
        """scheduler.go getInitialAssignments: full fit, else preempt,
        else partial admission. A scaled-up workload slice assigns with the
        replaced slice's usage removed (delta accounting) and carries the
        old slice as a pseudo preemption target (scheduler.go:705)."""
        from kueue_oss_tpu import workloadslicing

        slice_targets, replaced = workloadslicing.replaced_workload_slice(
            info, snapshot)
        if replaced is not None:
            revert = snapshot.simulate_workload_removal([replaced])
            try:
                assignment, targets = self._assign(info, snapshot, now)
            finally:
                revert()
            return assignment, slice_targets + targets
        return self._assign(info, snapshot, now)

    def _assign(self, info: WorkloadInfo, snapshot: Snapshot,
                now: float) -> tuple[Assignment, list[Target]]:
        cq = snapshot.cluster_queue(info.cluster_queue)
        assert cq is not None
        assigner = FlavorAssigner(
            info, cq, snapshot.resource_flavors, oracle=self.preemptor,
            enable_fair_sharing=self.enable_fair_sharing)
        full = assigner.assign()
        mode = full.representative_mode()
        if mode == fa.FIT:
            return full, []
        if mode == fa.PREEMPT:
            targets = self.preemptor.get_targets(info, full, snapshot, now)
            if targets:
                self._update_assignment_for_tas(
                    info, cq, snapshot, full, targets)
                return full, targets

        from kueue_oss_tpu import features

        if (self.enable_partial_admission
                and features.enabled("PartialAdmission")
                and info.can_be_partially_admitted()):
            def probe(counts):
                assignment = assigner.assign(counts)
                m = assignment.representative_mode()
                if m == fa.FIT:
                    return (assignment, []), True
                if m == fa.PREEMPT:
                    t = self.preemptor.get_targets(info, assignment, snapshot, now)
                    if t:
                        return (assignment, t), True
                return None, False

            reducer = PodSetReducer(info.obj.podsets, probe)
            result, found = reducer.search()
            if found:
                if result[1]:
                    self._update_assignment_for_tas(
                        info, cq, snapshot, result[0], result[1])
                return result
        return full, []

    def _update_assignment_for_tas(self, info: WorkloadInfo,
                                   cq: ClusterQueueSnapshot,
                                   snapshot: Snapshot,
                                   assignment: Assignment,
                                   targets: list[Target]) -> None:
        """Recompute topology assignments assuming the preemption victims
        are gone (scheduler.go updateAssignmentForTAS, :759-783)."""
        if assignment.representative_mode() != fa.PREEMPT:
            return
        if not any(fa.is_tas_requested(ps, cq) for ps in info.obj.podsets):
            return
        if info.obj.status.unhealthy_nodes:
            return
        tas_requests = fa.workload_topology_requests(info, cq, assignment)
        if not tas_requests:
            return
        revert = snapshot.simulate_workload_removal(
            [t.info for t in targets])
        try:
            result = cq.find_topology_assignments_for_workload(tas_requests)
        finally:
            revert()
        fa.update_for_tas_result(assignment, result)

    # ------------------------------------------------------------------
    # Iterators
    # ------------------------------------------------------------------

    def _make_iterator(self, entries: list[Entry], snapshot: Snapshot):
        if self.enable_fair_sharing:
            return _FairSharingIterator(entries)
        return _ClassicalIterator(entries)

    # ------------------------------------------------------------------
    # Entry processing
    # ------------------------------------------------------------------

    def _record_skip(self, e: Entry, slug: str,
                     detail: Optional[dict] = None) -> None:
        """Flight-recorder emission for a skipped entry: the bounded slug
        feeds the per-reason counters, the free-form inadmissible_msg
        (the flavor assigner's no-fit text included) survives verbatim."""
        self._cycle_skip_slugs[slug] = (
            self._cycle_skip_slugs.get(slug, 0) + 1)
        obs.recorder.record(
            obs.SKIPPED, e.info.key, cycle=self.cycle_count,
            cluster_queue=e.info.cluster_queue,
            reason=e.inadmissible_msg, reason_slug=slug, detail=detail)

    def _process_entry(self, e: Entry, snapshot: Snapshot,
                       preempted_workloads: dict[str, WorkloadInfo],
                       stats: CycleStats, now: float) -> None:
        from kueue_oss_tpu import features

        cq = e.cq_snapshot
        assert cq is not None

        is_variant = (features.enabled("ConcurrentAdmission")
                      and e.info.obj.parent_workload is not None)
        if is_variant and self._find_admitted_sibling(
                e.info, cq, less_favorable=False) is not None:
            # A more favorable flavor already won (scheduler.go:386-392).
            e.status = SKIPPED
            e.inadmissible_msg = "A more favorable variant is already admitted"
            stats.skipped += 1
            self._record_skip(e, "variant_raced")
            return

        mode = e.assignment.representative_mode()
        if mode == fa.NO_FIT:
            stats.skipped += 1
            # the flavor assigner's human-readable no-fit reason
            # (inadmissible_msg) is kept, not discarded with the entry
            self._record_skip(e, "no_fit", detail=e.assignment.skip_detail())
            return

        if mode == fa.PREEMPT and not e.preemption_targets:
            # Preemption is needed but no targets: reserve the capacity we
            # are entitled to so lower entries can't squat on it
            # (scheduler.go reserveCapacityForUnreclaimablePreempt).
            cq.add_usage(self._quota_to_reserve(e, cq))
            stats.skipped += 1
            self._record_skip(e, "no_candidates")
            return

        if (mode == fa.PREEMPT
                and features.enabled("MultiKueueOrchestratedPreemption")
                and e.info.obj.preemption_gates):
            # Orchestrated preemption (KEP-8303): a gated workload must not
            # preempt until MultiKueue opens the gate (scheduler.go:411-416).
            e.status = SKIPPED
            e.inadmissible_msg = "Workload requires preemption, but it's gated"
            stats.skipped += 1
            self._record_skip(e, "preemption_gated")
            return

        # One cohort-conflicting admission per cycle: skip overlapping targets.
        if any(t.info.key in preempted_workloads for t in e.preemption_targets):
            e.status = SKIPPED
            e.inadmissible_msg = (
                "Workload has overlapping preemption targets with another workload")
            stats.skipped += 1
            self._record_skip(e, "cohort_conflict")
            return

        # In-flight preemption guard (preemption.go:207-221 + the
        # expectations store): while a previously issued plan's evictions
        # are still unobserved, don't issue a second plan for the same
        # preemptor, and don't target workloads another plan already
        # expects to evict.
        if mode == fa.PREEMPT and e.preemption_targets:
            pending = self.preemption_expectations.pending_uids()
            if not self.preemption_expectations.satisfied(e.info.key) or any(
                    t.info.obj.uid in pending for t in e.preemption_targets):
                e.status = SKIPPED
                e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                e.inadmissible_msg = (
                    "Workload is waiting for previously issued preemptions")
                stats.skipped += 1
                self._record_skip(e, "pending_preemption")
                return

        usage = e.assignment_usage()
        if not self._fits(snapshot, cq, usage, preempted_workloads,
                          e.preemption_targets, e):
            e.status = SKIPPED
            e.inadmissible_msg = (
                "Workload no longer fits after processing another workload")
            stats.skipped += 1
            self._record_skip(e, "lost_race")
            return
        for t in e.preemption_targets:
            preempted_workloads[t.info.key] = t.info
        cq.add_usage(usage)

        # The old workload slice rides the target list for accounting but
        # is finished (replaced), never evicted (scheduler.go:437-454).
        from kueue_oss_tpu import workloadslicing

        e.preemption_targets, old_slice = (
            workloadslicing.find_replaced_slice_target(
                e.info.obj, e.preemption_targets))

        if mode == fa.PREEMPT:
            self._issue_preemptions(e, now)
            stats.preempted += len(e.preemption_targets)
            return

        if old_slice is not None:
            workloadslicing.finish_slice(
                self.store, self, old_slice.info.obj,
                workloadslicing.REASON_SLICE_REPLACED,
                f"Replaced to accommodate scaled-up slice {e.info.key}",
                now)
            snapshot.remove_workload(old_slice.info)
            metrics.replaced_workload_slices_total.inc(e.info.cluster_queue)

        if is_variant:
            sibling = self._find_admitted_sibling(
                e.info, cq, less_favorable=True)
            if sibling is not None:
                # Migration up the flavor order: evict the less favorable
                # sibling now; this variant re-attempts next cycle with the
                # freed quota (scheduler.go issueMigration, :488).
                self.evict_workload(
                    sibling.key, reason="Migrated",
                    message=f"Migrated to more favorable variant {e.info.key}",
                    now=now)
                e.inadmissible_msg = (
                    "Pending the migration eviction of a less favorable "
                    "variant")
                e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                # Reset the flavor cursor like the preemption path: the
                # next attempt must start from the best flavor again.
                e.info.last_assignment = None
                stats.preempted += 1
                obs.recorder.record(
                    obs.NOMINATED, e.info.key, cycle=self.cycle_count,
                    cluster_queue=e.info.cluster_queue,
                    reason=e.inadmissible_msg,
                    reason_slug="pending_migration",
                    detail={"migrated_sibling": sibling.key})
                return

        # Delayed topology assignment: on a CQ gated by admission checks
        # the topology is computed in a second pass after the checks turn
        # Ready (provisioned capacity may change the tree), so the TAS
        # usage must not be assumed now (KEP-2724 delayed assignment).
        if not self._delays_topology(e):
            self._assume_tas_usage(e, snapshot)
        e.status = NOMINATED
        self._admit(e, now)
        stats.admitted += 1

    @staticmethod
    def _delays_topology(e: Entry) -> bool:
        cq = e.cq_snapshot
        return (cq is not None and bool(cq.spec.admission_checks)
                and any(psa.topology_assignment is not None
                        for psa in e.assignment.podsets))

    def _find_admitted_sibling(self, info: WorkloadInfo,
                               cq: ClusterQueueSnapshot,
                               less_favorable: bool) -> Optional[WorkloadInfo]:
        """An admitted variant of the same parent on a (less/more) favorable
        flavor — favorability is the flavor's index in the CQ's first
        resource group (scheduler.go findAdmittedSibling, :1111-1187)."""
        from kueue_oss_tpu.controllers.concurrent_admission import (
            flavor_order_of,
        )

        parent = info.obj.parent_workload
        if parent is None or not cq.spec.resource_groups:
            return None
        order = flavor_order_of(cq.spec)
        my_idx = order.get(info.obj.allowed_flavor or "")
        if my_idx is None:
            return None
        for other in cq.workloads.values():
            obj = other.obj
            if obj.uid == info.obj.uid or obj.parent_workload != parent:
                continue
            if not obj.is_admitted:
                continue
            other_idx = order.get(obj.allowed_flavor or "")
            if other_idx is None:
                continue
            if (other_idx > my_idx) == less_favorable and other_idx != my_idx:
                return other
        return None

    @staticmethod
    def _assume_tas_usage(e: Entry, snapshot: Snapshot) -> None:
        """Charge the entry's topology assignment to the TAS snapshots so
        later entries in this cycle see the domain usage (mirrors the
        reference's assume path covering TAS usage in the cache)."""
        podsets = {ps.name: ps for ps in e.info.obj.podsets}
        for psa in e.assignment.podsets:
            ta = psa.topology_assignment
            if ta is None:
                continue
            flavor = next(
                (rec.name for rec in psa.flavors.values()
                 if rec.name in snapshot.tas_flavors), None)
            if flavor is None:
                continue
            ps = podsets.get(psa.name)
            per_pod = (effective_per_pod_requests(ps, e.info.obj.namespace)
                       if ps is not None else {})
            for dom in ta.domains:
                snapshot.tas_flavors[flavor].add_tas_usage(
                    dom.values, per_pod, dom.count)

    @staticmethod
    def _fits(snapshot: Snapshot, cq: ClusterQueueSnapshot, usage,
              preempted_workloads: dict[str, WorkloadInfo],
              targets: list[Target], e: Entry) -> bool:
        infos = list(preempted_workloads.values()) + [t.info for t in targets]
        revert = snapshot.simulate_workload_removal(infos)
        try:
            return cq.fits(usage) and Scheduler._tas_fits(e, snapshot)
        finally:
            revert()

    @staticmethod
    def _tas_fits(e: Entry, snapshot: Snapshot) -> bool:
        """Re-validate the entry's topology assignment against current
        domain usage: earlier admissions in this cycle charged the TAS
        snapshots (_assume_tas_usage), which can invalidate a placement
        computed during nomination."""
        if e.info.obj.is_quota_reserved:
            return True
        podsets = {ps.name: ps for ps in e.info.obj.podsets}
        # Accumulate the whole entry's demand per (flavor, leaf) first: a
        # multi-podset workload (leader+workers) or several domains landing
        # on the same leaf must be checked jointly, not one domain at a time.
        demand: dict[tuple[str, tuple[str, ...]], dict[str, int]] = {}
        for psa in e.assignment.podsets:
            ta = psa.topology_assignment
            if ta is None:
                continue
            flavor = next(
                (rec.name for rec in psa.flavors.values()
                 if rec.name in snapshot.tas_flavors), None)
            if flavor is None:
                continue
            ps = podsets.get(psa.name)
            per_pod = (effective_per_pod_requests(ps, e.info.obj.namespace)
                       if ps is not None else {})
            for dom in ta.domains:
                bucket = demand.setdefault((flavor, tuple(dom.values)), {})
                for r, q in per_pod.items():
                    bucket[r] = bucket.get(r, 0) + q * dom.count
                bucket["pods"] = bucket.get("pods", 0) + dom.count
        for (flavor, values), need in demand.items():
            remaining = snapshot.tas_flavors[flavor].remaining_capacity(values)
            if remaining is None:
                return False
            if any(q > remaining.get(r, 0) for r, q in need.items()):
                return False
        return True

    def _quota_to_reserve(self, e: Entry, cq: ClusterQueueSnapshot):
        """scheduler.go quotaResourcesToReserve for Preempt-mode entries."""
        reserved = {}
        borrowing = e.assignment.borrows() > 0
        for fr, usage in e.assignment.usage_quota.items():
            quota = cq.quota_for(fr)
            if borrowing:
                if quota.borrowing_limit is None:
                    reserved[fr] = usage
                else:
                    reserved[fr] = min(
                        usage,
                        quota.nominal + quota.borrowing_limit
                        - cq.node.usage.get(fr, 0))
            else:
                reserved[fr] = max(
                    0, min(usage, quota.nominal - cq.node.usage.get(fr, 0)))
        return reserved

    # ------------------------------------------------------------------
    # Admission / preemption / eviction
    # ------------------------------------------------------------------

    def _admit(self, e: Entry, now: float) -> None:
        """Reserve quota and write Admission into the store (scheduler.go
        admit/assumeWorkload; store write is synchronous here)."""
        wl = self.store.workloads.get(e.info.key)
        if wl is None:
            e.status = SKIPPED
            e.inadmissible_msg = "Workload vanished from the store"
            self._record_skip(e, "vanished")
            return
        p = getattr(self.store, "persistence", None)
        if p is not None:
            # decision intent BEFORE the store mutation, fenced by the
            # pre-write resource version (the update_workload_if token):
            # recovery matches it to the event at rv+1, or redoes the
            # admission from the recovered state (docs/DURABILITY.md)
            p.intent("admit", wl.key, rv=wl.resource_version,
                     cycle=self.cycle_count,
                     cluster_queue=e.info.cluster_queue)
        delay_tas = self._delays_topology(e)
        admission = Admission(
            cluster_queue=e.info.cluster_queue,
            podset_assignments=[
                PodSetAssignment(
                    name=psa.name,
                    flavors={r: rec.name for r, rec in psa.flavors.items()},
                    resource_usage=dict(psa.requests),
                    count=psa.count,
                    topology_assignment=(
                        None if delay_tas else psa.topology_assignment),
                    delayed_topology_request=(
                        "Pending" if delay_tas
                        and psa.topology_assignment is not None else None),
                )
                for psa in e.assignment.podsets
            ],
        )
        wl.status.admission = admission
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                         reason="QuotaReserved", now=now)
        if wl.is_evicted:
            # Quota reservation supersedes a previous eviction
            # (reference: SetQuotaReservation resets the Evicted condition).
            wl.set_condition(WorkloadConditionType.EVICTED, False,
                             reason="QuotaReserved", now=now)
        # Re-admission clears the backoff gate but keeps the count: the
        # count accumulates across PodsReady eviction/re-admission rounds so
        # RequeuingStrategy.backoffLimitCount can trip; it resets only when
        # pods actually become ready (WorkloadReconciler.set_pods_ready).
        if wl.status.requeue_state is not None:
            wl.status.requeue_state.requeue_at = None
        cq_spec = self.store.cluster_queues[e.info.cluster_queue]
        effective_checks = cq_spec.checks_for_flavors(
            admission.assigned_flavors())
        if effective_checks:
            for name in effective_checks:
                from kueue_oss_tpu.api.types import AdmissionCheckState
                wl.status.admission_checks.setdefault(
                    name, AdmissionCheckState(name=name))
        else:
            wl.set_condition(WorkloadConditionType.ADMITTED, True,
                             reason="Admitted", now=now)
            metrics.admitted_workload(e.info.cluster_queue,
                                      now - wl.creation_time,
                                      lq=wl.queue_name,
                                      namespace=wl.namespace,
                                      exemplar={
                                          "cycle": self.cycle_count,
                                          "workload": wl.key})
        self.store.update_workload(wl)
        e.status = ASSUMED
        events.eventf(wl.key, "Workload", NORMAL, "QuotaReserved",
                      f"Quota reserved in ClusterQueue {e.info.cluster_queue}",
                      now=now)
        if wl.is_admitted:
            events.eventf(wl.key, "Workload", NORMAL, "Admitted",
                          f"Admitted by ClusterQueue {e.info.cluster_queue}",
                          now=now)
        wait_s = max(now - wl.creation_time, 0.0)
        metrics.quota_reserved_workload(e.info.cluster_queue, wait_s,
                                        lq=wl.queue_name,
                                        namespace=wl.namespace,
                                        exemplar={
                                            "cycle": self.cycle_count,
                                            "workload": wl.key})
        # queue-wait SLI: one time-to-admit observation per admission
        # (obs/health.py); the same wait rides the journal detail so
        # the SLO windows can be rebuilt from a restored journal. The
        # priority scope keys by WorkloadPriorityClass name so
        # /api/slo groups by class, not by raw integer.
        pclass = obs.priority_class_of(self.store, wl)
        obs.slo_engine.observe_admission(
            e.info.cluster_queue, wait_s, priority=wl.priority,
            priority_class=pclass, now=now,
            cycle=self.cycle_count, workload=wl.key)
        obs.recorder.record(
            obs.ASSIGNED, wl.key, cycle=self.cycle_count,
            cluster_queue=e.info.cluster_queue,
            reason=f"Quota reserved in ClusterQueue {e.info.cluster_queue}",
            detail={
                "flavors": {psa.name: dict(psa.flavors)
                            for psa in admission.podset_assignments},
                "borrows": e.assignment.borrows(),
                "admitted": wl.is_admitted,
                "waitSeconds": round(wait_s, 3),
                "priority": wl.priority,
                "priorityClass": pclass,
            })
        # cohort subtree admission counters (metrics.go cohort_subtree_*)
        if e.cq_snapshot is not None and e.cq_snapshot.has_parent():
            for node in e.cq_snapshot.path_parent_to_root():
                metrics.cohort_subtree_admitted_workloads_total.inc(
                    node.name)
        self.admitted_total[e.info.cluster_queue] = (
            self.admitted_total.get(e.info.cluster_queue, 0) + 1)
        if (self.queues.afs is not None
                and cq_spec.admission_scope is not None
                and cq_spec.admission_scope.admission_mode
                == "UsageBasedAdmissionFairSharing"):
            # Entry penalty: charge the admitted usage to the LocalQueue
            # immediately (afs/entry_penalties.go).
            by_resource: dict[str, int] = {}
            for (_, r), q in e.assignment.usage_quota.items():
                by_resource[r] = by_resource.get(r, 0) + q
            self.queues.afs.record_admission(
                f"{wl.namespace}/{wl.queue_name}", by_resource, now)

    def _issue_preemptions(self, e: Entry, now: float) -> None:
        # Record expectations before issuing; each synchronous eviction is
        # observed immediately (the reference observes them from the
        # workload watch — expectations/store.go).
        self.preemption_expectations.expect_uids(
            e.info.key, [t.info.obj.uid for t in e.preemption_targets])
        for target in e.preemption_targets:
            self.evict_workload(
                target.info.key,
                reason="Preempted",
                message=f"Preempted to accommodate {e.info.key} due to "
                        f"{target.reason}",
                now=now,
                preemption_reason=target.reason,
            )
        e.inadmissible_msg += (
            f". Pending the preemption of {len(e.preemption_targets)} workload(s)")
        e.requeue_reason = RequeueReason.PENDING_PREEMPTION
        e.info.last_assignment = None
        obs.recorder.record(
            obs.NOMINATED, e.info.key, cycle=self.cycle_count,
            cluster_queue=e.info.cluster_queue,
            reason=e.inadmissible_msg, reason_slug="preempting",
            detail={"targets": [t.info.key for t in e.preemption_targets]})

    def evict_workload(self, key: str, reason: str, message: str, now: float,
                       preemption_reason: str = "",
                       backoff_base_s: Optional[float] = None,
                       backoff_max_s: Optional[float] = None,
                       requeue: bool = True,
                       underlying_cause: str = "",
                       decision_path: str = obs.HOST,
                       decision_cycle: Optional[int] = None) -> None:
        """Evict + finalize: release quota and requeue (the reference splits
        this between the scheduler patch and the Workload controller).

        Requeue semantics follow the reference: preemption/generic evictions
        re-enter the queue immediately, ordered by their eviction timestamp
        (workload.Ordering); ONLY controller-driven PodsReady evictions pass
        an explicit backoff (configuration_types.go RequeuingStrategy) and
        get a RequeueState gate + count. requeue=False skips re-queueing
        entirely (deactivation — the workload cannot re-enter anyway).
        """
        wl = self.store.workloads.get(key)
        if wl is None or wl.is_finished:
            return
        # Resolve the CQ before the admission is cleared: the LQ mapping
        # may be stale/deleted, but quota was released on the admitting CQ.
        cq = (wl.status.admission.cluster_queue
              if wl.status.admission is not None
              else self.store.cluster_queue_for(wl))
        p = getattr(self.store, "persistence", None)
        if p is not None:
            p.intent("preempt" if preemption_reason else "evict",
                     wl.key, rv=wl.resource_version,
                     cycle=(decision_cycle if decision_cycle is not None
                            else self.cycle_count),
                     cluster_queue=cq or "",
                     detail={"reason": reason})
        was_reserved = wl.is_quota_reserved
        if was_reserved:
            self._solver_freed_since_drain += 1
        wl.set_condition(WorkloadConditionType.EVICTED, True, reason=reason,
                         message=message, now=now)
        if preemption_reason:
            wl.set_condition(WorkloadConditionType.PREEMPTED, True,
                             reason=preemption_reason, message=message, now=now)
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, False,
                         reason=reason, now=now)
        wl.set_condition(WorkloadConditionType.ADMITTED, False, reason=reason,
                         now=now)
        wl.status.admission = None
        wl.status.admission_checks.clear()
        # Per-reason eviction counters on the workload status
        # (reference: schedulingStats.evictions, workload_types.go).
        for ev in wl.status.eviction_stats:
            if ev.reason == reason and ev.underlying_cause == underlying_cause:
                ev.count += 1
                break
        else:
            from kueue_oss_tpu.api.types import WorkloadSchedulingStatsEviction

            wl.status.eviction_stats.append(WorkloadSchedulingStatsEviction(
                reason=reason, underlying_cause=underlying_cause, count=1))
        # The unhealthy-nodes list and the pods-readiness signal belong to
        # the admission being released; a future re-admission starts a
        # fresh PodsReady window.
        wl.status.unhealthy_nodes = []
        ready_cond = wl.status.conditions.pop(
            WorkloadConditionType.PODS_READY, None)
        pods_ready_at = (ready_cond.last_transition_time
                         if ready_cond is not None and ready_cond.status
                         else None)
        if requeue and backoff_base_s is not None:
            # Exponential requeue backoff: the workload becomes schedulable
            # again only at requeue_at (reference: RequeueState).
            from kueue_oss_tpu.api.types import RequeueState

            cap = (backoff_max_s if backoff_max_s is not None
                   else self.eviction_backoff_max_s)
            rs = wl.status.requeue_state or RequeueState()
            rs.count += 1
            delay = min(backoff_base_s * (2 ** (rs.count - 1)), cap)
            rs.requeue_at = now + delay
            wl.status.requeue_state = rs
            heapq.heappush(self._requeue_heap, (rs.requeue_at, key))
        self.store.update_workload(wl)
        events.eventf(wl.key, "Workload",
                      WARNING if preemption_reason else NORMAL,
                      "Preempted" if preemption_reason else "Evicted",
                      message, now=now)
        self.log.info("workload evicted", v=2, workload=wl.key,
                      reason=reason, preemption=bool(preemption_reason))
        obs.recorder.record(
            obs.PREEMPTED if preemption_reason else obs.EVICTED, wl.key,
            cycle=(decision_cycle if decision_cycle is not None
                   else self.cycle_count),
            cluster_queue=cq or "", path=decision_path, reason=message,
            reason_slug=preemption_reason or reason)
        # the eviction is now observable: clear pending expectations
        self.preemption_expectations.observe(wl.uid)
        self.evicted_total[wl.key] = self.evicted_total.get(wl.key, 0) + 1
        if cq:
            metrics.evicted_workloads_total.inc(cq, reason)
            # latency = Evicted-condition transition -> quota released;
            # only meaningful when THIS call released a reservation (an
            # already-pending workload re-evicted by job deletion would
            # otherwise record the stale transition age)
            ev = wl.condition(WorkloadConditionType.EVICTED)
            if ev is not None and was_reserved:
                metrics.workload_eviction_latency_seconds.observe(
                    cq, reason,
                    value=max(now - ev.last_transition_time, 0.0))
            if self.evicted_total[wl.key] == 1:
                metrics.evicted_workloads_once_total.inc(cq, reason)
            if metrics._lq_metrics_enabled():
                metrics.local_queue_evicted_workloads_total.inc(
                    wl.queue_name, wl.namespace, reason)
            if pods_ready_at is not None:
                metrics.pods_ready_to_evicted_time_seconds.observe(
                    cq, reason, value=max(now - pods_ready_at, 0.0))
            self._cycle_touched_cqs.add(cq)
        if cq and preemption_reason:
            self.preempted_total[cq] = self.preempted_total.get(cq, 0) + 1
            metrics.preempted_workloads_total.inc(cq, preemption_reason)
        # Freed capacity wakes parked workloads in the cohort.
        self.queues.report_workload_evicted(wl)

    def requeue_due(self, now: float) -> bool:
        """Re-queue evicted workloads whose backoff has expired.

        A min-heap of (requeue_at, key) avoids scanning the whole store;
        stale entries (cleared or re-admitted workloads) are skipped.
        """
        added = False
        while self._requeue_heap and self._requeue_heap[0][0] <= now:
            due_at, key = heapq.heappop(self._requeue_heap)
            wl = self.store.workloads.get(key)
            if wl is None:
                continue
            rs = wl.status.requeue_state
            if rs is None or rs.requeue_at != due_at:
                continue  # stale: cleared or rescheduled since
            if not wl.active or wl.is_quota_reserved or wl.is_finished:
                continue
            rs.requeue_at = None
            added |= self.queues.add_or_update_workload(wl)
        return added

    def next_requeue_at(self) -> Optional[float]:
        while self._requeue_heap:
            due_at, key = self._requeue_heap[0]
            wl = self.store.workloads.get(key)
            rs = wl.status.requeue_state if wl is not None else None
            if (wl is None or rs is None or rs.requeue_at != due_at
                    or wl.is_finished or not wl.active):
                heapq.heappop(self._requeue_heap)
                continue
            return due_at
        return None

    def _run_second_pass(self, now: float) -> None:
        """Compute delayed topology assignments for quota-reserved
        workloads whose admission checks turned Ready (scheduler second
        pass, second_pass_queue.go + scheduler.go delayed TAS)."""
        keys = self.queues.take_second_pass_ready(now)
        if not keys:
            return
        from kueue_oss_tpu import tas as tas_pkg

        snapshot = build_snapshot(self.store)
        for key in keys:
            wl = self.store.workloads.get(key)
            if (wl is None or not wl.is_quota_reserved or wl.is_evicted
                    or wl.is_finished or wl.status.admission is None):
                self.queues.clear_second_pass(key)
                continue
            cq = snapshot.cluster_queue(wl.status.admission.cluster_queue)
            if cq is None:
                self.queues.queue_second_pass(key, now)
                continue
            tas_requests = tas_pkg.requests_from_admission(
                wl, cq, only_pending=True)
            if not tas_requests:
                self.queues.clear_second_pass(key)
                continue
            result = cq.find_topology_assignments_for_workload(tas_requests)
            if any(res.failure for res in result.values()):
                # Capacity not there yet: retry with backoff (1s -> 30s).
                self.queues.queue_second_pass(key, now)
                continue
            podsets = {ps.name: ps for ps in wl.podsets}
            for psa in wl.status.admission.podset_assignments:
                res = result.get(psa.name)
                if res is not None and res.assignment is not None:
                    psa.topology_assignment = res.assignment
                    psa.delayed_topology_request = "Ready"
                    # Charge the new placement so later workloads in this
                    # batch see the domain usage.
                    flavor = next((f for f in psa.flavors.values()
                                   if f in snapshot.tas_flavors), None)
                    ps = podsets.get(psa.name)
                    if flavor is not None and ps is not None:
                        for dom in res.assignment.domains:
                            snapshot.tas_flavors[flavor].add_tas_usage(
                                dom.values,
                                effective_per_pod_requests(ps, wl.namespace),
                                dom.count)
            self.queues.clear_second_pass(key)
            self.store.update_workload(wl)

    def finish_workload(self, key: str, now: float = 0.0) -> None:
        """Mark Finished and release quota (jobframework Finished path)."""
        wl = self.store.workloads.get(key)
        if wl is None:
            return
        cq = (wl.status.admission.cluster_queue
              if wl.status.admission is not None
              else self.store.cluster_queue_for(wl))
        wl.set_condition(WorkloadConditionType.FINISHED, True,
                         reason="JobFinished", now=now)
        if wl.is_quota_reserved:
            self._solver_freed_since_drain += 1
        self.store.update_workload(wl)
        if cq:
            # the retained-finished GAUGES are maintained by the Store's
            # write choke point (_track_finished); only the monotone
            # counters live here
            metrics.finished_workloads_total.inc(cq)
            if metrics._lq_metrics_enabled():
                metrics.local_queue_finished_workloads_total.inc(
                    wl.queue_name, wl.namespace)
            self._cycle_touched_cqs.add(cq)
        self.queues.report_workload_finished(wl)

    def _requeue_and_update(self, e: Entry) -> None:
        if e.status != NOT_NOMINATED and e.requeue_reason == RequeueReason.GENERIC:
            e.requeue_reason = RequeueReason.FAILED_AFTER_NOMINATION
        self.queues.requeue_workload(e.info, e.requeue_reason)


# ---------------------------------------------------------------------------
# Entry iterators
# ---------------------------------------------------------------------------


class _ClassicalIterator:
    """scheduler.go makeClassicalIterator: quota-reserved first, fewer
    borrows first, higher priority, FIFO."""

    def __init__(self, entries: list[Entry]) -> None:
        from kueue_oss_tpu import features

        priority_step = features.enabled("PrioritySortingWithinCohort")

        def cmp(a: Entry, b: Entry) -> int:
            aq = a.info.obj.is_quota_reserved
            bq = b.info.obj.is_quota_reserved
            if aq != bq:
                return -1 if aq else 1
            ab, bb = a.assignment.borrows(), b.assignment.borrows()
            if ab != bb:
                return -1 if ab < bb else 1
            if priority_step:
                pa = effective_priority(a.info.obj)
                pb = effective_priority(b.info.obj)
                if pa != pb:
                    return -1 if pa > pb else 1
            ta = queue_order_timestamp(a.info.obj)
            tb = queue_order_timestamp(b.info.obj)
            if ta != tb:
                return -1 if ta < tb else 1
            return 0

        self.entries = sorted(entries, key=functools.cmp_to_key(cmp))
        self._idx = 0

    def has_next(self) -> bool:
        return self._idx < len(self.entries)

    def pop(self) -> Entry:
        e = self.entries[self._idx]
        self._idx += 1
        return e


class _FairSharingIterator:
    """fair_sharing_iterator.go: per-cohort tournament picking, at every
    level, the child whose nominated workload yields the lowest DRS."""

    def __init__(self, entries: list[Entry]) -> None:
        self.cq_to_entry: dict[ClusterQueueSnapshot, Entry] = {}
        for e in entries:
            assert e.cq_snapshot is not None
            self.cq_to_entry[e.cq_snapshot] = e

    def has_next(self) -> bool:
        return bool(self.cq_to_entry)

    def pop(self) -> Entry:
        cq = next(iter(self.cq_to_entry))
        if not cq.has_parent():
            return self.cq_to_entry.pop(cq)
        root = cq.parent().root()
        drs_values, requested_frs = self._compute_drs(root)
        winner = self._run_tournament(root, drs_values, requested_frs)
        assert winner is not None
        del self.cq_to_entry[winner.cq_snapshot]
        return winner

    def _compute_drs(self, root):
        drs_values: dict[tuple[str, str], object] = {}
        requested_frs: dict[str, dict] = {}
        for cq in root.subtree_cluster_queues():
            entry = self.cq_to_entry.get(cq)
            if entry is None:
                continue
            usage = entry.assignment_usage()
            requested_frs[entry.info.key] = usage
            revert = cq.simulate_usage_addition(usage)
            try:
                share = cq.dominant_resource_share()
                for ancestor in cq.path_parent_to_root():
                    drs_values[(ancestor.name, entry.info.key)] = share
                    share = ancestor.dominant_resource_share()
            finally:
                revert()
        return drs_values, requested_frs

    def _run_tournament(self, cohort, drs_values,
                        requested_frs) -> Optional[Entry]:
        from kueue_oss_tpu import features
        from kueue_oss_tpu.core.quota import compare_drs

        candidates: list[Entry] = []
        for child in cohort.child_cohorts():
            c = self._run_tournament(child, drs_values, requested_frs)
            if c is not None:
                candidates.append(c)
        for child_cq in cohort.child_cqs():
            if child_cq in self.cq_to_entry:
                candidates.append(self.cq_to_entry[child_cq])
        if not candidates:
            return None

        non_borrowing_first = features.enabled(
            "FairSharingPrioritizeNonBorrowing")
        priority_step = features.enabled("PrioritySortingWithinCohort")

        def less(a: Entry, b: Entry) -> bool:
            a_drs = drs_values.get((cohort.name, a.info.key))
            b_drs = drs_values.get((cohort.name, b.info.key))
            if a_drs is not None and b_drs is not None:
                if non_borrowing_first:
                    # 1: nominal first — a subtree not borrowing on the
                    # workload's REQUESTED flavors at this tournament
                    # level wins (fair_sharing_iterator.go:180-193)
                    ab = a_drs.is_borrowing_on(
                        requested_frs.get(a.info.key, {}))
                    bb = b_drs.is_borrowing_on(
                        requested_frs.get(b.info.key, {}))
                    if ab != bb:
                        return not ab
                # 2: DRF
                c = compare_drs(a_drs, b_drs)
                if c != 0:
                    return c < 0
            # 3: effective priority (gated like the reference)
            if priority_step:
                pa = effective_priority(a.info.obj)
                pb = effective_priority(b.info.obj)
                if pa != pb:
                    return pa > pb
            # 4: FIFO
            return (queue_order_timestamp(a.info.obj)
                    < queue_order_timestamp(b.info.obj))

        best = candidates[0]
        for cur in candidates[1:]:
            if less(cur, best):
                best = cur
        return best
