"""Preemption target search: classical heuristic and fair-sharing (DRS).

Reference parity: pkg/scheduler/preemption/{preemption.go,
classical/candidate_generator.go, classical/hierarchical_preemption.go,
fairsharing/*}. The classical path removes candidates from the snapshot in
a legality-and-priority order until the preemptor fits, then greedily adds
back; the fair path runs a DRS tournament over the cohort tree applying the
configured strategy rules (S2-a LessThanOrEqualToFinalShare, S2-b
LessThanInitialShare).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

from kueue_oss_tpu.api.types import (
    FlavorResource,
    PreemptionPolicyValue,
    Workload,
)
from kueue_oss_tpu.core.quota import DRS, compare_drs, negative_drs
from kueue_oss_tpu.core.snapshot import (
    ClusterQueueSnapshot,
    CohortSnapshot,
    Snapshot,
)
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_priority,
    queue_order_timestamp,
    quota_reservation_time,
)
from kueue_oss_tpu.scheduler import flavor_assigner as fa

# Preemption reasons (reference: workload_types.go reason constants).
IN_CLUSTER_QUEUE = "InClusterQueue"
IN_COHORT_RECLAMATION = "InCohortReclamation"
IN_COHORT_FAIR_SHARING = "InCohortFairSharing"
IN_COHORT_RECLAIM_WHILE_BORROWING = "InCohortReclaimWhileBorrowing"

# preemptionVariant (classical/candidate_generator.go)
V_NEVER = 0
V_WITHIN_CQ = 1
V_HIERARCHICAL_RECLAIM = 2
V_RECLAIM_WITHOUT_BORROWING = 3
V_RECLAIM_WHILE_BORROWING = 4

_VARIANT_REASON = {
    V_WITHIN_CQ: IN_CLUSTER_QUEUE,
    V_HIERARCHICAL_RECLAIM: IN_COHORT_RECLAMATION,
    V_RECLAIM_WITHOUT_BORROWING: IN_COHORT_RECLAMATION,
    V_RECLAIM_WHILE_BORROWING: IN_COHORT_RECLAIM_WHILE_BORROWING,
}


@dataclass
class Target:
    info: WorkloadInfo
    reason: str
    cq: ClusterQueueSnapshot


# ---------------------------------------------------------------------------
# Legality & ordering
# ---------------------------------------------------------------------------


#: same-priority preemption timestamp gap under the gate
#: (preemption_policy.go:30 timestampPreemptionBuffer)
TIMESTAMP_PREEMPTION_BUFFER_S = 300.0


def satisfies_preemption_policy(preemptor: Workload, candidate: Workload,
                                policy: str) -> bool:
    """common/preemption_policy.go SatisfiesPreemptionPolicy."""
    lower_priority = effective_priority(preemptor) > effective_priority(candidate)
    if policy == PreemptionPolicyValue.LOWER_PRIORITY:
        return lower_priority
    if policy == PreemptionPolicyValue.LOWER_OR_NEWER_EQUAL_PRIORITY:
        newer_equal = (
            effective_priority(preemptor) == effective_priority(candidate)
            and queue_order_timestamp(preemptor) < queue_order_timestamp(candidate)
        )
        from kueue_oss_tpu import features

        if newer_equal and features.enabled(
                "SchedulerTimestampPreemptionBuffer"):
            # a marginally-newer equal-priority candidate is spared:
            # the gap must exceed the buffer (preemption_policy.go:44)
            newer_equal = (
                queue_order_timestamp(candidate)
                - queue_order_timestamp(preemptor)
                > TIMESTAMP_PREEMPTION_BUFFER_S)
        return lower_priority or newer_equal
    return policy == PreemptionPolicyValue.ANY


def candidates_ordering(a: WorkloadInfo, b: WorkloadInfo, cq_name: str,
                        now: float) -> int:
    """common/ordering.go CandidatesOrdering: evicted first, other-CQ first,
    lower priority first, more recently admitted first."""
    a_evicted, b_evicted = a.obj.is_evicted, b.obj.is_evicted
    if a_evicted != b_evicted:
        return -1 if a_evicted else 1
    a_same, b_same = a.cluster_queue == cq_name, b.cluster_queue == cq_name
    if a_same != b_same:
        return 1 if a_same else -1
    pa, pb = effective_priority(a.obj), effective_priority(b.obj)
    if pa != pb:
        return -1 if pa < pb else 1
    ta = quota_reservation_time(a.obj, now)
    tb = quota_reservation_time(b.obj, now)
    if ta != tb:
        return 1 if ta < tb else -1  # more recently admitted first
    return -1 if a.obj.uid < b.obj.uid else (1 if a.obj.uid > b.obj.uid else 0)


def _sort_candidates(cands: list["_CandidateElem"], cq_name: str,
                     now: float) -> None:
    import functools
    cands.sort(key=functools.cmp_to_key(
        lambda x, y: candidates_ordering(x.wl, y.wl, cq_name, now)))


def workload_uses_resources(wl: WorkloadInfo,
                            frs: set[FlavorResource]) -> bool:
    for psr in wl.total_requests:
        for res, flv in psr.flavors.items():
            if (flv, res) in frs:
                return True
    return False


# ---------------------------------------------------------------------------
# Classical candidate generation (hierarchical)
# ---------------------------------------------------------------------------


@dataclass
class _CandidateElem:
    wl: WorkloadInfo
    lca: Optional[CohortSnapshot]
    variant: int


class _HierarchicalCtx:
    def __init__(self, wl: WorkloadInfo, cq: ClusterQueueSnapshot,
                 frs_need_preemption: set[FlavorResource],
                 requests: dict[FlavorResource, int]) -> None:
        self.wl = wl
        self.cq = cq
        self.frs = frs_need_preemption
        self.requests = requests


def is_borrowing_within_cohort_forbidden(
        cq: ClusterQueueSnapshot) -> tuple[bool, Optional[int]]:
    bwc = cq.spec.preemption.borrow_within_cohort
    if bwc.policy == PreemptionPolicyValue.NEVER:
        return True, None
    return False, bwc.max_priority_threshold


def _classify_variant(ctx: _HierarchicalCtx, wl: WorkloadInfo,
                      hierarchical_advantage: bool) -> int:
    if not workload_uses_resources(wl, ctx.frs):
        return V_NEVER
    if wl.cluster_queue == ctx.cq.name:
        policy = ctx.cq.spec.preemption.within_cluster_queue
    else:
        policy = ctx.cq.spec.preemption.reclaim_within_cohort
    if not satisfies_preemption_policy(ctx.wl.obj, wl.obj, policy):
        return V_NEVER
    if wl.cluster_queue == ctx.cq.name:
        return V_WITHIN_CQ
    if hierarchical_advantage:
        return V_HIERARCHICAL_RECLAIM
    forbidden, threshold = is_borrowing_within_cohort_forbidden(ctx.cq)
    if forbidden:
        return V_RECLAIM_WITHOUT_BORROWING
    cand_pri = effective_priority(wl.obj)
    inc_pri = effective_priority(ctx.wl.obj)
    if _above_borrowing_threshold(cand_pri, inc_pri, threshold):
        return V_RECLAIM_WITHOUT_BORROWING
    return V_RECLAIM_WHILE_BORROWING


def _above_borrowing_threshold(cand_pri: int, inc_pri: int,
                               threshold: Optional[int]) -> bool:
    if cand_pri >= inc_pri:
        return True
    if threshold is None:
        return False
    return cand_pri > threshold


def _candidates_from_cq(cq: ClusterQueueSnapshot, lca: Optional[CohortSnapshot],
                        ctx: _HierarchicalCtx,
                        hierarchical_advantage: bool) -> list[_CandidateElem]:
    out = []
    for wl in cq.workloads.values():
        variant = _classify_variant(ctx, wl, hierarchical_advantage)
        if variant != V_NEVER:
            out.append(_CandidateElem(wl, lca, variant))
    return out


def _quantities_fit_in_quota(node, requests: dict[FlavorResource, int]):
    """resource_node.go QuantitiesFitInQuota."""
    fits = True
    remaining = {}
    for fr, v in requests.items():
        if node.usage.get(fr, 0) + v > node.subtree_quota.get(fr, 0):
            fits = False
        remaining[fr] = max(0, v - node.local_available(fr))
    return fits, remaining


def _collect_hierarchical_candidates(
        ctx: _HierarchicalCtx) -> tuple[list[_CandidateElem], list[_CandidateElem]]:
    """hierarchical_preemption.go collectCandidatesForHierarchicalReclaim."""
    hierarchy_cands: list[_CandidateElem] = []
    priority_cands: list[_CandidateElem] = []
    if (not ctx.cq.has_parent()
            or ctx.cq.spec.preemption.reclaim_within_cohort
            == PreemptionPolicyValue.NEVER):
        return hierarchy_cands, priority_cands
    prev_subtree: Optional[CohortSnapshot] = None
    advantage, remaining = _quantities_fit_in_quota(ctx.cq.node, ctx.requests)
    for subtree_root in ctx.cq.path_parent_to_root():
        target = hierarchy_cands if advantage else priority_cands
        _collect_in_subtree(ctx, subtree_root, subtree_root, prev_subtree,
                            advantage, target)
        fits, remaining = _quantities_fit_in_quota(subtree_root.node, remaining)
        advantage = advantage or fits
        prev_subtree = subtree_root
    return hierarchy_cands, priority_cands


def _collect_in_subtree(ctx: _HierarchicalCtx, current: CohortSnapshot,
                        subtree_root: CohortSnapshot,
                        skip: Optional[CohortSnapshot],
                        advantage: bool, out: list[_CandidateElem]) -> None:
    for child in current.child_cohorts():
        if skip is not None and child == skip:
            continue
        if child.is_within_nominal(ctx.frs):
            continue
        _collect_in_subtree(ctx, child, subtree_root, skip, advantage, out)
    for child_cq in current.child_cqs():
        if child_cq == ctx.cq:
            continue
        if not child_cq.is_within_nominal(ctx.frs):
            out.extend(_candidates_from_cq(child_cq, subtree_root, ctx, advantage))


class CandidateIterator:
    """classical/candidate_generator.go candidateIterator."""

    def __init__(self, ctx: _HierarchicalCtx, snapshot: Snapshot,
                 now: float) -> None:
        self.ctx = ctx
        self.snapshot = snapshot
        same_queue: list[_CandidateElem] = []
        if ctx.cq.spec.preemption.within_cluster_queue != PreemptionPolicyValue.NEVER:
            same_queue = _candidates_from_cq(ctx.cq, None, ctx, False)
        hierarchy, priority_cands = _collect_hierarchical_candidates(ctx)
        for group in (same_queue, priority_cands, hierarchy):
            _sort_candidates(group, ctx.cq.name, now)

        def split_evicted(group):
            ev = [c for c in group if c.wl.obj.is_evicted]
            non = [c for c in group if not c.wl.obj.is_evicted]
            return ev, non

        eh, nh = split_evicted(hierarchy)
        ep, np_ = split_evicted(priority_cands)
        es, ns = split_evicted(same_queue)
        self.candidates: list[_CandidateElem] = eh + ep + es + nh + np_ + ns
        self.no_candidate_from_other_queues = not hierarchy and not priority_cands
        self.no_candidate_for_hierarchical_reclaim = not hierarchy
        self._idx = 0

    def reset(self) -> None:
        self._idx = 0

    def next(self, borrow: bool) -> tuple[Optional[WorkloadInfo], str]:
        while self._idx < len(self.candidates):
            cand = self.candidates[self._idx]
            self._idx += 1
            if self._valid(cand, borrow):
                return cand.wl, _VARIANT_REASON[cand.variant]
        return None, ""

    def _valid(self, cand: _CandidateElem, borrow: bool) -> bool:
        if self.ctx.cq.name == cand.wl.cluster_queue:
            return True
        if borrow and cand.variant == V_RECLAIM_WITHOUT_BORROWING:
            return False
        cq = self.snapshot.cluster_queue(cand.wl.cluster_queue)
        if cq is None or cq.is_within_nominal(self.ctx.frs):
            return False
        for node in cq.path_parent_to_root():
            if cand.lca is not None and node == cand.lca:
                break
            if node.is_within_nominal(self.ctx.frs):
                return False
        return True


# ---------------------------------------------------------------------------
# Fair-sharing strategies & tournament ordering
# ---------------------------------------------------------------------------


def less_than_or_equal_to_final_share(preemptor_new: DRS, _target_old: DRS,
                                      target_new: DRS) -> bool:
    """Rule S2-a."""
    return compare_drs(preemptor_new, target_new) <= 0


def less_than_initial_share(preemptor_new: DRS, target_old: DRS,
                            _target_new: DRS) -> bool:
    """Rule S2-b."""
    return compare_drs(preemptor_new, target_old) < 0


DEFAULT_FS_STRATEGIES = (less_than_or_equal_to_final_share,
                         less_than_initial_share)


class _TargetCQ:
    def __init__(self, ordering: "_CQOrdering", cq: ClusterQueueSnapshot):
        self.ordering = ordering
        self.cq = cq

    def in_cluster_queue_preemption(self) -> bool:
        return self.cq is self.ordering.preemptor_cq

    def has_workload(self) -> bool:
        return bool(self.ordering.cq_to_targets.get(self.cq.name))

    def pop_workload(self) -> WorkloadInfo:
        return self.ordering.cq_to_targets[self.cq.name].pop(0)

    # -- almost-LCA share computation (fairsharing/least_common_ancestor.go)

    def _lca(self) -> CohortSnapshot:
        for ancestor in self.cq.path_parent_to_root():
            if ancestor in self.ordering.preemptor_ancestors:
                return ancestor
        raise RuntimeError("no least common ancestor")

    @staticmethod
    def _almost_lca(cq: ClusterQueueSnapshot, lca: CohortSnapshot):
        node = cq
        for ancestor in cq.path_parent_to_root():
            if ancestor == lca:
                return node
            node = ancestor
        raise RuntimeError("no almost-LCA")

    def compute_shares(self) -> tuple[DRS, DRS]:
        lca = self._lca()
        pre = self._almost_lca(self.ordering.preemptor_cq, lca)
        tgt = self._almost_lca(self.cq, lca)
        return pre.dominant_resource_share(), tgt.dominant_resource_share()

    def compute_target_share_after_removal(self, wl: WorkloadInfo) -> DRS:
        revert = self.cq.simulate_usage_removal(wl.usage())
        try:
            lca = self._lca()
            tgt = self._almost_lca(self.cq, lca)
            return tgt.dominant_resource_share()
        finally:
            revert()


class _CQOrdering:
    """fairsharing/ordering.go TargetClusterQueueOrdering — DRS tournament."""

    def __init__(self, preemptor_cq: ClusterQueueSnapshot,
                 candidates: list[WorkloadInfo], now: float) -> None:
        self.preemptor_cq = preemptor_cq
        self.now = now
        self.preemptor_ancestors = set(preemptor_cq.path_parent_to_root())
        self.cq_to_targets: dict[str, list[WorkloadInfo]] = {}
        for c in candidates:
            self.cq_to_targets.setdefault(c.cluster_queue, []).append(c)
        self.pruned_cqs: set[ClusterQueueSnapshot] = set()
        self.pruned_cohorts: set[CohortSnapshot] = set()

    def iter(self) -> Iterator[_TargetCQ]:
        if not self.preemptor_cq.has_parent():
            target = _TargetCQ(self, self.preemptor_cq)
            while target.has_workload():
                yield target
            return
        root = self.preemptor_cq.parent().root()
        while root not in self.pruned_cohorts:
            target = self._next_target(root)
            if target is not None:
                yield target

    def drop_queue(self, target: _TargetCQ) -> None:
        self.pruned_cqs.add(target.cq)

    def _next_target(self, cohort: CohortSnapshot) -> Optional[_TargetCQ]:
        highest_cq: Optional[ClusterQueueSnapshot] = None
        highest_cq_drs = negative_drs()
        for cq in cohort.child_cqs():
            if cq in self.pruned_cqs:
                continue
            drs = cq.dominant_resource_share()
            has_wl = bool(self.cq_to_targets.get(cq.name))
            if (not drs.borrowing and cq is not self.preemptor_cq) or not has_wl:
                self.pruned_cqs.add(cq)
            elif compare_drs(drs, highest_cq_drs) == 0 and highest_cq is not None:
                new_wl = self.cq_to_targets[cq.name][0]
                cur_wl = self.cq_to_targets[highest_cq.name][0]
                if candidates_ordering(new_wl, cur_wl, self.preemptor_cq.name,
                                       self.now) < 0:
                    highest_cq = cq
            elif compare_drs(drs, highest_cq_drs) > 0:
                highest_cq_drs = drs
                highest_cq = cq

        highest_cohort: Optional[CohortSnapshot] = None
        highest_cohort_drs = negative_drs()
        for child in cohort.child_cohorts():
            if child in self.pruned_cohorts:
                continue
            drs = child.dominant_resource_share()
            on_path = child in self.preemptor_ancestors
            if not drs.borrowing and not on_path:
                self.pruned_cohorts.add(child)
            elif compare_drs(drs, highest_cohort_drs) >= 0:
                highest_cohort_drs = drs
                highest_cohort = child

        if highest_cohort is None and highest_cq is None:
            self.pruned_cohorts.add(cohort)
            return None
        if highest_cq is not None and (
                highest_cohort is None
                or compare_drs(highest_cq_drs, highest_cohort_drs) >= 0):
            return _TargetCQ(self, highest_cq)
        return self._next_target(highest_cohort)  # type: ignore[arg-type]


# ---------------------------------------------------------------------------
# The Preemptor
# ---------------------------------------------------------------------------


class _PreemptionCtx:
    def __init__(self, preemptor: WorkloadInfo, cq: ClusterQueueSnapshot,
                 snapshot: Snapshot, usage: dict[FlavorResource, int],
                 frs: set[FlavorResource], now: float) -> None:
        self.preemptor = preemptor
        self.cq = cq
        self.snapshot = snapshot
        self.usage = usage
        self.frs = frs
        self.now = now


class Preemptor:
    def __init__(self, enable_fair_sharing: bool = False,
                 fs_strategies=DEFAULT_FS_STRATEGIES) -> None:
        self.enable_fair_sharing = enable_fair_sharing
        self.fs_strategies = fs_strategies

    # -- public API --------------------------------------------------------

    def get_targets(self, wl: WorkloadInfo, assignment: fa.Assignment,
                    snapshot: Snapshot, now: float = 0.0) -> list[Target]:
        cq = snapshot.cluster_queue(wl.cluster_queue)
        assert cq is not None
        frs = {
            (rec.name, res)
            for ps in assignment.podsets
            for res, rec in ps.flavors.items()
            if rec.mode == fa.PREEMPT
        }
        usage = dict(assignment.usage_quota)
        return self._get_targets(
            _PreemptionCtx(wl, cq, snapshot, usage, frs, now))

    def simulate_preemption(self, cq: ClusterQueueSnapshot, wl: WorkloadInfo,
                            fr: FlavorResource,
                            quantity: int) -> tuple[str, int]:
        """preemption_oracle.go SimulatePreemption."""
        snapshot = cq._snapshot
        targets = self._get_targets(_PreemptionCtx(
            wl, cq, snapshot, {fr: quantity}, {fr}, 0.0))
        if not targets:
            borrow, _ = fa.find_height_of_lowest_subtree_that_fits(
                cq, fr, quantity)
            return fa.NO_CANDIDATES, borrow
        infos = [t.info for t in targets]
        revert = snapshot.simulate_workload_removal(infos)
        borrow_after, _ = fa.find_height_of_lowest_subtree_that_fits(
            cq, fr, quantity)
        revert()
        if any(t.info.cluster_queue == cq.name for t in targets):
            return fa.POSSIBILITY_PREEMPT, borrow_after
        return fa.POSSIBILITY_RECLAIM, borrow_after

    # -- dispatch ----------------------------------------------------------

    def _get_targets(self, ctx: _PreemptionCtx) -> list[Target]:
        if self.enable_fair_sharing:
            return self._fair_preemptions(ctx)
        return self._classical_preemptions(ctx)

    # -- classical ---------------------------------------------------------

    def _classical_preemptions(self, ctx: _PreemptionCtx) -> list[Target]:
        hctx = _HierarchicalCtx(ctx.preemptor, ctx.cq, ctx.frs, ctx.usage)
        it = CandidateIterator(hctx, ctx.snapshot, ctx.now)
        borrow_forbidden, _ = is_borrowing_within_cohort_forbidden(ctx.cq)
        if it.no_candidate_from_other_queues or (
                borrow_forbidden and not self._queue_under_nominal(ctx)):
            attempts = [True]
        elif borrow_forbidden and it.no_candidate_for_hierarchical_reclaim:
            attempts = [False, True]
        else:
            attempts = [True, False]

        for allow_borrowing in attempts:
            targets: list[Target] = []
            it.reset()
            while True:
                cand, reason = it.next(allow_borrowing)
                if cand is None:
                    break
                ctx.snapshot.remove_workload(cand)
                targets.append(Target(
                    cand, reason,
                    ctx.snapshot.cluster_queue(cand.cluster_queue)))
                if self._workload_fits(ctx, allow_borrowing):
                    targets = self._fill_back(ctx, targets, allow_borrowing)
                    self._restore(ctx.snapshot, targets)
                    return targets
            self._restore(ctx.snapshot, targets)
        return []

    def _fill_back(self, ctx: _PreemptionCtx, targets: list[Target],
                   allow_borrowing: bool) -> list[Target]:
        """Re-add targets (newest first, excluding the last) while still
        fitting (preemption.go fillBackWorkloads)."""
        i = len(targets) - 2
        while i >= 0:
            ctx.snapshot.add_workload(targets[i].info)
            if self._workload_fits(ctx, allow_borrowing):
                targets[i] = targets[-1]
                targets.pop()
            else:
                ctx.snapshot.remove_workload(targets[i].info)
            i -= 1
        return targets

    @staticmethod
    def _restore(snapshot: Snapshot, targets: list[Target]) -> None:
        for t in targets:
            snapshot.add_workload(t.info)

    def _workload_fits(self, ctx: _PreemptionCtx, allow_borrowing: bool) -> bool:
        for fr, v in ctx.usage.items():
            if not allow_borrowing and ctx.cq.borrowing_with(fr, v):
                return False
            if v > ctx.cq.available(fr):
                return False
        return True

    def _workload_fits_fs(self, ctx: _PreemptionCtx) -> bool:
        """Fair sharing pre-adds the incoming usage; remove it around the
        fit check (preemption.go workloadFitsForFairSharing)."""
        revert = ctx.cq.simulate_usage_removal(ctx.usage)
        try:
            return self._workload_fits(ctx, True)
        finally:
            revert()

    def _queue_under_nominal(self, ctx: _PreemptionCtx) -> bool:
        for fr in ctx.frs:
            if ctx.cq.node.usage.get(fr, 0) >= ctx.cq.quota_for(fr).nominal:
                return False
        return True

    # -- fair sharing ------------------------------------------------------

    def _find_fs_candidates(self, ctx: _PreemptionCtx) -> list[WorkloadInfo]:
        """preemption.go findCandidates."""
        out: list[WorkloadInfo] = []
        pre = ctx.cq.spec.preemption
        if pre.within_cluster_queue != PreemptionPolicyValue.NEVER:
            for wl in ctx.cq.workloads.values():
                if (satisfies_preemption_policy(
                        ctx.preemptor.obj, wl.obj, pre.within_cluster_queue)
                        and workload_uses_resources(wl, ctx.frs)):
                    out.append(wl)
        if ctx.cq.has_parent() and (
                pre.reclaim_within_cohort != PreemptionPolicyValue.NEVER):
            for cohort_cq in ctx.cq.parent().root().subtree_cluster_queues():
                if cohort_cq == ctx.cq:
                    continue
                if not any(cohort_cq.borrowing(fr) for fr in ctx.frs):
                    continue
                for wl in cohort_cq.workloads.values():
                    if (satisfies_preemption_policy(
                            ctx.preemptor.obj, wl.obj, pre.reclaim_within_cohort)
                            and workload_uses_resources(wl, ctx.frs)):
                        out.append(wl)
        return out

    def _fair_preemptions(self, ctx: _PreemptionCtx) -> list[Target]:
        candidates = self._find_fs_candidates(ctx)
        if not candidates:
            return []
        import functools
        candidates.sort(key=functools.cmp_to_key(
            lambda a, b: candidates_ordering(a, b, ctx.cq.name, ctx.now)))

        revert_sim = ctx.cq.simulate_usage_addition(ctx.usage)
        try:
            fits, targets, retry = self._run_first_fs_strategy(
                ctx, candidates, self.fs_strategies[0])
            if not fits and len(self.fs_strategies) > 1:
                fits, targets = self._run_second_fs_strategy(ctx, retry, targets)
        finally:
            revert_sim()

        if not fits:
            self._restore(ctx.snapshot, targets)
            return []
        # fill back with the incoming usage still present semantics: the
        # reference reverts the simulation before fillBack, then uses the
        # allowBorrowing=true fit check.
        targets = self._fill_back(ctx, targets, True)
        self._restore(ctx.snapshot, targets)
        return targets

    def _run_first_fs_strategy(
        self, ctx: _PreemptionCtx, candidates: list[WorkloadInfo], strategy
    ) -> tuple[bool, list[Target], list[WorkloadInfo]]:
        from kueue_oss_tpu import features

        # FairSharingPreemptWithinNominal (beta, on): a preemptor whose
        # CQ stays within nominal quota on the contested resources —
        # the incoming usage is already simulated by the caller — is
        # entitled to preempt cross-CQ candidates UNCONDITIONALLY,
        # bypassing the DRS strategy check (preemption.go:377-412); no
        # retry candidates are produced for the second strategy.
        within_nominal = (
            features.enabled("FairSharingPreemptWithinNominal")
            and ctx.cq.is_within_nominal(ctx.frs))
        ordering = _CQOrdering(ctx.cq, candidates, ctx.now)
        targets: list[Target] = []
        retry: list[WorkloadInfo] = []
        for cand_cq in ordering.iter():
            if cand_cq.in_cluster_queue_preemption():
                wl = cand_cq.pop_workload()
                ctx.snapshot.remove_workload(wl)
                targets.append(Target(wl, IN_CLUSTER_QUEUE, cand_cq.cq))
                if self._workload_fits_fs(ctx):
                    return True, targets, []
                continue
            if within_nominal:
                wl = cand_cq.pop_workload()
                ctx.snapshot.remove_workload(wl)
                targets.append(Target(wl, IN_COHORT_RECLAMATION,
                                      cand_cq.cq))
                if self._workload_fits_fs(ctx):
                    return True, targets, []
                continue
            preemptor_new, target_old = cand_cq.compute_shares()
            while cand_cq.has_workload():
                wl = cand_cq.pop_workload()
                target_new = cand_cq.compute_target_share_after_removal(wl)
                if strategy(preemptor_new, target_old, target_new):
                    ctx.snapshot.remove_workload(wl)
                    targets.append(Target(wl, IN_COHORT_FAIR_SHARING, cand_cq.cq))
                    if self._workload_fits_fs(ctx):
                        return True, targets, []
                    break  # re-evaluate CQ ordering with changed shares
                retry.append(wl)
        return False, targets, retry

    def _run_second_fs_strategy(
        self, ctx: _PreemptionCtx, retry: list[WorkloadInfo],
        targets: list[Target]
    ) -> tuple[bool, list[Target]]:
        ordering = _CQOrdering(ctx.cq, retry, ctx.now)
        for cand_cq in ordering.iter():
            preemptor_new, target_old = cand_cq.compute_shares()
            if less_than_initial_share(preemptor_new, target_old, DRS()):
                wl = cand_cq.pop_workload()
                ctx.snapshot.remove_workload(wl)
                targets.append(Target(wl, IN_COHORT_FAIR_SHARING, cand_cq.cq))
                if self._workload_fits_fs(ctx):
                    return True, targets
            ordering.drop_queue(cand_cq)
        return False, targets
