"""Streaming control plane: micro-batched sub-cycle admission drains.

The cycle-batch model re-exports and re-solves the world every cycle,
so at sustained high arrival rates p50 time-to-admit is dominated by
the full-solve cadence — the batch-vs-continuous trade-off quantified
in arXiv 1106.4985. This module decouples them: between full solves,
new arrivals to **uncontended** ClusterQueues are admitted sub-cycle
through a compact per-CQ fast path, while anything whose outcome could
depend on cross-CQ ordering defers to the next full solve.

Soundness model (docs/ARCHITECTURE.md "Streaming dataflow"):

- A CQ is *fast-path eligible* only when the host flavor-assigner
  oracle fully models it sub-cycle: no preemption policies, no fair
  sharing, no admission-scope AFS, no TAS flavors. For such CQs a
  greedy in-order walk of the pending heap — admit the head while the
  oracle says FIT, park BestEffortFIFO no-fits, stop at a blocked
  StrictFIFO head — is exactly the per-CQ behavior of the batched
  solve (the established kernel↔oracle parity).
- **Multi-flavor determinism.** With several flavor options, the
  batch oracle's pick for a workload depends on capacity margins: a
  capacity event landing later in the window (a finish freeing an
  earlier-preference flavor) would make the boundary solve pick a
  different flavor than the one already streamed — a retroactive
  divergence no fence can undo. So each pick is checked against a
  **flavor-pick witness** captured per full solve (the preference
  order plus static zero-usage capacity ceilings derived from the
  same spec data the solver exports — ``engine.flavor_witness()`` /
  ``tensors.flavor_option_ceilings``, cached by
  ``ExportCache.spec_gen``): a pick streams only when every
  earlier-preference compatible option's ceiling sits below the
  request, i.e. no capacity event can flip it; otherwise the CQ
  demotes (``flavor_witness_invalid``).
- Cross-CQ coupling happens only through cohort **borrowing**, and
  the batch oracle interleaves cohort-mates in global priority
  order. Singleton cohort subtrees stream freely (their borrowing
  races nobody) and no-borrow subtrees are capacity-independent. A
  borrow-capable multi-CQ subtree streams under the
  **reserved-headroom protocol**: each full solve reserves a per-CQ
  nominal-headroom budget (the CQ's unused nominal at the boundary);
  sub-cycle admissions consume only that budget — never borrowed
  capacity (borrowing stays a full-solve-only decision, the Aryl
  capacity-loaning contract, arXiv 2202.07896) — and the subtree's
  members are walked as ONE merged sequence in global order (the
  batch interleave). Within-nominal admissions are order-independent
  across mates, so the merged prefix matches the boundary solve
  bit-for-bit; the first entry that would need borrowed capacity (or
  overruns its budget) fences the whole subtree
  (``headroom_exhausted``) until the next full solve resolves the
  borrow jointly.
- On top of that, any cohort-crossing event — an eviction/finish/
  preemption candidate (capacity freed), a quota or flavor edit, a
  node flap (all spec events bump ``ExportCache.spec_gen``), an
  admission by any other path — marks the subtree **contended** until
  the next full solve.
- Within one CQ, the cycle-batch oracle reorders a whole inter-solve
  window by ``_order_key`` (priority, then FIFO). Streaming admits in
  arrival order, which matches the batch order exactly while arrivals
  are order-monotone; an **out-of-order arrival** (one sorting before
  a workload already admitted this window) demotes the CQ before it
  is processed. Admissions already committed before the inversion
  arrived are the one place streaming trades strict window-priority
  for latency — the same trade the cycle-batch model makes for any
  arrival that lands just after a solve boundary closes its batch.

Under those fences the final store state after each full solve is
bit-identical to the pure cycle-batch oracle (the ``streaming``
oracle-parity property test replays randomized arrival/quota/flap
scripts against both twins and byte-compares the canonical dumps at
every boundary).

Commits reuse ``SolverEngine._commit_admission`` — the same store
writes, WAL intents, SLO feed, and flight-recorder events as a solver
drain — so a streaming admission is indistinguishable in durable state
from a batched one. The delta-session slot coordinates stay valid: a
micro-admission just dirties its ExportCache row like any other store
event, and the next full solve ships it as a normal dirty-row delta
(no session reset, resident device tensors untouched).

**Device micro-solve.** A watch-driven burst large enough to amortize
a kernel dispatch (``micro_solve_min`` eligible entries across the
streamable single-flavor CQs) is coalesced into ONE lean-kernel solve
(``_drain_micro``): per-entry fences are re-checked host-side while
building the batch, the export pins the window snapshot (so earlier
streamed usage is visible), and the plan decodes through the same
opt->flavor mapping as ``SolverEngine._apply_plan``. Small bursts and
multi-flavor CQs keep the per-entry host FlavorAssigner walk — the
small-burst path and the micro-solve's parity oracle
(``KUEUE_STREAM_MICROSOLVE=0`` forces it everywhere).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_oss_tpu import metrics, obs, resilience
from kueue_oss_tpu.core.queue_manager import _order_key
from kueue_oss_tpu.core.workload_info import WorkloadInfo
from kueue_oss_tpu.scheduler import flavor_assigner as fa
from kueue_oss_tpu.scheduler.flavor_assigner import FlavorAssigner
from kueue_oss_tpu.scheduler.preemption import Preemptor


@dataclass
class MicroDrainResult:
    """Outcome of one micro-batched admission drain."""

    admitted: int = 0
    parked: int = 0
    #: CQs skipped this drain because their subtree is contended, a
    #: sibling holds pending work, or an entry needs the full solve
    deferred_cqs: int = 0
    duration_s: float = 0.0
    admitted_keys: list[str] = field(default_factory=list)
    #: entries routed through the device micro-solve (0 = host walk)
    micro_batch: int = 0
    micro_export_s: float = 0.0
    micro_solve_s: float = 0.0
    #: wall spent inside the engine commit core — identical work in
    #: the host-walk and micro-solve arms (parity requires it), so
    #: benches subtract it to compare the decision phases honestly
    commit_s: float = 0.0


#: human-readable fence explanations (tools/explain.py surfaces these
#: for "why did this workload not stream"); keys double as
#: stream_demotions_total reasons where a metric is emitted
_FENCE_TEXT = {
    "out_of_order": (
        "demoted from the streaming fast path: the arrival sorts "
        "before an admission already committed this window, so only "
        "the full solve can honor batch priority order"),
    "unsupported": (
        "deferred to the full solve: workload shape outside the "
        "streaming fast path (topology request, concurrent-admission "
        "variant, or multi-podset flavor choice)"),
    "flavor_witness_invalid": (
        "demoted from the streaming fast path: an earlier-preference "
        "flavor option stays reachable under its capacity ceiling, "
        "so a capacity event could flip the batch oracle's pick"),
    "headroom_exhausted": (
        "demoted from the streaming fast path: the admission would "
        "need borrowed capacity or overrun the reserved "
        "nominal-headroom budget (borrowing is a full-solve-only "
        "decision)"),
    "borrow_capable": (
        "deferred to the full solve: borrow-capable cohort subtree "
        "with a member outside the streaming fast path"),
    "ineligible": (
        "deferred to the full solve: the ClusterQueue uses "
        "preemption, AdmissionFairSharing, TAS flavors, or "
        "non-default flavor fungibility"),
}


class StreamingAdmitter:
    """Per-CQ sub-cycle admission fast path between full solves.

    One instance per SolverEngine; ``drain()`` runs on the scheduler
    thread, the store-watch classifier may run on any mutating thread
    (controller callbacks), so the contention sets are lock-guarded.
    """

    def __init__(self, store, queues, engine,
                 max_batch: int = 512) -> None:
        self.store = store
        self.queues = queues
        self.engine = engine
        self.enabled = True
        #: admissions per drain() call — bounds one micro-batch's
        #: latency; the remainder stays in order for the next drain
        self.max_batch = max_batch
        #: device micro-solve: coalesce a watch-driven burst into ONE
        #: lean-kernel solve instead of per-entry host oracle walks.
        #: The host walk stays as the small-burst path and the parity
        #: oracle (KUEUE_STREAM_MICROSOLVE=0 forces it everywhere).
        self.micro_solve = os.environ.get(
            "KUEUE_STREAM_MICROSOLVE", "1") != "0"
        #: bursts below this many pending entries stay on the host
        #: walk — kernel dispatch overhead dominates tiny batches
        self.micro_solve_min = int(os.environ.get(
            "KUEUE_STREAM_MICROSOLVE_MIN", "64"))
        #: sticky pow2 pad target for micro-solve exports (bounds
        #: lean-kernel recompiles, same discipline as _pad_target)
        self._micro_hwm = 0
        #: subscribed ExportCache for micro-solve exports (memoized
        #: row building); its columnar view is disabled — the micro
        #: path always exports classic against the pinned window
        #: snapshot
        self._micro_cache = None
        #: a full solve must have completed since the last contending
        #: epoch before any micro-drain runs (the parity baseline)
        self.armed = False
        #: ExportCache.spec_gen at arm time — ANY spec event (quota
        #: edit, flavor change, node flap, cohort edit) bumps it, which
        #: fences the whole window (tensors.py dirty tracking)
        self._armed_gen = -1
        self._mu = threading.Lock()
        #: cohort roots contended since the last full solve, stamped
        #: with the fence generation they were raised at — a full
        #: solve clears only fences raised BEFORE it began, so an
        #: event landing mid-solve (which the solve's export never
        #: saw) keeps its subtree fenced for the next one
        self._contended_roots: dict[str, int] = {}
        self._gen = 0
        self._solve_mark = 0
        self._solve_spec_gen = -1
        #: thread running the current full solve: its events (plan
        #: commits, plan evictions) ARE the solve — they land in the
        #: boundary state and must not fence the next window
        self._solve_thread: Optional[int] = None
        #: per-spec-gen derived tables: cq -> root key, root -> members
        self._root_gen = -1
        self._root_of: dict[str, str] = {}
        self._members: dict[str, list[str]] = {}
        #: roots whose subtree structure permits free per-CQ streaming
        #: (singleton, or borrowing disabled throughout)
        self._root_streamable: dict[str, bool] = {}
        #: borrow-capable multi-CQ roots whose members are ALL
        #: statically eligible — these stream through the merged-order
        #: reserved-headroom walk instead of deferring outright
        self._root_merge_ok: dict[str, bool] = {}
        self._eligible_cache: dict[str, bool] = {}
        self._multi_flavor_cache: dict[str, bool] = {}
        #: static zero-usage capacity ceilings per CQ flavor option —
        #: the flavor-pick witness (engine.flavor_witness, cached per
        #: spec generation)
        self._flavor_ceilings: dict[str, dict] = {}
        #: reserved nominal-headroom budgets, cq -> fr -> remaining;
        #: captured from the window snapshot at first touch after each
        #: full solve and drawn down by merged-walk commits
        self._headroom: dict[str, dict] = {}
        #: newest merged-order key admitted per borrow-capable root
        #: this window (the cross-CQ out-of-order fence)
        self._root_floor: dict[str, tuple] = {}
        #: structural fences already recorded to the flight recorder
        #: this spec generation (explain support, one event per cause)
        self._fence_noted: set[tuple[str, str]] = set()
        #: watch-driven drain support: the serve loop registers a
        #: notifier; _on_event signals it on every streamable arrival
        #: so micro-drain latency is event-bound, not tick-bound
        self._notify = None
        self._signal_pending = 0
        #: window snapshot for oracle fit checks, built lazily at the
        #: first micro-drain after arm and mutated incrementally by our
        #: own admissions (contended subtrees never consult it)
        self._snap = None
        #: newest _order_key admitted per CQ this window (the
        #: out-of-order arrival fence)
        self._max_admitted: dict[str, tuple] = {}
        #: thread id whose commit events are self-classification to
        #: suppress — thread-SCOPED, not a process-wide flag: a
        #: controller thread's capacity event arriving mid-commit
        #: must still contend its root
        self._committing_thread: Optional[int] = None
        self._preemptor = Preemptor(enable_fair_sharing=False)
        self.micro_drains = 0
        #: a spec edit (quota/flavor change, node flap) observed
        #: mid-window doesn't just fence the window — it requests the
        #: full solve be pulled FORWARD (the serve loop consumes this
        #: and runs the heavy cycle now): the edit changed capacity
        #: the parked/pending backlog may now fit (or no longer fit),
        #: and waiting out the cadence would serve stale answers
        self.full_solve_pending = False
        store.watch(self._on_event)

    # -- event classification (the safety fence) ---------------------------

    def _on_event(self, event) -> None:
        ident = threading.get_ident()
        if self._committing_thread == ident:
            return  # our own commit: tracked via _max_admitted/_snap
        if self._solve_thread == ident:
            # the full solve's own plan application: part of the
            # boundary state note_full_solve re-arms against
            return
        verb, kind, obj = event
        if kind != "Workload":
            # spec events ride ExportCache.spec_gen (checked per
            # drain); nothing to classify here
            return
        wl = obj
        if (verb != "delete" and wl.active and not wl.is_quota_reserved
                and not wl.is_finished and not wl.is_evicted
                and wl.status.admission is None):
            # pure pending arrival/update: the work we stream — wake
            # the watch-driven drain worker instead of waiting for the
            # serve loop's next tick
            with self._mu:
                self._signal_pending += 1
                cb = self._notify
            if cb is not None:
                cb()
            return
        cq = self.store.cluster_queue_for(wl)
        if cq is None and wl.status.admission is not None:
            cq = wl.status.admission.cluster_queue
        self._contend(cq, "cohort_event")

    def set_arrival_notifier(self, cb) -> None:
        """Register the watch-driven drain wakeup (serve loop). The
        callback runs on the mutating thread — it must only signal."""
        with self._mu:
            self._notify = cb

    def take_arrival_signals(self) -> int:
        """Number of arrival signals since the last take — the drain
        worker coalesces a burst of N signals into one drain and
        accounts the other N-1 as ``watch_coalesced``."""
        with self._mu:
            n = self._signal_pending
            self._signal_pending = 0
            return n

    def _contend(self, cq: Optional[str], reason: str) -> None:
        with self._mu:
            self._gen += 1
            if cq is None:
                # unresolvable owner: fence everything (rare — a
                # deleted LQ mid-flight)
                self.armed = False
            else:
                self._contended_roots[self._root(cq)] = self._gen
        metrics.stream_demotions_total.inc(reason)
        if cq is None:
            resilience.controller.report(
                resilience.STREAMING, "stream_off", True,
                reason=f"window disarmed ({reason}): event owner "
                       "unresolvable; full fence")

    # -- per-spec-gen derived tables ---------------------------------------

    def _refresh_tables(self) -> None:
        gen = self.engine.export_cache.spec_gen
        if self._root_gen == gen:
            return
        self._root_gen = gen
        self._root_of = {}
        self._members = {}
        self._eligible_cache = {}
        self._multi_flavor_cache = {}
        self._fence_noted = set()
        #: the flavor-pick witness for this spec generation (static
        #: zero-usage ceilings the multi-flavor fence checks against)
        self._flavor_ceilings = self.engine.flavor_witness()
        roots: dict[str, str] = {}

        def root_of_cohort(name: str) -> str:
            if name in roots:
                return roots[name]
            seen = set()
            cur = name
            while True:
                if cur in seen:
                    break
                seen.add(cur)
                spec_c = self.store.cohorts.get(cur)
                if spec_c is None or not spec_c.parent:
                    break
                cur = spec_c.parent
            roots[name] = cur
            return cur

        for name, spec in self.store.cluster_queues.items():
            root = (f"cohort:{root_of_cohort(spec.cohort)}"
                    if spec.cohort else f"cq:{name}")
            self._root_of[name] = root
            self._members.setdefault(root, []).append(name)
        # structural borrowing fence: a multi-CQ subtree streams only
        # when NO member can borrow (zero borrowing limits => the
        # members are capacity-independent and per-CQ greedy order is
        # the joint batch order); a singleton subtree always may (its
        # borrowing races nobody)
        self._root_streamable = {}
        for root, members in self._members.items():
            if len(members) == 1:
                self._root_streamable[root] = True
                continue
            self._root_streamable[root] = all(
                not _can_borrow(self.store.cluster_queues[m])
                for m in members)
        # borrow-capable multi-CQ subtrees stream via the merged-order
        # reserved-headroom walk — but only when every member is
        # statically eligible (one ineligible member's full-solve
        # admissions would interleave with streamed ones)
        self._root_merge_ok = {}
        for root, members in self._members.items():
            if self._root_streamable[root]:
                continue
            self._root_merge_ok[root] = all(
                self._static_eligible(m) for m in members)

    def _root(self, cq: str) -> str:
        self._refresh_tables()
        return self._root_of.get(cq, f"cq:{cq}")

    def _static_eligible(self, name: str) -> bool:
        """Oracle-modelable CQ (cached per spec generation): no
        preemption, no admission-scope AFS, no TAS flavors. Multi-
        flavor-option CQs are eligible — the per-pick flavor witness
        (``_pick_stable``) guards their determinism at walk time —
        but only under the DEFAULT flavor fungibility: a non-default
        early-stop policy (TryNextFlavor on borrow, preemption
        preference) makes the pick depend on capacity margins of
        LATER flavors too, which the zero-usage witness cannot
        bound."""
        cached = self._eligible_cache.get(name)
        if cached is not None:
            return cached
        from kueue_oss_tpu import features
        from kueue_oss_tpu.api.types import (
            FlavorFungibilityPolicy,
        )

        spec = self.store.cluster_queues.get(name)
        ok = (spec is not None
              and not spec.preemption.any_enabled
              and not (spec.admission_scope is not None
                       and self.queues.afs is not None)
              and not self.engine._is_tas_cq(name))
        if ok and self._cq_multi_flavor(name) and features.enabled(
                "FlavorFungibility"):
            fung = spec.flavor_fungibility
            ok = (fung.when_can_borrow == FlavorFungibilityPolicy.BORROW
                  and fung.when_can_preempt
                  == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
                  and fung.preference is None)
        self._eligible_cache[name] = ok
        return ok

    def _cq_multi_flavor(self, name: str) -> bool:
        """Whether any resource group of this CQ offers a flavor
        choice (cached per spec generation)."""
        cached = self._multi_flavor_cache.get(name)
        if cached is not None:
            return cached
        spec = self.store.cluster_queues.get(name)
        multi = (spec is not None and any(
            len(rg.flavors) > 1 for rg in spec.resource_groups))
        self._multi_flavor_cache[name] = multi
        return multi

    # -- window lifecycle --------------------------------------------------

    def note_solve_begin(self) -> None:
        """Called by the engine right before a full solve: records
        the fence generation and spec generation the solve's export
        can possibly cover. Events landing after this mark survive
        note_full_solve — the solve never saw them."""
        with self._mu:
            self._solve_mark = self._gen
            self._solve_spec_gen = self.engine.export_cache.spec_gen
            self._solve_thread = threading.get_ident()

    def note_full_solve(self) -> None:
        """A full solve completed: fences raised before it began
        reset and the next window opens against the post-solve store
        (the oracle-parity baseline boundary). Fences and spec bumps
        from mid-solve events stay — they defer to the NEXT solve."""
        with self._mu:
            self.armed = True
            self._armed_gen = self._solve_spec_gen
            self._solve_thread = None
            self._contended_roots = {
                root: g for root, g in self._contended_roots.items()
                if g > self._solve_mark}
            self._snap = None
            self._max_admitted.clear()
            # the boundary re-reserves the headroom budgets and
            # resets the merged-order floors: the new window opens
            # against post-solve usage
            self._headroom.clear()
            self._root_floor.clear()
            contended = bool(self._contended_roots)
        # degradation ladder: a completed full solve re-arms the window
        # (stream_off clears); structural fences survive only for roots
        # contended mid-solve
        ctl = resilience.controller
        if ctl.active(resilience.STREAMING, "stream_off"):
            ctl.report(resilience.STREAMING, "stream_off", False,
                       reason="full solve completed; window re-armed")
        if not contended and ctl.active(resilience.STREAMING,
                                        "structural_fence"):
            ctl.report(resilience.STREAMING, "structural_fence", False,
                       reason="full solve cleared every contended root")

    def note_solve_abort(self) -> None:
        """The solve failed (host fallback): stop attributing events
        to it; every fence it raised stays down until a COMPLETED
        solve re-arms."""
        with self._mu:
            self._solve_thread = None

    def _window_snapshot(self):
        if self._snap is None:
            from kueue_oss_tpu.core.snapshot import build_snapshot

            self._snap = build_snapshot(self.store)
        return self._snap

    # -- the micro-drain ---------------------------------------------------

    def drain(self, now: float = 0.0) -> MicroDrainResult:
        """Admit in-order arrivals for every uncontended fast-path CQ.

        Runs between full solves; O(pending-in-eligible-CQs), never
        O(store) beyond the one lazily built window snapshot."""
        result = MicroDrainResult()
        if not self.enabled or not self.armed:
            return result
        if self.engine.enable_fair_sharing:
            return result
        if self.engine.export_cache.spec_gen != self._armed_gen:
            # quota edit / flavor change / node flap since arm: fence
            # the whole window AND request an immediate full solve —
            # consume_full_solve_request() tells the serve loop to run
            # the heavy cycle now rather than on its natural cadence
            with self._mu:
                self.armed = False
                self.full_solve_pending = True
            metrics.stream_demotions_total.inc("spec_change")
            resilience.controller.report(
                resilience.STREAMING, "stream_off", True,
                reason="spec generation changed mid-window; streaming "
                       "disarmed pending a full solve")
            return result
        t0 = time.perf_counter()
        self.micro_drains += 1
        pending = self.queues.cqs_with_pending()
        if not pending:
            metrics.stream_microdrains_total.inc("idle")
            return result
        self._refresh_tables()
        with self._mu:
            contended = set(self._contended_roots)
        considered = 0
        by_root: dict[str, list[str]] = {}
        for name in pending:
            root = self._root_of.get(name, f"cq:{name}")
            by_root.setdefault(root, []).append(name)
        #: streamable CQs this drain, routed by burst size: large
        #: coalesced bursts go through ONE device micro-solve, small
        #: ones (and every multi-flavor CQ) through the host walk
        micro_cqs: list[tuple[str, str]] = []
        host_cqs: list[tuple[str, str]] = []
        for root, names in by_root.items():
            if result.admitted + result.parked >= self.max_batch:
                break
            considered += len(names)
            if root in contended:
                result.deferred_cqs += len(names)
                continue
            flooded = False
            for name in names:
                q = self.queues.queues.get(name)
                if (q is not None
                        and len(q._in_heap) > 4 * self.max_batch):
                    # a flood-sized heap is the batched solver's job
                    # (the scheduler's solver_min_backlog routing);
                    # walking it entry-by-entry here would stall the
                    # serve loop
                    flooded = True
                    break
            if flooded:
                result.deferred_cqs += len(names)
                continue
            if self._root_streamable.get(root, False):
                for name in names:
                    if not self._static_eligible(name):
                        result.deferred_cqs += 1
                        self._note_structural(name, "ineligible")
                        continue
                    # single-flavor CQs may batch into the device
                    # micro-solve; multi-flavor picks stay on the
                    # host walk where the per-pick witness
                    # (_pick_stable) guards their determinism
                    if (self.micro_solve
                            and not self._cq_multi_flavor(name)):
                        micro_cqs.append((name, root))
                    else:
                        host_cqs.append((name, root))
                continue
            # borrow-capable multi-CQ subtree: streams through the
            # merged-order reserved-headroom walk when every member
            # is statically eligible; otherwise only the full solve
            # reproduces the joint order
            if not self._root_merge_ok.get(root, False):
                result.deferred_cqs += len(names)
                metrics.stream_demotions_total.inc("borrow_capable")
                for name in names:
                    self._note_structural(name, "borrow_capable")
                continue
            if not self._drain_root(root, names, now, result):
                contended.add(root)  # demoted mid-walk
        if micro_cqs:
            total = sum(
                len(q._in_heap) for q in
                (self.queues.queues.get(n) for n, _ in micro_cqs)
                if q is not None)
            if total >= self.micro_solve_min:
                self._drain_micro(micro_cqs, now, result, contended)
            else:
                # small burst: the per-entry host oracle walk is
                # cheaper than a kernel dispatch (and doubles as the
                # micro-solve's parity oracle)
                host_cqs = micro_cqs + host_cqs
        for name, root in host_cqs:
            if root in contended:
                continue
            if result.admitted + result.parked >= self.max_batch:
                break
            if not self._drain_cq(name, root, now, result):
                contended.add(root)  # demoted mid-walk
        if considered:
            metrics.stream_eligible_fraction.set(value=max(
                0.0, 1.0 - result.deferred_cqs / considered))
        result.duration_s = time.perf_counter() - t0
        metrics.stream_microdrains_total.inc(
            "admitted" if result.admitted else
            ("parked" if result.parked else
             ("deferred" if result.deferred_cqs else "idle")))
        # degradation ladder: deferrals mean part of the fleet runs on
        # the structural (full-solve-only) rung; a clean drain over a
        # non-empty set recovers it
        ctl = resilience.controller
        if result.deferred_cqs:
            ctl.report(
                resilience.STREAMING, "structural_fence", True,
                reason=f"{result.deferred_cqs} CQ(s) deferred to the "
                       "next full solve behind structural fences")
        elif considered and ctl.active(resilience.STREAMING,
                                       "structural_fence"):
            ctl.report(resilience.STREAMING, "structural_fence", False,
                       reason="micro-drain covered every eligible CQ")
        if result.admitted:
            self._record_ledger(result)
            p = getattr(self.store, "persistence", None)
            if p is not None:
                # sub-cycle durability barrier: the micro-batch's
                # intents + events group-commit now, and the
                # (incremental) checkpoint cadence gets its look —
                # this is what makes sub-second cadences affordable
                p.flush()
        return result

    def _drain_cq(self, name: str, root: str, now: float,
                  result: MicroDrainResult) -> bool:
        """Greedy in-order walk of one CQ's heap. Returns False when
        the CQ demoted itself (out-of-order arrival, preempt-needed,
        unsupported shape) — the caller fences its root for the rest
        of this drain; the sticky fence rides ``_contended_roots``."""
        from kueue_oss_tpu import features
        from kueue_oss_tpu.api.types import QueueingStrategy

        q = self.queues.queues.get(name)
        if q is None:
            return True
        strict = q.strategy == QueueingStrategy.STRICT_FIFO
        ca_gate = features.enabled("ConcurrentAdmission")
        snap = self._window_snapshot()
        cq_snap = snap.cluster_queue(name)
        if cq_snap is None:
            return True
        floor = self._max_admitted.get(name)
        multi = self._cq_multi_flavor(name)
        for info in q.snapshot_order():
            # max_batch bounds PROCESSED entries (admits + parks), not
            # just admissions — one micro-drain must never walk an
            # unbounded no-fit backlog; the remainder keeps its order
            # for the next tick (or the full solve)
            if result.admitted + result.parked >= self.max_batch:
                return True
            with self._mu:
                # live fence re-check per entry: a controller thread
                # may contend this root mid-walk (capacity freed);
                # committing past that point would stream into state
                # the batch oracle would re-order
                if root in self._contended_roots or not self.armed:
                    return True
            key = _order_key(info)
            if floor is not None and key < floor:
                # out-of-order arrival: the batch oracle would have
                # sorted it before admissions already committed this
                # window — demote before processing it
                self._fence_event(info.key, name, "out_of_order")
                self._contend(name, "out_of_order")
                return False
            wl = self.store.workloads.get(info.key)
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            if any(ps.topology_request is not None for ps in wl.podsets):
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "topology_request"})
                self._contend(name, "unsupported")
                return False
            if ca_gate and wl.parent_workload is not None:
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "concurrent_admission"})
                self._contend(name, "unsupported")
                return False
            if multi and len(wl.podsets) > 1:
                # the flavor witness bounds single-podset picks only:
                # grouped multi-podset assignment shares flavors in
                # ways the per-resource ceilings don't model
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "multi_flavor_multi_podset"})
                self._contend(name, "unsupported")
                return False
            fresh = WorkloadInfo(wl, cluster_queue=name)
            assigner = FlavorAssigner(
                fresh, cq_snap, snap.resource_flavors,
                oracle=self._preemptor, enable_fair_sharing=False)
            assignment = assigner.assign()
            mode = assignment.representative_mode()
            if mode == fa.FIT:
                if multi and not self._pick_stable(
                        name, wl, cq_snap, snap, assignment):
                    self._fence_event(
                        info.key, name, "flavor_witness_invalid")
                    self._contend(name, "flavor_witness_invalid")
                    return False
                self._commit(wl, name, fresh, assignment, now, result)
                floor = key
                self._max_admitted[name] = key
                continue
            # NO_FIT, or PREEMPT on a CQ whose policies are all Never
            # (static eligibility excludes preemption-capable CQs, so
            # "fits only by preempting" is a lean-kernel park) —
            # kernel parity: BestEffortFIFO parks and walks on; a
            # blocked StrictFIFO head blocks the queue
            if strict:
                return True
            q.park(info.key)
            result.parked += 1
            obs.recorder.record(
                obs.SKIPPED, info.key, cycle=self._cycle(),
                cluster_queue=name, path=obs.STREAM,
                reason="parked inadmissible by the streaming fast "
                       "path: no flavor option fits at current "
                       "capacity",
                reason_slug="stream_parked")
        return True

    def _drain_root(self, root: str, names: list[str], now: float,
                    result: MicroDrainResult) -> bool:
        """Merged-order walk of a borrow-capable multi-CQ subtree
        under the reserved-headroom protocol: every member's pending
        entries are walked as one sequence in global ``_order_key``
        order (the batch oracle's cohort interleave), each admission
        must fit its CQ's reserved nominal-headroom budget with a
        zero borrowing level, and the first entry that would need
        borrowed capacity fences the subtree to the full solve.
        Returns False when the subtree demoted itself mid-walk."""
        from kueue_oss_tpu import features
        from kueue_oss_tpu.api.types import QueueingStrategy

        snap = self._window_snapshot()
        entries: list[tuple] = []
        lanes: dict[str, tuple] = {}
        for name in names:
            q = self.queues.queues.get(name)
            if q is None:
                continue
            cq_snap = snap.cluster_queue(name)
            if cq_snap is None:
                continue
            # reserve the budget before the first commit can land
            self._headroom_budget(name, cq_snap)
            lanes[name] = (q, cq_snap)
            for info in q.snapshot_order():
                entries.append((name, info))
        entries.sort(key=lambda e: _order_key(e[1]))
        ca_gate = features.enabled("ConcurrentAdmission")
        blocked: set[str] = set()
        floor = self._root_floor.get(root)
        for name, info in entries:
            if result.admitted + result.parked >= self.max_batch:
                return True
            if name in blocked:
                continue
            with self._mu:
                if root in self._contended_roots or not self.armed:
                    return True
            q, cq_snap = lanes[name]
            key = _order_key(info)
            if floor is not None and key < floor:
                # cross-CQ out-of-order arrival: the batch oracle
                # would interleave it before an admission another
                # member already committed this window
                self._fence_event(info.key, name, "out_of_order")
                self._contend(name, "out_of_order")
                return False
            wl = self.store.workloads.get(info.key)
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            if any(ps.topology_request is not None
                   for ps in wl.podsets):
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "topology_request"})
                self._contend(name, "unsupported")
                return False
            if ca_gate and wl.parent_workload is not None:
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "concurrent_admission"})
                self._contend(name, "unsupported")
                return False
            multi = self._cq_multi_flavor(name)
            if multi and len(wl.podsets) > 1:
                self._fence_event(info.key, name, "unsupported",
                                  {"check": "multi_flavor_multi_podset"})
                self._contend(name, "unsupported")
                return False
            fresh = WorkloadInfo(wl, cluster_queue=name)
            assigner = FlavorAssigner(
                fresh, cq_snap, snap.resource_flavors,
                oracle=self._preemptor, enable_fair_sharing=False)
            assignment = assigner.assign()
            mode = assignment.representative_mode()
            if mode == fa.FIT:
                if (assignment.borrows()
                        or not self._headroom_admits(name, assignment)):
                    # the admission would consume borrowed capacity
                    # (or overrun the reserved budget): borrowing is
                    # a full-solve-only decision — fence the subtree
                    # until the next solve resolves it jointly
                    self._fence_event(
                        info.key, name, "headroom_exhausted",
                        {"borrows": assignment.borrows()})
                    self._contend(name, "headroom_exhausted")
                    return False
                if multi and not self._pick_stable(
                        name, wl, cq_snap, snap, assignment):
                    self._fence_event(
                        info.key, name, "flavor_witness_invalid")
                    self._contend(name, "flavor_witness_invalid")
                    return False
                self._headroom_consume(name, assignment)
                self._commit(wl, name, fresh, assignment, now, result)
                floor = key
                self._root_floor[root] = key
                prev = self._max_admitted.get(name)
                if prev is None or key > prev:
                    self._max_admitted[name] = key
                continue
            # NO_FIT / lean-kernel park: a blocked StrictFIFO head
            # blocks only its own lane — the batch interleave keeps
            # walking the other members
            if q.strategy == QueueingStrategy.STRICT_FIFO:
                blocked.add(name)
                continue
            q.park(info.key)
            result.parked += 1
            obs.recorder.record(
                obs.SKIPPED, info.key, cycle=self._cycle(),
                cluster_queue=name, path=obs.STREAM,
                reason="parked inadmissible by the streaming fast "
                       "path: no flavor option fits at current "
                       "capacity",
                reason_slug="stream_parked")
        return True

    # -- device micro-solve ------------------------------------------------

    def _micro_export_cache(self):
        if self._micro_cache is None:
            from kueue_oss_tpu.solver.tensors import ExportCache

            self._micro_cache = ExportCache(self.store)
            # micro exports always run classic against the pinned
            # window snapshot; drop the columnar view so this cache
            # only pays for memoized row building
            self._micro_cache.columnar = None
        return self._micro_cache

    def _drain_micro(self, cqs: list[tuple[str, str]], now: float,
                     result: MicroDrainResult,
                     contended: set[str]) -> None:
        """Batch a streamed burst into ONE lean-kernel micro-solve.

        Every per-entry fence the host walk applies (out-of-order
        floor, topology request, concurrent-admission variant) is
        re-checked host-side while building the batch, and the
        kernel's plan decodes through the same opt -> flavor mapping
        as ``SolverEngine._apply_plan`` — so the committed store
        state is bit-identical to walking the same entries through
        the host FlavorAssigner (the small-burst path below
        ``micro_solve_min``, which doubles as the parity oracle).
        Multi-flavor CQs never route here; their picks keep the
        per-pick witness on the host path. The export pins the window
        snapshot, so usage from this window's earlier streamed
        commits is visible to the kernel exactly as the host oracle
        would see it, and no delta session is touched (no session
        reset; the admissions dirty their ExportCache rows like any
        other store event and ship as normal deltas next full solve).
        """
        from kueue_oss_tpu import features
        from kueue_oss_tpu.solver.kernels import solve_backlog, to_device
        from kueue_oss_tpu.solver.tensors import (
            export_problem, pad_workloads,
        )

        t0 = time.perf_counter()
        ca_gate = features.enabled("ConcurrentAdmission")
        snap = self._window_snapshot()
        budget = self.max_batch - (result.admitted + result.parked)
        batch: dict[str, list[WorkloadInfo]] = {}
        root_of_batch: dict[str, str] = {}
        for name, root in cqs:
            if budget <= 0:
                break
            if root in contended:
                result.deferred_cqs += 1
                continue
            q = self.queues.queues.get(name)
            if q is None or snap.cluster_queue(name) is None:
                continue
            floor = self._max_admitted.get(name)
            infos: list[WorkloadInfo] = []
            demoted = False
            for info in q.snapshot_order():
                if len(infos) >= budget:
                    break
                key = _order_key(info)
                if floor is not None and key < floor:
                    self._fence_event(info.key, name, "out_of_order")
                    self._contend(name, "out_of_order")
                    contended.add(root)
                    demoted = True
                    break
                wl = self.store.workloads.get(info.key)
                if wl is None or wl.is_quota_reserved or not wl.active:
                    continue
                if any(ps.topology_request is not None
                       for ps in wl.podsets):
                    self._fence_event(info.key, name, "unsupported",
                                      {"check": "topology_request"})
                    self._contend(name, "unsupported")
                    contended.add(root)
                    demoted = True
                    break
                if ca_gate and wl.parent_workload is not None:
                    self._fence_event(info.key, name, "unsupported",
                                      {"check": "concurrent_admission"})
                    self._contend(name, "unsupported")
                    contended.add(root)
                    demoted = True
                    break
                infos.append(info)
            if demoted or not infos:
                continue
            batch[name] = infos
            root_of_batch[name] = root
            budget -= len(infos)
        if not batch:
            return
        problem = export_problem(
            self.store, batch, snapshot=snap, now=now,
            cache=self._micro_export_cache(), columnar=False)
        W = problem.n_workloads
        if not W:
            return
        target = 1 << max(6, (W - 1).bit_length())
        self._micro_hwm = max(self._micro_hwm, target)
        padded = pad_workloads(problem, self._micro_hwm)
        t1 = time.perf_counter()
        out = solve_backlog(to_device(padded))
        admitted, opt, admit_round, parked = (
            np.asarray(a) for a in out[:4])
        t2 = time.perf_counter()
        result.micro_batch += W
        result.micro_export_s += t1 - t0
        result.micro_solve_s += t2 - t1
        self._commit_plan(padded, admitted, opt, admit_round, parked,
                          root_of_batch, now, result)

    def _commit_plan(self, problem, admitted, opt, admit_round,
                     parked, root_of_batch: dict[str, str],
                     now: float, result: MicroDrainResult) -> None:
        """Decode and commit the micro-solve plan — the streaming
        twin of ``SolverEngine._apply_plan``: admissions in (round,
        entry) order through the engine commit, kernel park decisions
        mirrored into the heaps, StrictFIFO blocked heads untouched."""
        W = problem.n_workloads
        adm = np.nonzero(admitted[:-1])[0]
        order = adm[np.argsort(admit_round[adm], kind="stable")]
        declared_of: dict[str, set] = {}
        for w in order:
            key = problem.wl_keys[w]
            if not key:
                continue
            name = problem.cq_names[problem.wl_cqid[w]]
            root = root_of_batch.get(name)
            if root is None:
                continue
            with self._mu:
                # live fence re-check per commit: a controller thread
                # may have contended this root mid-solve — committing
                # past that point would stream into state the batch
                # oracle would re-order
                if root in self._contended_roots or not self.armed:
                    continue
            wl = self.store.workloads.get(key)
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            flavor = problem.cq_option_flavors[name][opt[w]]
            info = WorkloadInfo(wl, cluster_queue=name)
            flavor_of = {r: flavor for psr in info.total_requests
                         for r in psr.requests}
            declared = declared_of.get(name)
            if declared is None:
                declared = {
                    r for rg in
                    self.store.cluster_queues[name].resource_groups
                    for r in rg.covered_resources}
                declared_of[name] = declared
            usage: dict[tuple[str, str], int] = {}
            for psr in info.total_requests:
                for r, qty in psr.requests.items():
                    if r not in declared:
                        continue  # QuotaCheckStrategy=IgnoreUndeclared
                    fr = (flavor, r)
                    usage[fr] = usage.get(fr, 0) + qty
            self._commit_entry(wl, name, info, flavor_of, usage,
                               now, result)
            key_o = _order_key(info)
            prev = self._max_admitted.get(name)
            if prev is None or key_o > prev:
                self._max_admitted[name] = key_o
        for w in np.nonzero(parked[:W])[0]:
            key = problem.wl_keys[w]
            if not key:
                continue
            name = problem.cq_names[problem.wl_cqid[w]]
            root = root_of_batch.get(name)
            if root is None:
                continue
            with self._mu:
                if root in self._contended_roots or not self.armed:
                    continue
            q = self.queues.queues.get(name)
            if q is None:
                continue
            q.park(key)
            result.parked += 1
            obs.recorder.record(
                obs.SKIPPED, key, cycle=self._cycle(),
                cluster_queue=name, path=obs.STREAM,
                reason="parked inadmissible by the streaming fast "
                       "path: no flavor option fits at current "
                       "capacity",
                reason_slug="stream_parked")

    # -- wide-fence support: witness, headroom, explain events -------------

    def _pick_stable(self, name: str, wl, cq_snap, snap,
                     assignment) -> bool:
        """The multi-flavor determinism witness: True when NO
        capacity event could make the batch oracle prefer an
        earlier-preference flavor over the pick just made — every
        earlier option is either statically incompatible (taint,
        selector, TAS shape, variant pin) or exceeds its static
        zero-usage capacity ceiling for some covered resource, so
        freeing capacity cannot surface it."""
        ceilings = self._flavor_ceilings.get(name) or {}
        ps = wl.podsets[0]
        checked: set[tuple[int, str]] = set()
        for psa in assignment.podsets:
            for res, rec in psa.flavors.items():
                rg = cq_snap.rg_by_resource(res)
                if rg is None:
                    return False
                if len(rg.flavors) <= 1:
                    continue
                mark = (id(rg), rec.name)
                if mark in checked:
                    continue
                checked.add(mark)
                order = [fq.name for fq in rg.flavors]
                try:
                    k = order.index(rec.name)
                except ValueError:
                    return False
                if k == 0:
                    continue
                allowed_keys = frozenset(
                    lk for fname in order
                    for lk in self._flavor_labels(snap, fname))
                covered = [(r, v) for r, v in psa.requests.items()
                           if r in rg.covered_resources]
                for g in order[:k]:
                    if (wl.allowed_flavor is not None
                            and g != wl.allowed_flavor):
                        continue  # variant-pinned away: static
                    flavor = snap.resource_flavors.get(g)
                    if flavor is None:
                        continue
                    if fa._untolerated_taint(ps, flavor) is not None:
                        continue
                    if not fa._selector_matches(ps, flavor,
                                                allowed_keys):
                        continue
                    if fa.tas_flavor_mismatch(
                            ps, flavor, cq_snap) is not None:
                        continue
                    # a compatible earlier option: the pick is stable
                    # only if the request tops the option's ceiling
                    # even on an empty hierarchy
                    if not any(v > ceilings.get((g, r), 0)
                               for r, v in covered):
                        return False
        return True

    @staticmethod
    def _flavor_labels(snap, fname: str):
        flavor = snap.resource_flavors.get(fname)
        return flavor.node_labels if flavor is not None else {}

    def _headroom_budget(self, name: str, cq_snap) -> dict:
        """The reserved nominal-headroom budget for one CQ, captured
        lazily from the window snapshot at first touch after the full
        solve (= the boundary's unused nominal) and drawn down by
        merged-walk commits. Mate commits never touch it: within-
        nominal usage lives on the CQ's own quota node."""
        budget = self._headroom.get(name)
        if budget is None:
            budget = {}
            spec = self.store.cluster_queues.get(name)
            if spec is not None:
                for rg in spec.resource_groups:
                    for fq in rg.flavors:
                        for rq in fq.resources:
                            fr = (fq.name, rq.name)
                            used = cq_snap.node.usage.get(fr, 0)
                            budget[fr] = max(0, rq.nominal - used)
            self._headroom[name] = budget
        return budget

    def _headroom_admits(self, name: str, assignment) -> bool:
        budget = self._headroom.get(name) or {}
        return all(v <= budget.get(fr, 0)
                   for fr, v in assignment.usage_quota.items())

    def _headroom_consume(self, name: str, assignment) -> None:
        budget = self._headroom.get(name)
        if budget is None:
            return
        for fr, v in assignment.usage_quota.items():
            budget[fr] = max(0, budget.get(fr, 0) - v)

    def _fence_event(self, key: str, cq: str, fence: str,
                     detail: Optional[dict] = None) -> None:
        """Flight-recorder trail for tools/explain.py: WHY a workload
        did not stream (which fence demoted it)."""
        d = {"fence": fence, "root": self._root_of.get(cq, f"cq:{cq}")}
        if detail:
            d.update(detail)
        obs.recorder.record(
            obs.SKIPPED, key, cycle=self._cycle(), cluster_queue=cq,
            path=obs.STREAM, reason=_FENCE_TEXT.get(fence, fence),
            reason_slug=f"stream_fence_{fence}", detail=d)

    def _note_structural(self, name: str, fence: str) -> None:
        """Record a structural (per-spec-generation) fence once per
        CQ against its current queue head, so explain can answer
        "why is this stuck on the slow path" without a per-drain
        event flood."""
        if (name, fence) in self._fence_noted:
            return
        self._fence_noted.add((name, fence))
        q = self.queues.queues.get(name)
        if q is None or not q._in_heap:
            return
        head = min(q._in_heap.values(), key=_order_key)
        self._fence_event(head.key, name, fence)

    def _cycle(self) -> int:
        sched = self.engine.scheduler
        return (sched.cycle_count + 1 if sched is not None
                else self.engine.drain_count + 1)

    def _commit(self, wl, name: str, info: WorkloadInfo, assignment,
                now: float, result: MicroDrainResult) -> None:
        flavor_of: dict[str, str] = {}
        for psa in assignment.podsets:
            for r, rec in psa.flavors.items():
                flavor_of[r] = rec.name
        self._commit_entry(wl, name, info, flavor_of,
                           dict(assignment.usage_quota), now, result)

    def _commit_entry(self, wl, name: str, info: WorkloadInfo,
                      flavor_of: dict[str, str],
                      usage_quota: dict, now: float,
                      result: MicroDrainResult) -> None:
        """Engine commit shared by the host-walk (assignment-decoded)
        and micro-solve (plan-decoded) paths."""
        drain_result = _EngineResultAdapter()
        self.engine._drain_cycle = self._cycle()
        self.engine.last_drain_arm = "stream"
        self._committing_thread = threading.get_ident()
        t0 = time.perf_counter()
        try:
            self.engine._commit_admission(
                wl, name, flavor_of, info, now, drain_result)
        finally:
            self._committing_thread = None
            result.commit_s += time.perf_counter() - t0
        # keep the window snapshot current so the next entry's fit
        # check sees this admission's usage (the kernel's in-round
        # usage refresh, host-side)
        cq_snap = self._snap.cluster_queue(name)
        if cq_snap is not None:
            cq_snap.add_usage(dict(usage_quota))
        result.admitted += drain_result.admitted
        result.admitted_keys.extend(drain_result.admitted_keys)
        metrics.stream_admitted_total.inc(by=drain_result.admitted)

    def _record_ledger(self, result: MicroDrainResult) -> None:
        ledger = obs.cycle_ledger
        if not ledger.enabled:
            return
        phases = {"stream": round(result.duration_s, 6)}
        detail: dict = {"deferredCqs": result.deferred_cqs}
        if result.micro_batch:
            phases["micro_export"] = round(result.micro_export_s, 6)
            phases["micro_solve"] = round(result.micro_solve_s, 6)
            detail["microBatch"] = result.micro_batch
        ledger.record(
            self._cycle(), obs.STREAM_DRAIN,
            breaker=obs.breaker_state_name(),
            duration_s=result.duration_s,
            phases=phases,
            admitted=result.admitted, parked=result.parked,
            solver_arm="stream",
            detail=detail)

    def consume_full_solve_request(self) -> bool:
        """True at most once per spec-change fence: drain() observed a
        spec edit mid-window and the caller (the serve loop) should
        run the full cycle immediately instead of skipping it."""
        with self._mu:
            pending = self.full_solve_pending
            self.full_solve_pending = False
            return pending

    # -- introspection -----------------------------------------------------

    def contended(self) -> set[str]:
        with self._mu:
            return set(self._contended_roots)

    def status(self) -> dict:
        gen, keys, cqs = self.engine.export_cache.dirty_snapshot()
        with self._mu:
            return {"armed": self.armed,
                    "contendedRoots": sorted(self._contended_roots),
                    "specGen": gen, "armedGen": self._armed_gen,
                    "dirtyKeys": len(keys), "dirtyCqs": len(cqs),
                    "microDrains": self.micro_drains,
                    "microSolve": self.micro_solve,
                    "microSolveMin": self.micro_solve_min,
                    "mergedRoots": sorted(
                        r for r, ok in self._root_merge_ok.items()
                        if ok),
                    "headroom": {
                        cq: {f"{fr[0]}/{fr[1]}": v
                             for fr, v in budget.items()}
                        for cq, budget in self._headroom.items()},
                    "pendingArrivalSignals": self._signal_pending}


def _can_borrow(spec) -> bool:
    """Whether any flavor quota of this CQ permits borrowing
    (borrowing_limit None = unlimited, the kueue default)."""
    for rg in spec.resource_groups:
        for fq in rg.flavors:
            for rq in fq.resources:
                if rq.borrowing_limit is None or rq.borrowing_limit > 0:
                    return True
    return False


class _EngineResultAdapter:
    """Duck-typed DrainResult stand-in for _commit_admission."""

    def __init__(self) -> None:
        self.admitted = 0
        self.admitted_keys: list[str] = []
