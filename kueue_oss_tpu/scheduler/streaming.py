"""Streaming control plane: micro-batched sub-cycle admission drains.

The cycle-batch model re-exports and re-solves the world every cycle,
so at sustained high arrival rates p50 time-to-admit is dominated by
the full-solve cadence — the batch-vs-continuous trade-off quantified
in arXiv 1106.4985. This module decouples them: between full solves,
new arrivals to **uncontended** ClusterQueues are admitted sub-cycle
through a compact per-CQ fast path, while anything whose outcome could
depend on cross-CQ ordering defers to the next full solve.

Soundness model (docs/ARCHITECTURE.md "Streaming dataflow"):

- A CQ is *fast-path eligible* only when the lean (fit-only) kernel
  would model it: no preemption policies, a single resource group, no
  fair sharing, no admission-scope AFS, no TAS flavors. For such CQs a
  greedy in-order walk of the pending heap — admit the head while the
  host flavor-assigner oracle says FIT, park BestEffortFIFO no-fits,
  stop at a blocked StrictFIFO head — is exactly the per-CQ behavior
  of the batched lean solve (the established kernel↔oracle parity).
- Cross-CQ coupling happens only through cohort **borrowing**, and
  the batch oracle interleaves cohort-mates round-by-round — an
  interleave no event-time fence can reproduce after the fact. So the
  borrowing fence is *structural*: a CQ streams only when it is the
  sole CQ in its cohort root's subtree (it may then borrow freely —
  nobody races it), or when every CQ in the subtree has borrowing
  disabled (zero borrowing limits make cohort-mates capacity-
  independent, so per-CQ greedy order IS the joint order). Borrow-
  capable multi-CQ subtrees always take the full solve.
- On top of that, any cohort-crossing event — an eviction/finish/
  preemption candidate (capacity freed), a quota or flavor edit, a
  node flap (all spec events bump ``ExportCache.spec_gen``), an
  admission by any other path — marks the subtree **contended** until
  the next full solve.
- Within one CQ, the cycle-batch oracle reorders a whole inter-solve
  window by ``_order_key`` (priority, then FIFO). Streaming admits in
  arrival order, which matches the batch order exactly while arrivals
  are order-monotone; an **out-of-order arrival** (one sorting before
  a workload already admitted this window) demotes the CQ before it
  is processed. Admissions already committed before the inversion
  arrived are the one place streaming trades strict window-priority
  for latency — the same trade the cycle-batch model makes for any
  arrival that lands just after a solve boundary closes its batch.

Under those fences the final store state after each full solve is
bit-identical to the pure cycle-batch oracle (the ``streaming``
oracle-parity property test replays randomized arrival/quota/flap
scripts against both twins and byte-compares the canonical dumps at
every boundary).

Commits reuse ``SolverEngine._commit_admission`` — the same store
writes, WAL intents, SLO feed, and flight-recorder events as a solver
drain — so a streaming admission is indistinguishable in durable state
from a batched one. The delta-session slot coordinates stay valid: a
micro-admission just dirties its ExportCache row like any other store
event, and the next full solve ships it as a normal dirty-row delta
(no session reset, resident device tensors untouched).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import metrics, obs
from kueue_oss_tpu.core.queue_manager import _order_key
from kueue_oss_tpu.core.workload_info import WorkloadInfo
from kueue_oss_tpu.scheduler import flavor_assigner as fa
from kueue_oss_tpu.scheduler.flavor_assigner import FlavorAssigner
from kueue_oss_tpu.scheduler.preemption import Preemptor


@dataclass
class MicroDrainResult:
    """Outcome of one micro-batched admission drain."""

    admitted: int = 0
    parked: int = 0
    #: CQs skipped this drain because their subtree is contended, a
    #: sibling holds pending work, or an entry needs the full solve
    deferred_cqs: int = 0
    duration_s: float = 0.0
    admitted_keys: list[str] = field(default_factory=list)


class StreamingAdmitter:
    """Per-CQ sub-cycle admission fast path between full solves.

    One instance per SolverEngine; ``drain()`` runs on the scheduler
    thread, the store-watch classifier may run on any mutating thread
    (controller callbacks), so the contention sets are lock-guarded.
    """

    def __init__(self, store, queues, engine,
                 max_batch: int = 512) -> None:
        self.store = store
        self.queues = queues
        self.engine = engine
        self.enabled = True
        #: admissions per drain() call — bounds one micro-batch's
        #: latency; the remainder stays in order for the next drain
        self.max_batch = max_batch
        #: a full solve must have completed since the last contending
        #: epoch before any micro-drain runs (the parity baseline)
        self.armed = False
        #: ExportCache.spec_gen at arm time — ANY spec event (quota
        #: edit, flavor change, node flap, cohort edit) bumps it, which
        #: fences the whole window (tensors.py dirty tracking)
        self._armed_gen = -1
        self._mu = threading.Lock()
        #: cohort roots contended since the last full solve, stamped
        #: with the fence generation they were raised at — a full
        #: solve clears only fences raised BEFORE it began, so an
        #: event landing mid-solve (which the solve's export never
        #: saw) keeps its subtree fenced for the next one
        self._contended_roots: dict[str, int] = {}
        self._gen = 0
        self._solve_mark = 0
        self._solve_spec_gen = -1
        #: thread running the current full solve: its events (plan
        #: commits, plan evictions) ARE the solve — they land in the
        #: boundary state and must not fence the next window
        self._solve_thread: Optional[int] = None
        #: per-spec-gen derived tables: cq -> root key, root -> members
        self._root_gen = -1
        self._root_of: dict[str, str] = {}
        self._members: dict[str, list[str]] = {}
        #: roots whose subtree structure permits streaming at all
        #: (singleton, or borrowing disabled throughout)
        self._root_streamable: dict[str, bool] = {}
        self._eligible_cache: dict[str, bool] = {}
        #: window snapshot for oracle fit checks, built lazily at the
        #: first micro-drain after arm and mutated incrementally by our
        #: own admissions (contended subtrees never consult it)
        self._snap = None
        #: newest _order_key admitted per CQ this window (the
        #: out-of-order arrival fence)
        self._max_admitted: dict[str, tuple] = {}
        #: thread id whose commit events are self-classification to
        #: suppress — thread-SCOPED, not a process-wide flag: a
        #: controller thread's capacity event arriving mid-commit
        #: must still contend its root
        self._committing_thread: Optional[int] = None
        self._preemptor = Preemptor(enable_fair_sharing=False)
        self.micro_drains = 0
        #: a spec edit (quota/flavor change, node flap) observed
        #: mid-window doesn't just fence the window — it requests the
        #: full solve be pulled FORWARD (the serve loop consumes this
        #: and runs the heavy cycle now): the edit changed capacity
        #: the parked/pending backlog may now fit (or no longer fit),
        #: and waiting out the cadence would serve stale answers
        self.full_solve_pending = False
        store.watch(self._on_event)

    # -- event classification (the safety fence) ---------------------------

    def _on_event(self, event) -> None:
        ident = threading.get_ident()
        if self._committing_thread == ident:
            return  # our own commit: tracked via _max_admitted/_snap
        if self._solve_thread == ident:
            # the full solve's own plan application: part of the
            # boundary state note_full_solve re-arms against
            return
        verb, kind, obj = event
        if kind != "Workload":
            # spec events ride ExportCache.spec_gen (checked per
            # drain); nothing to classify here
            return
        wl = obj
        if (verb != "delete" and wl.active and not wl.is_quota_reserved
                and not wl.is_finished and not wl.is_evicted
                and wl.status.admission is None):
            return  # pure pending arrival/update: the work we stream
        cq = self.store.cluster_queue_for(wl)
        if cq is None and wl.status.admission is not None:
            cq = wl.status.admission.cluster_queue
        self._contend(cq, "cohort_event")

    def _contend(self, cq: Optional[str], reason: str) -> None:
        with self._mu:
            self._gen += 1
            if cq is None:
                # unresolvable owner: fence everything (rare — a
                # deleted LQ mid-flight)
                self.armed = False
            else:
                self._contended_roots[self._root(cq)] = self._gen
        metrics.stream_demotions_total.inc(reason)

    # -- per-spec-gen derived tables ---------------------------------------

    def _refresh_tables(self) -> None:
        gen = self.engine.export_cache.spec_gen
        if self._root_gen == gen:
            return
        self._root_gen = gen
        self._root_of = {}
        self._members = {}
        self._eligible_cache = {}
        roots: dict[str, str] = {}

        def root_of_cohort(name: str) -> str:
            if name in roots:
                return roots[name]
            seen = set()
            cur = name
            while True:
                if cur in seen:
                    break
                seen.add(cur)
                spec_c = self.store.cohorts.get(cur)
                if spec_c is None or not spec_c.parent:
                    break
                cur = spec_c.parent
            roots[name] = cur
            return cur

        for name, spec in self.store.cluster_queues.items():
            root = (f"cohort:{root_of_cohort(spec.cohort)}"
                    if spec.cohort else f"cq:{name}")
            self._root_of[name] = root
            self._members.setdefault(root, []).append(name)
        # structural borrowing fence: a multi-CQ subtree streams only
        # when NO member can borrow (zero borrowing limits => the
        # members are capacity-independent and per-CQ greedy order is
        # the joint batch order); a singleton subtree always may (its
        # borrowing races nobody)
        self._root_streamable = {}
        for root, members in self._members.items():
            if len(members) == 1:
                self._root_streamable[root] = True
                continue
            self._root_streamable[root] = all(
                not _can_borrow(self.store.cluster_queues[m])
                for m in members)

    def _root(self, cq: str) -> str:
        self._refresh_tables()
        return self._root_of.get(cq, f"cq:{cq}")

    def _static_eligible(self, name: str) -> bool:
        """Lean-kernel-shaped, flavor-deterministic CQ (cached per
        spec generation). Single flavor option only: with multiple
        options, a capacity-freeing event between a streamed
        admission and the next full solve could have changed which
        flavor the batch oracle would pick for it — a retroactive
        divergence no fence can undo. Multi-flavor CQs keep the
        full-solve path."""
        cached = self._eligible_cache.get(name)
        if cached is not None:
            return cached
        spec = self.store.cluster_queues.get(name)
        ok = (spec is not None
              and not spec.preemption.any_enabled
              and len(spec.resource_groups) <= 1
              and sum(len(rg.flavors)
                      for rg in spec.resource_groups) <= 1
              and not (spec.admission_scope is not None
                       and self.queues.afs is not None)
              and not self.engine._is_tas_cq(name))
        self._eligible_cache[name] = ok
        return ok

    # -- window lifecycle --------------------------------------------------

    def note_solve_begin(self) -> None:
        """Called by the engine right before a full solve: records
        the fence generation and spec generation the solve's export
        can possibly cover. Events landing after this mark survive
        note_full_solve — the solve never saw them."""
        with self._mu:
            self._solve_mark = self._gen
            self._solve_spec_gen = self.engine.export_cache.spec_gen
            self._solve_thread = threading.get_ident()

    def note_full_solve(self) -> None:
        """A full solve completed: fences raised before it began
        reset and the next window opens against the post-solve store
        (the oracle-parity baseline boundary). Fences and spec bumps
        from mid-solve events stay — they defer to the NEXT solve."""
        with self._mu:
            self.armed = True
            self._armed_gen = self._solve_spec_gen
            self._solve_thread = None
            self._contended_roots = {
                root: g for root, g in self._contended_roots.items()
                if g > self._solve_mark}
            self._snap = None
            self._max_admitted.clear()

    def note_solve_abort(self) -> None:
        """The solve failed (host fallback): stop attributing events
        to it; every fence it raised stays down until a COMPLETED
        solve re-arms."""
        with self._mu:
            self._solve_thread = None

    def _window_snapshot(self):
        if self._snap is None:
            from kueue_oss_tpu.core.snapshot import build_snapshot

            self._snap = build_snapshot(self.store)
        return self._snap

    # -- the micro-drain ---------------------------------------------------

    def drain(self, now: float = 0.0) -> MicroDrainResult:
        """Admit in-order arrivals for every uncontended fast-path CQ.

        Runs between full solves; O(pending-in-eligible-CQs), never
        O(store) beyond the one lazily built window snapshot."""
        result = MicroDrainResult()
        if not self.enabled or not self.armed:
            return result
        if self.engine.enable_fair_sharing:
            return result
        if self.engine.export_cache.spec_gen != self._armed_gen:
            # quota edit / flavor change / node flap since arm: fence
            # the whole window AND request an immediate full solve —
            # consume_full_solve_request() tells the serve loop to run
            # the heavy cycle now rather than on its natural cadence
            with self._mu:
                self.armed = False
                self.full_solve_pending = True
            metrics.stream_demotions_total.inc("spec_change")
            return result
        t0 = time.perf_counter()
        self.micro_drains += 1
        pending = self.queues.cqs_with_pending()
        if not pending:
            metrics.stream_microdrains_total.inc("idle")
            return result
        self._refresh_tables()
        with self._mu:
            contended = set(self._contended_roots)
        for name in pending:
            if result.admitted + result.parked >= self.max_batch:
                break
            root = self._root_of.get(name, f"cq:{name}")
            if root in contended:
                result.deferred_cqs += 1
                continue
            q = self.queues.queues.get(name)
            if q is not None and len(q._in_heap) > 4 * self.max_batch:
                # a flood-sized heap is the batched solver's job (the
                # scheduler's solver_min_backlog routing); walking it
                # entry-by-entry here would stall the serve loop
                result.deferred_cqs += 1
                continue
            if not self._root_streamable.get(root, False):
                # borrow-capable multi-CQ subtree: the batch oracle
                # interleaves its members round-by-round — only the
                # full solve reproduces that order
                result.deferred_cqs += 1
                metrics.stream_demotions_total.inc("borrow_capable")
                continue
            if not self._static_eligible(name):
                result.deferred_cqs += 1
                continue
            if not self._drain_cq(name, root, now, result):
                contended.add(root)  # demoted mid-walk
        result.duration_s = time.perf_counter() - t0
        metrics.stream_microdrains_total.inc(
            "admitted" if result.admitted else
            ("parked" if result.parked else
             ("deferred" if result.deferred_cqs else "idle")))
        if result.admitted:
            self._record_ledger(result)
            p = getattr(self.store, "persistence", None)
            if p is not None:
                # sub-cycle durability barrier: the micro-batch's
                # intents + events group-commit now, and the
                # (incremental) checkpoint cadence gets its look —
                # this is what makes sub-second cadences affordable
                p.flush()
        return result

    def _drain_cq(self, name: str, root: str, now: float,
                  result: MicroDrainResult) -> bool:
        """Greedy in-order walk of one CQ's heap. Returns False when
        the CQ demoted itself (out-of-order arrival, preempt-needed,
        unsupported shape) — the caller fences its root for the rest
        of this drain; the sticky fence rides ``_contended_roots``."""
        from kueue_oss_tpu import features
        from kueue_oss_tpu.api.types import QueueingStrategy

        q = self.queues.queues.get(name)
        if q is None:
            return True
        strict = q.strategy == QueueingStrategy.STRICT_FIFO
        ca_gate = features.enabled("ConcurrentAdmission")
        snap = self._window_snapshot()
        cq_snap = snap.cluster_queue(name)
        if cq_snap is None:
            return True
        floor = self._max_admitted.get(name)
        for info in q.snapshot_order():
            # max_batch bounds PROCESSED entries (admits + parks), not
            # just admissions — one micro-drain must never walk an
            # unbounded no-fit backlog; the remainder keeps its order
            # for the next tick (or the full solve)
            if result.admitted + result.parked >= self.max_batch:
                return True
            with self._mu:
                # live fence re-check per entry: a controller thread
                # may contend this root mid-walk (capacity freed);
                # committing past that point would stream into state
                # the batch oracle would re-order
                if root in self._contended_roots or not self.armed:
                    return True
            key = _order_key(info)
            if floor is not None and key < floor:
                # out-of-order arrival: the batch oracle would have
                # sorted it before admissions already committed this
                # window — demote before processing it
                self._contend(name, "out_of_order")
                return False
            wl = self.store.workloads.get(info.key)
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            if any(ps.topology_request is not None for ps in wl.podsets):
                self._contend(name, "unsupported")
                return False
            if ca_gate and wl.parent_workload is not None:
                self._contend(name, "unsupported")
                return False
            fresh = WorkloadInfo(wl, cluster_queue=name)
            assigner = FlavorAssigner(
                fresh, cq_snap, snap.resource_flavors,
                oracle=self._preemptor, enable_fair_sharing=False)
            assignment = assigner.assign()
            mode = assignment.representative_mode()
            if mode == fa.FIT:
                self._commit(wl, name, fresh, assignment, now, result)
                floor = key
                self._max_admitted[name] = key
                continue
            # NO_FIT, or PREEMPT on a CQ whose policies are all Never
            # (static eligibility excludes preemption-capable CQs, so
            # "fits only by preempting" is a lean-kernel park) —
            # kernel parity: BestEffortFIFO parks and walks on; a
            # blocked StrictFIFO head blocks the queue
            if strict:
                return True
            q.park(info.key)
            result.parked += 1
            obs.recorder.record(
                obs.SKIPPED, info.key, cycle=self._cycle(),
                cluster_queue=name, path=obs.STREAM,
                reason="parked inadmissible by the streaming fast "
                       "path: no flavor option fits at current "
                       "capacity",
                reason_slug="stream_parked")
        return True

    def _cycle(self) -> int:
        sched = self.engine.scheduler
        return (sched.cycle_count + 1 if sched is not None
                else self.engine.drain_count + 1)

    def _commit(self, wl, name: str, info: WorkloadInfo, assignment,
                now: float, result: MicroDrainResult) -> None:
        flavor_of: dict[str, str] = {}
        for psa in assignment.podsets:
            for r, rec in psa.flavors.items():
                flavor_of[r] = rec.name
        drain_result = _EngineResultAdapter()
        self.engine._drain_cycle = self._cycle()
        self.engine.last_drain_arm = "stream"
        self._committing_thread = threading.get_ident()
        try:
            self.engine._commit_admission(
                wl, name, flavor_of, info, now, drain_result)
        finally:
            self._committing_thread = None
        # keep the window snapshot current so the next entry's fit
        # check sees this admission's usage (the kernel's in-round
        # usage refresh, host-side)
        cq_snap = self._snap.cluster_queue(name)
        if cq_snap is not None:
            cq_snap.add_usage(dict(assignment.usage_quota))
        result.admitted += drain_result.admitted
        result.admitted_keys.extend(drain_result.admitted_keys)
        metrics.stream_admitted_total.inc(by=drain_result.admitted)

    def _record_ledger(self, result: MicroDrainResult) -> None:
        ledger = obs.cycle_ledger
        if not ledger.enabled:
            return
        ledger.record(
            self._cycle(), obs.STREAM_DRAIN,
            breaker=obs.breaker_state_name(),
            duration_s=result.duration_s,
            phases={"stream": round(result.duration_s, 6)},
            admitted=result.admitted, parked=result.parked,
            solver_arm="stream",
            detail={"deferredCqs": result.deferred_cqs})

    def consume_full_solve_request(self) -> bool:
        """True at most once per spec-change fence: drain() observed a
        spec edit mid-window and the caller (the serve loop) should
        run the full cycle immediately instead of skipping it."""
        with self._mu:
            pending = self.full_solve_pending
            self.full_solve_pending = False
            return pending

    # -- introspection -----------------------------------------------------

    def contended(self) -> set[str]:
        with self._mu:
            return set(self._contended_roots)

    def status(self) -> dict:
        gen, keys, cqs = self.engine.export_cache.dirty_snapshot()
        with self._mu:
            return {"armed": self.armed,
                    "contendedRoots": sorted(self._contended_roots),
                    "specGen": gen, "armedGen": self._armed_gen,
                    "dirtyKeys": len(keys), "dirtyCqs": len(cqs),
                    "microDrains": self.micro_drains}


def _can_borrow(spec) -> bool:
    """Whether any flavor quota of this CQ permits borrowing
    (borrowing_limit None = unlimited, the kueue default)."""
    for rg in spec.resource_groups:
        for fq in rg.flavors:
            for rq in fq.resources:
                if rq.borrowing_limit is None or rq.borrowing_limit > 0:
                    return True
    return False


class _EngineResultAdapter:
    """Duck-typed DrainResult stand-in for _commit_admission."""

    def __init__(self) -> None:
        self.admitted = 0
        self.admitted_keys: list[str] = []
