"""Scheduling decision stack (the scalar oracle path).

Reference parity: pkg/scheduler of hiboyang/kueue_oss — flavor assignment,
preemption (classical + fair sharing), and the per-cycle scheduling loop.
The batched TPU path in kueue_oss_tpu.solver mirrors these semantics.
"""
