"""Flavor assignment: map each podset resource onto a ResourceFlavor.

Reference parity: pkg/scheduler/flavorassigner/flavorassigner.go. Walks the
ClusterQueue's ordered flavor list per resource group, classifying each
flavor into a mode lattice NoFit < Preempt < Fit with a borrowing level,
honoring FlavorFungibility early-stop policy and resuming from the
last-tried flavor cursor across cycles.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

from kueue_oss_tpu.api.types import (
    FlavorFungibility,
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    FlavorResource,
    PodSet,
    PreemptionPolicyValue,
    ResourceFlavor,
    Taint,
    TopologyAssignment,
)
from kueue_oss_tpu.core.snapshot import ClusterQueueSnapshot
from kueue_oss_tpu.core.workload_info import (
    AssignmentClusterQueueState,
    WorkloadInfo,
    effective_per_pod_requests,
)
from kueue_oss_tpu.tas.snapshot import TASPodSetRequest

# FlavorAssignmentMode — public lattice (flavorassigner.go:362-377).
NO_FIT = 0
PREEMPT = 1
FIT = 2

MODE_NAMES = {NO_FIT: "NoFit", PREEMPT: "Preempt", FIT: "Fit"}

# preemptionMode — internal lattice (flavorassigner.go:429-437).
P_NOFIT = 0
P_NO_CANDIDATES = 1  # preemption possible by quota, but no targets found
P_PREEMPT = 2
P_RECLAIM = 3
P_FIT = 4


def preemption_to_assignment_mode(pmode: int) -> int:
    if pmode == P_NOFIT:
        return NO_FIT
    if pmode == P_FIT:
        return FIT
    return PREEMPT


# granularMode = (preemption_mode, borrowing_level); lower borrowing level =
# quota sourced more locally = better.
GranularMode = tuple[int, int]

WORST_MODE: GranularMode = (P_NOFIT, 1 << 30)
BEST_MODE: GranularMode = (P_FIT, 0)


def is_preferred(a: GranularMode, b: GranularMode,
                 fungibility: FlavorFungibility) -> bool:
    """True if mode a beats mode b under the configured preference
    (flavorassigner.go:439-470)."""
    if a[0] == P_NOFIT:
        return False
    if b[0] == P_NOFIT:
        return True

    def borrowing_over_preemption() -> bool:
        if a[0] != b[0]:
            return a[0] > b[0]
        return a[1] < b[1]

    def preemption_over_borrowing() -> bool:
        if a[1] != b[1]:
            return a[1] < b[1]
        return a[0] > b[0]

    if fungibility.preference == FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING:
        return preemption_over_borrowing()
    return borrowing_over_preemption()


def should_try_next_flavor(mode: GranularMode,
                           fungibility: FlavorFungibility) -> bool:
    """flavorassigner.go:1000-1017."""
    pmode, borrow_level = mode
    if pmode in (P_NOFIT, P_NO_CANDIDATES):
        return True
    if pmode in (P_PREEMPT, P_RECLAIM) and (
            fungibility.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR):
        return True
    if borrow_level != 0 and (
            fungibility.when_can_borrow == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR):
        return True
    return False


# ---------------------------------------------------------------------------
# Assignment result model
# ---------------------------------------------------------------------------


@dataclass
class FlavorAssignmentRec:
    name: str  # flavor
    mode: int  # FlavorAssignmentMode
    borrow: int = 0
    tried_flavor_idx: int = -1


@dataclass
class PodSetAssignmentResult:
    name: str
    count: int
    requests: dict[str, int] = field(default_factory=dict)
    flavors: dict[str, FlavorAssignmentRec] = field(default_factory=dict)
    reasons: list[str] = field(default_factory=list)
    topology_assignment: Optional[TopologyAssignment] = None

    def representative_mode(self) -> int:
        if self.requests and not self.flavors:
            return NO_FIT
        mode = FIT
        for rec in self.flavors.values():
            mode = min(mode, rec.mode)
        return mode

    def set_mode(self, mode: int) -> None:
        for rec in self.flavors.values():
            rec.mode = mode

    def cap_mode(self, mode: int) -> None:
        for rec in self.flavors.values():
            rec.mode = min(rec.mode, mode)


@dataclass
class Assignment:
    podsets: list[PodSetAssignmentResult] = field(default_factory=list)
    usage_quota: dict[FlavorResource, int] = field(default_factory=dict)
    last_state: Optional[AssignmentClusterQueueState] = None

    def representative_mode(self) -> int:
        if not self.podsets:
            return FIT
        return min(ps.representative_mode() for ps in self.podsets)

    def borrows(self) -> int:
        """Max borrowing level across assigned flavors (Assignment.Borrows)."""
        return max(
            (rec.borrow for ps in self.podsets for rec in ps.flavors.values()),
            default=0,
        )

    def message(self) -> str:
        reasons = [r for ps in self.podsets for r in ps.reasons]
        return "; ".join(dict.fromkeys(reasons)) if reasons else "couldn't assign flavors"

    def skip_detail(self) -> dict:
        """Structured no-fit explanation for the decision flight
        recorder: the representative mode plus each podset's reason
        list, preserved verbatim instead of being discarded with the
        skipped entry (the flattened ``message()`` loses the
        podset association)."""
        return {
            "mode": MODE_NAMES[self.representative_mode()],
            "podsets": {ps.name: list(ps.reasons)
                        for ps in self.podsets if ps.reasons},
        }

    def counts(self) -> list[int]:
        return [ps.count for ps in self.podsets]

    def podset_by_name(self, name: str) -> Optional[PodSetAssignmentResult]:
        for ps in self.podsets:
            if ps.name == name:
                return ps
        return None


# ---------------------------------------------------------------------------
# Preemption oracle protocol (implemented in scheduler.preemption)
# ---------------------------------------------------------------------------

# PreemptionPossibility values
NO_CANDIDATES = "NoCandidates"
POSSIBILITY_PREEMPT = "Preempt"
POSSIBILITY_RECLAIM = "Reclaim"


class PreemptionOracle(Protocol):
    def simulate_preemption(
        self, cq: ClusterQueueSnapshot, wl: WorkloadInfo,
        fr: FlavorResource, quantity: int,
    ) -> tuple[str, int]: ...


POSSIBILITY_TO_PMODE = {
    NO_CANDIDATES: P_NO_CANDIDATES,
    POSSIBILITY_PREEMPT: P_PREEMPT,
    POSSIBILITY_RECLAIM: P_RECLAIM,
}


# ---------------------------------------------------------------------------
# Hierarchical helpers
# ---------------------------------------------------------------------------


def _node_height(cohort) -> int:
    max_height = min(cohort.child_count(), 1)
    for child in cohort.child_cohorts():
        max_height = max(max_height, _node_height(child) + 1)
    return max_height


def find_height_of_lowest_subtree_that_fits(
    cq: ClusterQueueSnapshot, fr: FlavorResource, val: int
) -> tuple[int, bool]:
    """Height of the lowest cohort subtree that could absorb val of fr.

    Reference parity: classical/hierarchical_preemption.go:221-243. Returns
    (height, subtree_is_proper) where height doubles as the "borrowing
    level" used to rank flavors, and subtree_is_proper indicates that a
    subtree smaller than the whole hierarchy fits (hierarchical reclaim is
    possible).
    """
    if not cq.borrowing_with(fr, val) or not cq.has_parent():
        return 0, cq.has_parent()
    remaining = val - cq.node.local_available(fr)
    for tracking in cq.path_parent_to_root():
        if not tracking.borrowing_with(fr, remaining):
            return _node_height(tracking), tracking.has_parent()
        remaining -= tracking.node.local_available(fr)
    return _node_height(cq.parent().root()), False


# ---------------------------------------------------------------------------
# Flavor ↔ podset compatibility (taints / node selector)
# ---------------------------------------------------------------------------


def _untolerated_taint(podset: PodSet, flavor: ResourceFlavor) -> Optional[Taint]:
    tolerations = list(podset.tolerations) + list(flavor.tolerations)
    for taint in flavor.node_taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return taint
    return None


def _selector_matches(podset: PodSet, flavor: ResourceFlavor,
                      allowed_keys: frozenset[str]) -> bool:
    """Node-selector subset match against the flavor's node labels,
    restricted to keys the resource group's flavors define
    (flavorassigner.go flavorSelector)."""
    for k, v in podset.node_selector.items():
        if k in allowed_keys and flavor.node_labels.get(k) != v:
            return False
    return True


# ---------------------------------------------------------------------------
# TAS helpers (reference: flavorassigner/tas_flavorassigner.go)
# ---------------------------------------------------------------------------


def is_tas_requested(podset: PodSet, cq: ClusterQueueSnapshot) -> bool:
    """Explicit topology request, or implied because the CQ is TAS-only
    (tas_flavorassigner.go:216-225)."""
    return podset.topology_request is not None or cq.is_tas_only()


def tas_flavor_mismatch(podset: PodSet, flavor: ResourceFlavor,
                        cq: ClusterQueueSnapshot) -> Optional[str]:
    """checkPodSetAndFlavorMatchForTAS (tas_flavorassigner.go:170-208)."""
    if is_tas_requested(podset, cq):
        if podset.topology_request is None and cq.is_tas_only():
            return None  # implied: every flavor in the CQ is a TAS flavor
        if flavor.topology_name is None:
            return (f"flavor {flavor.name} does not support "
                    "TopologyAwareScheduling")
        snap = cq.tas_flavors.get(flavor.name)
        if snap is None:
            return f"flavor {flavor.name} information missing in TAS cache"
        if not snap.has_level(podset):
            return (f"flavor {flavor.name} does not contain the requested "
                    "topology level")
        return None
    if flavor.topology_name is not None:
        return f"flavor {flavor.name} supports only TopologyAwareScheduling"
    return None


def workload_topology_requests(
    wl: WorkloadInfo, cq: ClusterQueueSnapshot, assignment: Assignment
) -> dict[str, list[TASPodSetRequest]]:
    """Per-flavor TAS placement requests for a quota-assigned workload
    (Assignment.WorkloadsTopologyRequests, tas_flavorassigner.go:40-84)."""
    out: dict[str, list[TASPodSetRequest]] = {}
    for ps in wl.obj.podsets:
        if not is_tas_requested(ps, cq):
            continue
        psa = assignment.podset_by_name(ps.name)
        if psa is None or not psa.flavors or psa.count == 0:
            continue
        tas_flavor = next(
            (rec.name for rec in psa.flavors.values()
             if rec.name in cq.tas_flavors), None)
        if tas_flavor is None:
            psa.reasons.append("no TAS flavor assigned")
            continue
        out.setdefault(tas_flavor, []).append(TASPodSetRequest(
            podset=ps,
            single_pod_requests=effective_per_pod_requests(
                ps, wl.obj.namespace),
            count=psa.count,
            flavor=tas_flavor,
            implied=ps.topology_request is None,
            podset_group_name=(
                ps.topology_request.podset_group_name
                if ps.topology_request is not None else None),
        ))
    return out


def update_for_tas_result(assignment: Assignment, result: dict) -> None:
    """Attach successful topology assignments to their podsets
    (Assignment.UpdateForTASResult, flavorassigner.go:81-92)."""
    for name, res in result.items():
        psa = assignment.podset_by_name(name)
        if psa is not None and res.assignment is not None:
            psa.topology_assignment = res.assignment


# ---------------------------------------------------------------------------
# The assigner
# ---------------------------------------------------------------------------


class FlavorAssigner:
    def __init__(
        self,
        wl: WorkloadInfo,
        cq: ClusterQueueSnapshot,
        resource_flavors: dict[str, ResourceFlavor],
        oracle: PreemptionOracle,
        enable_fair_sharing: bool = False,
    ) -> None:
        self.wl = wl
        self.cq = cq
        self.resource_flavors = resource_flavors
        self.oracle = oracle
        self.enable_fair_sharing = enable_fair_sharing
        if (wl.last_assignment is not None
                and wl.last_assignment.cluster_queue_generation != cq.generation):
            wl.last_assignment = None  # cursor outdated (flavorassigner.go:571)

    def assign(self, counts: Optional[list[int]] = None) -> Assignment:
        """Compute flavor assignment for all podsets (optionally scaled)."""
        requests = [
            psr if counts is None else psr.scaled_to(counts[i])
            for i, psr in enumerate(self.wl.total_requests)
        ]
        assignment = Assignment(
            last_state=AssignmentClusterQueueState(
                cluster_queue_generation=self.cq.generation),
        )

        # Group podsets that must share flavor choices (TAS podset groups).
        groups: dict[str, list[int]] = {}
        for i, ps in enumerate(self.wl.obj.podsets):
            key = str(i)
            tr = ps.topology_request
            if tr is not None and tr.podset_group_name:
                key = f"group/{tr.podset_group_name}"
            groups.setdefault(key, []).append(i)

        for ps_ids in groups.values():
            group_requests: dict[str, int] = {}
            for i in ps_ids:
                for r, q in requests[i].requests.items():
                    group_requests[r] = group_requests.get(r, 0) + q

            group_flavors: dict[str, FlavorAssignmentRec] = {}
            group_reasons: list[str] = []
            failed = False
            for res in group_requests:
                if self.cq.rg_by_resource(res) is None:
                    if group_requests[res] == 0:
                        continue
                    from kueue_oss_tpu.core.workload_info import (
                        ignore_undeclared_resources,
                    )

                    if ignore_undeclared_resources():
                        # QuotaCheckStrategy=IgnoreUndeclared: the
                        # resource simply doesn't participate in quota
                        continue
                    group_reasons.append(
                        f"resource {res} unavailable in ClusterQueue")
                    failed = True
                    break
                if res in group_flavors:
                    continue
                flavors, reasons = self._find_flavor_for_podsets(
                    ps_ids, group_requests, res, assignment.usage_quota)
                group_reasons.extend(reasons)
                if not flavors:
                    failed = True
                    break
                group_flavors.update(flavors)

            for i in ps_ids:
                psa = PodSetAssignmentResult(
                    name=requests[i].name,
                    count=requests[i].count,
                    requests=dict(requests[i].requests),
                    reasons=list(group_reasons),
                )
                if not failed:
                    psa.flavors = {
                        r: group_flavors[r]
                        for r in requests[i].requests
                        if r in group_flavors
                    }
                self._append(assignment, psa, i)
            if failed:
                return assignment
        self._update_for_tas(assignment)
        return assignment

    def _update_for_tas(self, assignment: Assignment) -> None:
        """Topology placement after quota assignment (flavorassigner.go
        assignFlavors TAS tail, :733-765).

        Fit: place with real usage; a placement failure downgrades the
        failing podset to Preempt. Preempt (not node-replacement): place
        on an empty cluster; failure means NoFit even after preempting
        everything, success keeps the TAS podsets at Preempt because
        the free quota may be fragmented across domains.
        """
        if assignment.representative_mode() == NO_FIT:
            return
        tas_requests = workload_topology_requests(self.wl, self.cq, assignment)
        if not tas_requests:
            return
        if assignment.representative_mode() == FIT:
            result = self.cq.find_topology_assignments_for_workload(
                tas_requests, workload=self.wl.obj)
            failed = False
            for name, res in result.items():
                if res.failure:
                    psa = assignment.podset_by_name(name)
                    if psa is not None:
                        psa.reasons.append(res.failure)
                        psa.set_mode(PREEMPT)
                    failed = True
                    break
            if not failed:
                update_for_tas_result(assignment, result)
        if (assignment.representative_mode() == PREEMPT
                and not self.wl.obj.status.unhealthy_nodes):
            result = self.cq.find_topology_assignments_for_workload(
                tas_requests, simulate_empty=True)
            for name, res in result.items():
                if res.failure:
                    psa = assignment.podset_by_name(name)
                    if psa is not None:
                        psa.reasons.append(res.failure)
                        psa.set_mode(NO_FIT)
                    return
            for requests in tas_requests.values():
                for tr in requests:
                    psa = assignment.podset_by_name(tr.podset.name)
                    if psa is not None:
                        psa.cap_mode(PREEMPT)

    def _append(self, assignment: Assignment,
                psa: PodSetAssignmentResult, ps_idx: int) -> None:
        assignment.podsets.append(psa)
        cursor: dict[str, int] = {}
        for res, rec in psa.flavors.items():
            fr = (rec.name, res)
            assignment.usage_quota[fr] = (
                assignment.usage_quota.get(fr, 0) + psa.requests.get(res, 0))
            cursor[res] = rec.tried_flavor_idx
        ls = assignment.last_state
        assert ls is not None
        while len(ls.last_tried_flavor_idx) <= ps_idx:
            ls.last_tried_flavor_idx.append({})
        ls.last_tried_flavor_idx[ps_idx] = cursor

    def _find_flavor_for_podsets(
        self,
        ps_ids: list[int],
        requests: dict[str, int],
        res_name: str,
        assignment_usage: dict[FlavorResource, int],
    ) -> tuple[dict[str, FlavorAssignmentRec], list[str]]:
        rg = self.cq.rg_by_resource(res_name)
        assert rg is not None
        reasons: list[str] = []
        covered = {r: v for r, v in requests.items()
                   if r in rg.covered_resources}
        allowed_keys = frozenset(
            k
            for fq in rg.flavors
            for k in self.resource_flavors.get(
                fq.name, ResourceFlavor(name=fq.name)).node_labels
        )

        from kueue_oss_tpu import features
        from kueue_oss_tpu.api.types import FlavorFungibility

        # gate FlavorFungibility: when off, custom fungibility policies
        # are ignored and the default (Borrow / TryNextFlavor) applies
        fungibility = (self.cq.spec.flavor_fungibility
                       if features.enabled("FlavorFungibility")
                       else FlavorFungibility())
        best: dict[str, FlavorAssignmentRec] = {}
        best_mode = WORST_MODE
        num_flavors = len(rg.flavors)

        start = 0
        if self.wl.last_assignment is not None:
            start = self.wl.last_assignment.next_flavor_to_try(
                ps_ids[0], res_name)
        attempted_idx = -1
        for idx in range(start, num_flavors):
            attempted_idx = idx
            f_name = rg.flavors[idx].name
            # A concurrent-admission variant is pinned to its flavor
            # (reference: WorkloadAllowedResourceFlavorAnnotation,
            # flavorassigner IsFlavorAllowedForVariant check).
            if (self.wl.obj.allowed_flavor is not None
                    and f_name != self.wl.obj.allowed_flavor):
                reasons.append(
                    f"flavor {f_name} not allowed for this variant")
                continue
            flavor = self.resource_flavors.get(f_name)
            if flavor is None:
                reasons.append(f"flavor {f_name} not found")
                continue

            flavor_ok = True
            for psid in ps_ids:
                ps = self.wl.obj.podsets[psid]
                taint = _untolerated_taint(ps, flavor)
                if taint is not None:
                    reasons.append(
                        f"untolerated taint {taint.key} in flavor {f_name}")
                    flavor_ok = False
                    break
                if not _selector_matches(ps, flavor, allowed_keys):
                    reasons.append(
                        f"flavor {f_name} doesn't match node affinity")
                    flavor_ok = False
                    break
                tas_reason = tas_flavor_mismatch(ps, flavor, self.cq)
                if tas_reason is not None:
                    reasons.append(tas_reason)
                    flavor_ok = False
                    break
            if not flavor_ok:
                continue

            assignments: dict[str, FlavorAssignmentRec] = {}
            representative = BEST_MODE
            for r_name, val in covered.items():
                fr = (f_name, r_name)
                pmode, borrow, why = self._fits_resource_quota(
                    fr, assignment_usage.get(fr, 0), val)
                if why:
                    reasons.extend(why)
                mode: GranularMode = (pmode, borrow)
                if is_preferred(representative, mode, fungibility):
                    representative = mode
                if representative[0] == P_NOFIT:
                    break
                assignments[r_name] = FlavorAssignmentRec(
                    name=f_name,
                    mode=preemption_to_assignment_mode(pmode),
                    borrow=borrow,
                )

            if not should_try_next_flavor(
                    representative, fungibility):
                best = assignments
                best_mode = representative
                break
            if is_preferred(representative, best_mode,
                            fungibility):
                best = assignments
                best_mode = representative

        for rec in best.values():
            rec.tried_flavor_idx = (
                -1 if attempted_idx == num_flavors - 1 else attempted_idx)
        return best, reasons

    def _fits_resource_quota(
        self, fr: FlavorResource, assumed: int, request: int
    ) -> tuple[int, int, list[str]]:
        """Classify one (flavor, resource) into the preemption-mode lattice.

        Reference parity: flavorassigner.go:1071-1108.
        """
        available = self.cq.available(fr)
        max_capacity = self.cq.potential_available(fr)
        val = assumed + request

        if val > max_capacity:
            return P_NOFIT, 0, [
                f"insufficient quota for {fr[1]} in flavor {fr[0]}, request "
                f"{val} > maximum capacity {max_capacity}"]

        borrow, may_reclaim = find_height_of_lowest_subtree_that_fits(
            self.cq, fr, val)
        if val <= available:
            return P_FIT, borrow, []

        reasons = [
            f"insufficient unused quota for {fr[1]} in flavor {fr[0]}, "
            f"{val - available} more needed"]
        nominal = self.cq.quota_for(fr).nominal
        if val <= nominal or may_reclaim or self._can_preempt_while_borrowing():
            possibility, borrow_after = self.oracle.simulate_preemption(
                self.cq, self.wl, fr, val)
            return POSSIBILITY_TO_PMODE[possibility], borrow_after, reasons
        return P_NOFIT, borrow, reasons

    def _can_preempt_while_borrowing(self) -> bool:
        preemption = self.cq.spec.preemption
        return (
            preemption.borrow_within_cohort.policy != PreemptionPolicyValue.NEVER
            or (self.enable_fair_sharing
                and preemption.reclaim_within_cohort != PreemptionPolicyValue.NEVER)
        )


class PodSetReducer:
    """Binary search over reduced pod counts for partial admission.

    Reference parity: flavorassigner/podset_reducer.go (KEP-420) — searches
    the largest total count, interpolating each podset between min_count and
    count, for which the probe function succeeds.
    """

    def __init__(self, podsets: list[PodSet], probe) -> None:
        self.podsets = podsets
        self.probe = probe

    def _counts_for(self, step: int, max_steps: int) -> list[int]:
        out = []
        for ps in self.podsets:
            lo = ps.min_count if ps.min_count is not None else ps.count
            out.append(ps.count - ((ps.count - lo) * step) // max_steps)
        return out

    def search(self):
        max_steps = max(
            (ps.count - (ps.min_count if ps.min_count is not None else ps.count)
             for ps in self.podsets),
            default=0,
        )
        if max_steps == 0:
            return None, False
        # Find smallest step (largest counts) that fits: binary search over
        # the monotone predicate probe(counts(step)).
        lo, hi = 1, max_steps
        best = None
        while lo <= hi:
            mid = (lo + hi) // 2
            result, ok = self.probe(self._counts_for(mid, max_steps))
            if ok:
                best = result
                hi = mid - 1
            else:
                lo = mid + 1
        return best, best is not None
