"""Provisioning admission-check controller.

Reference parity: pkg/controller/admissionchecks/provisioning (KEP-1136) —
for every quota-reserved workload whose ClusterQueue lists an AdmissionCheck
handled by this controller, it creates a capacity ProvisioningRequest,
relays the provider's answer into the workload's AdmissionCheckState, and
retries failed requests with exponential backoff up to a retry limit
(KEP-3258), after which the check goes Rejected.

The cloud/autoscaler side is abstracted as a `CapacityProvider` callable so
tests (and the in-process runtime) can decide provisioning outcomes; the
reference's equivalent boundary is the autoscaler acting on the
ProvisioningRequest CR.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from kueue_oss_tpu.api.types import CheckState, Workload
from kueue_oss_tpu.core.store import Store

CONTROLLER_NAME = "kueue.x-k8s.io/provisioning-request"

#: provider(request) -> True (provisioned) | False (failed) | None (pending)
CapacityProvider = Callable[["ProvisioningRequest"], Optional[bool]]


@dataclass
class ProvisioningRequest:
    """In-memory analog of the autoscaler ProvisioningRequest CR."""

    name: str
    workload_key: str
    check_name: str
    #: aggregated resource requests the capacity must cover
    requests: dict[str, int] = field(default_factory=dict)
    attempt: int = 1
    state: str = "Pending"  # Pending | Provisioned | Failed
    #: when a failed attempt may be retried
    retry_at: Optional[float] = None
    #: QuotaReserved transition time this request was provisioned for; a
    #: later re-admission must re-provision, not reuse a stale answer
    reservation_epoch: float = 0.0


@dataclass
class ProvisioningConfig:
    """Reference parity: ProvisioningRequestConfig CRD (retry KEP-3258)."""

    max_retries: int = 3
    base_backoff_seconds: float = 60.0
    max_backoff_seconds: float = 1800.0


class ProvisioningController:
    def __init__(self, store: Store,
                 provider: Optional[CapacityProvider] = None,
                 config: Optional[ProvisioningConfig] = None) -> None:
        self.store = store
        self.provider: CapacityProvider = provider or (lambda req: True)
        self.config = config or ProvisioningConfig()
        #: live request per (workload key, check name); superseded attempts
        #: are replaced in place so retention stays O(reserved workloads)
        self.requests: dict[tuple[str, str], ProvisioningRequest] = {}

    # -- helpers ------------------------------------------------------------

    def _checks_for(self, wl: Workload) -> list[str]:
        """Names of this controller's checks pending on the workload."""
        out = []
        for name, state in wl.status.admission_checks.items():
            ac = self.store.admission_checks.get(name)
            if ac is not None and ac.controller_name == CONTROLLER_NAME:
                if state.state == CheckState.PENDING:
                    out.append(name)
        return out

    @staticmethod
    def _request_name(wl: Workload, check: str, attempt: int) -> str:
        return f"{wl.namespace}/{wl.name}/{check}/{attempt}"

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, now: float) -> Optional[float]:
        """Advance every provisioning request; returns next retry deadline."""
        next_due: Optional[float] = None
        for wl in list(self.store.workloads.values()):
            if not wl.is_quota_reserved or wl.is_finished:
                continue
            for check in self._checks_for(wl):
                due = self._advance(wl, check, now)
                if due is not None:
                    next_due = due if next_due is None else min(next_due, due)
        self._gc(now)
        return next_due

    @staticmethod
    def _epoch(wl: Workload) -> float:
        from kueue_oss_tpu.api.types import WorkloadConditionType

        cond = wl.condition(WorkloadConditionType.QUOTA_RESERVED)
        return cond.last_transition_time if cond is not None else 0.0

    def _advance(self, wl: Workload, check: str, now: float) -> Optional[float]:
        epoch = self._epoch(wl)
        req = self.requests.get((wl.key, check))
        if req is not None and req.reservation_epoch != epoch:
            # Evicted + re-admitted since this request was made: the old
            # provisioned/failed answer belongs to the previous admission.
            req = None
        if req is None:
            req = ProvisioningRequest(
                name=self._request_name(wl, check, 1),
                workload_key=wl.key, check_name=check,
                requests=wl.total_requests(), reservation_epoch=epoch)
            self.requests[(wl.key, check)] = req

        if req.state == "Pending":
            answer = self.provider(req)
            if answer is None:
                return None  # still provisioning; provider will be re-polled
            req.state = "Provisioned" if answer else "Failed"

        state = wl.status.admission_checks.get(check)
        if state is None:
            return None
        if req.state == "Provisioned":
            state.state = CheckState.READY
            state.message = f"Provisioning request {req.name} provisioned"
            self.store.update_workload(wl)
            return None
        # Failed: retry with backoff, then reject (KEP-3258).
        if req.attempt > self.config.max_retries:
            state.state = CheckState.REJECTED
            state.message = (f"Provisioning request failed after "
                             f"{req.attempt} attempt(s)")
            self.store.update_workload(wl)
            return None
        if req.retry_at is None:
            delay = min(
                self.config.base_backoff_seconds * (2 ** (req.attempt - 1)),
                self.config.max_backoff_seconds)
            req.retry_at = now + delay
        if now < req.retry_at:
            return req.retry_at
        nxt = ProvisioningRequest(
            name=self._request_name(wl, check, req.attempt + 1),
            workload_key=wl.key, check_name=check,
            requests=wl.total_requests(), attempt=req.attempt + 1,
            reservation_epoch=req.reservation_epoch)
        self.requests[(wl.key, check)] = nxt
        return self._advance(wl, check, now)

    def _gc(self, now: float) -> None:
        """Drop requests whose workload no longer reserves quota
        (reference: provisioning controller owns requests via ownerRefs)."""
        for key, req in list(self.requests.items()):
            wl = self.store.workloads.get(req.workload_key)
            if wl is None or not wl.is_quota_reserved or wl.is_finished:
                del self.requests[key]
