"""Provisioning admission-check controller.

Reference parity: pkg/controller/admissionchecks/provisioning (KEP-1136,
controller.go 1222 LoC) — for every quota-reserved workload whose
ClusterQueue lists an AdmissionCheck handled by this controller:

- resolve the check's ProvisioningRequestConfig (class name, parameters,
  managedResources, retryStrategy, podSetUpdates);
- build a capacity ProvisioningRequest covering the podsets that
  request MANAGED resources (requiredPodSets, controller.go:427); a
  workload touching none of them needs no provisioning — the check goes
  Ready immediately;
- relay the provider's condition into the workload's
  AdmissionCheckState (controller.go:543-590):
    Provisioned      -> Ready, with the config's podSetUpdates attached
                        (node selector/labels steering pods onto the
                        provisioned capacity, :629-660);
    Failed           -> Retry with exponential backoff while attempts
                        remain (KEP-3258), else Rejected;
    BookingExpired   -> like Failed while the workload is NOT admitted;
                        ignored after admission (:568-583);
    CapacityRevoked  -> Rejected (triggers workload deactivation — the
                        autoscaler already deleted the nodes, :560-567).

The cloud/autoscaler side is abstracted as a `CapacityProvider` callable
so tests (and the in-process runtime) decide provisioning outcomes; the
reference's equivalent boundary is the autoscaler acting on the
ProvisioningRequest CR.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Union

from kueue_oss_tpu.api.types import CheckState, PodSetUpdate, Workload
from kueue_oss_tpu.core.store import Store

CONTROLLER_NAME = "kueue.x-k8s.io/provisioning-request"

# ProvisioningRequest condition states (autoscaling.x-k8s.io/v1)
PENDING = "Pending"
PROVISIONED = "Provisioned"
FAILED = "Failed"
BOOKING_EXPIRED = "BookingExpired"
CAPACITY_REVOKED = "CapacityRevoked"

#: provider(request) -> one of the condition states above; bool/None
#: keep their legacy meaning (True=Provisioned, False=Failed,
#: None=still pending)
CapacityProvider = Callable[["ProvisioningRequest"],
                            Union[str, bool, None]]


@dataclass
class ProvisioningRequest:
    """In-memory analog of the autoscaler ProvisioningRequest CR."""

    name: str
    workload_key: str
    check_name: str
    #: aggregated MANAGED resource requests the capacity must cover
    requests: dict[str, int] = field(default_factory=dict)
    #: podset names included (those requesting managed resources)
    podsets: list[str] = field(default_factory=list)
    #: ProvisioningRequestConfig passthrough
    provisioning_class: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    attempt: int = 1
    state: str = PENDING
    #: when a failed attempt may be retried
    retry_at: Optional[float] = None
    #: QuotaReserved transition time this request was provisioned for; a
    #: later re-admission must re-provision, not reuse a stale answer
    reservation_epoch: float = 0.0


@dataclass
class ProvisioningConfig:
    """Reference parity: ProvisioningRequestConfig CRD."""

    #: spec.provisioningClassName (e.g. queued-provisioning.gke.io)
    provisioning_class: str = "check-capacity.autoscaling.x-k8s.io"
    #: spec.parameters passthrough to the autoscaler
    parameters: dict[str, str] = field(default_factory=dict)
    #: spec.managedResources: only these count toward the request; an
    #: empty list means ALL resources are managed
    managed_resources: list[str] = field(default_factory=list)
    #: node selector injected into Ready checks' podSetUpdates
    #: (spec.podSetUpdates.nodeSelector)
    update_node_selector: dict[str, str] = field(default_factory=dict)
    #: retryStrategy (KEP-3258)
    max_retries: int = 3
    base_backoff_seconds: float = 60.0
    max_backoff_seconds: float = 1800.0


class ProvisioningController:
    def __init__(self, store: Store,
                 provider: Optional[CapacityProvider] = None,
                 config: Optional[ProvisioningConfig] = None,
                 configs_by_check: Optional[dict] = None) -> None:
        self.store = store
        self.provider: CapacityProvider = provider or (lambda req: True)
        self.config = config or ProvisioningConfig()
        #: per-check ProvisioningRequestConfig overrides (the reference
        #: resolves the config through the AdmissionCheck's parameters
        #: reference)
        self.configs_by_check = configs_by_check or {}
        #: live request per (workload key, check name); superseded attempts
        #: are replaced in place so retention stays O(reserved workloads)
        self.requests: dict[tuple[str, str], ProvisioningRequest] = {}
        #: completed FAILED attempts per (workload key, check) — survives
        #: the Retry eviction (the reference derives this from retained
        #: ProvisioningRequest objects, getAttempt)
        self.attempts: dict[tuple[str, str], int] = {}
        #: earliest time the next attempt may be created (retryStrategy
        #: backoff gates provreq re-creation, controller.go remainingTime)
        self.retry_at: dict[tuple[str, str], float] = {}

    # -- helpers ------------------------------------------------------------

    def _config_for(self, check: str) -> ProvisioningConfig:
        return self.configs_by_check.get(check, self.config)

    def _checks_for(self, wl: Workload) -> list[str]:
        """Names of this controller's checks pending on the workload."""
        out = []
        for name, state in wl.status.admission_checks.items():
            ac = self.store.admission_checks.get(name)
            if ac is not None and ac.controller_name == CONTROLLER_NAME:
                if state.state == CheckState.PENDING:
                    out.append(name)
        return out

    @staticmethod
    def _request_name(wl: Workload, check: str, attempt: int) -> str:
        return f"{wl.namespace}/{wl.name}/{check}/{attempt}"

    def _required_podsets(self, wl: Workload,
                          cfg: ProvisioningConfig) -> list:
        """requiredPodSets (controller.go:427): podsets requesting at
        least one managed resource; all podsets when managedResources
        is empty."""
        if not cfg.managed_resources:
            return list(wl.podsets)
        managed = set(cfg.managed_resources)
        return [ps for ps in wl.podsets
                if any(r in managed and q > 0
                       for r, q in ps.requests.items())]

    @staticmethod
    def _managed_totals(podsets, cfg: ProvisioningConfig) -> dict:
        managed = set(cfg.managed_resources)
        out: dict[str, int] = {}
        for ps in podsets:
            for r, q in ps.requests.items():
                if not managed or r in managed:
                    out[r] = out.get(r, 0) + q * ps.count
        return out

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, now: float) -> Optional[float]:
        """Advance every provisioning request; returns next retry deadline."""
        next_due: Optional[float] = None
        for wl in list(self.store.workloads.values()):
            if not wl.is_quota_reserved or wl.is_finished:
                continue
            for check in self._checks_for(wl):
                due = self._advance(wl, check, now)
                if due is not None:
                    next_due = due if next_due is None else min(next_due, due)
            self._watch_ready(wl, now)
        self._gc(now)
        return next_due

    def _watch_ready(self, wl: Workload, now: float) -> None:
        """Re-poll PROVISIONED requests behind READY checks: the
        autoscaler can revoke capacity or expire a booking AFTER the
        check went Ready (controller.go:560-583 — condition updates
        arrive via the provreq watch, not only while pending)."""
        for name, state in wl.status.admission_checks.items():
            if state.state != CheckState.READY:
                continue
            ac = self.store.admission_checks.get(name)
            if ac is None or ac.controller_name != CONTROLLER_NAME:
                continue
            req = self.requests.get((wl.key, name))
            if req is None or req.state != PROVISIONED:
                continue
            self._poll(req)
            if req.state == CAPACITY_REVOKED:
                # nodes already deleted: reject to trigger deactivation
                state.state = CheckState.REJECTED
                state.message = (f"Provisioning request {req.name}: "
                                 f"capacity revoked")
                self.store.update_workload(wl)
            elif req.state == BOOKING_EXPIRED:
                if wl.is_admitted:
                    req.state = PROVISIONED  # booked long enough; ignore
                else:
                    # Ready but not yet admitted (other checks pending):
                    # the booking lapsed — retry like a failure
                    self._schedule_retry(wl, state, req,
                                         "booking expired", now)

    @staticmethod
    def _epoch(wl: Workload) -> float:
        from kueue_oss_tpu.api.types import WorkloadConditionType

        cond = wl.condition(WorkloadConditionType.QUOTA_RESERVED)
        return cond.last_transition_time if cond is not None else 0.0

    def _poll(self, req: ProvisioningRequest) -> None:
        answer = self.provider(req)
        if answer is None:
            return
        if answer is True:
            req.state = PROVISIONED
        elif answer is False:
            req.state = FAILED
        else:
            req.state = answer

    def _advance(self, wl: Workload, check: str, now: float) -> Optional[float]:
        cfg = self._config_for(check)
        state = wl.status.admission_checks.get(check)
        if state is None:
            return None

        required = self._required_podsets(wl, cfg)
        if not required:
            # nothing to provision: the check is immediately satisfied
            # (controller.go:427 requiredPodSets empty -> Ready)
            state.state = CheckState.READY
            state.message = ("no podset requests managed resources; "
                            "provisioning not required")
            self.store.update_workload(wl)
            return None

        epoch = self._epoch(wl)
        req = self.requests.get((wl.key, check))
        if req is not None and req.reservation_epoch != epoch:
            # Evicted + re-admitted since this request was made: the old
            # provisioned/failed answer belongs to the previous admission.
            req = None
        if req is None:
            prior = self.attempts.get((wl.key, check), 0)
            gate = self.retry_at.get((wl.key, check))
            if prior and gate is not None and now < gate:
                # backoff still running: the next attempt may not be
                # created yet (controller.go remainingTime)
                return gate
            req = ProvisioningRequest(
                name=self._request_name(wl, check, prior + 1),
                workload_key=wl.key, check_name=check,
                requests=self._managed_totals(required, cfg),
                podsets=[ps.name for ps in required],
                provisioning_class=cfg.provisioning_class,
                parameters=dict(cfg.parameters),
                attempt=prior + 1,
                reservation_epoch=epoch)
            self.requests[(wl.key, check)] = req

        if req.state == PENDING:
            self._poll(req)
            if req.state == PENDING:
                return None  # still provisioning; re-polled next pass

        if req.state == PROVISIONED:
            state.state = CheckState.READY
            state.message = f"Provisioning request {req.name} provisioned"
            # steer the provisioned podsets onto the new capacity
            # (controller.go podSetUpdates :629-660)
            state.pod_set_updates = [PodSetUpdate(
                name=name,
                node_selector=dict(cfg.update_node_selector),
                annotations={
                    "cluster-autoscaler.kubernetes.io/"
                    "consume-provisioning-request": req.name,
                    "cluster-autoscaler.kubernetes.io/"
                    "provisioning-class-name": req.provisioning_class,
                }) for name in req.podsets]
            self.store.update_workload(wl)
            return None

        if req.state == CAPACITY_REVOKED:
            # nodes already deleted by the autoscaler: reject to trigger
            # workload deactivation (controller.go:560-567)
            if wl.active and not wl.is_finished:
                state.state = CheckState.REJECTED
                state.message = (f"Provisioning request {req.name}: "
                                 f"capacity revoked")
                self.store.update_workload(wl)
            return None

        if req.state == BOOKING_EXPIRED and wl.is_admitted:
            # an admitted workload keeps running; the booking mattered
            # only until admission (controller.go:568-570)
            return None

        # Failed (or BookingExpired before admission): Retry — the check
        # flips to CheckState.RETRY so the workload controller EVICTS and
        # releases the quota for the whole backoff window (KEP-3258; the
        # reference does not hold capacity while a retry waits) — then
        # Rejected once attempts are exhausted.
        kind = ("booking expired" if req.state == BOOKING_EXPIRED
                else "failed")
        self._schedule_retry(wl, state, req, kind, now)
        return None

    def _schedule_retry(self, wl: Workload, state, req, kind: str,
                        now: float) -> None:
        cfg = self._config_for(req.check_name)
        if req.attempt > cfg.max_retries:
            state.state = CheckState.REJECTED
            state.message = (f"Provisioning request {kind} after "
                             f"{req.attempt} attempt(s)")
            self.store.update_workload(wl)
            return
        key = (wl.key, req.check_name)
        self.attempts[key] = req.attempt
        delay = min(cfg.base_backoff_seconds * (2 ** (req.attempt - 1)),
                    cfg.max_backoff_seconds)
        self.retry_at[key] = now + delay
        self.requests.pop(key, None)
        state.state = CheckState.RETRY
        state.retry_count = req.attempt
        state.message = (f"Retrying after {kind}: attempt {req.attempt}, "
                         f"next at t+{delay:.0f}s")
        self.store.update_workload(wl)

    def _gc(self, now: float) -> None:
        """Drop requests whose workload no longer reserves quota; the
        attempt/backoff bookkeeping survives evictions (it paces the
        NEXT attempt) and dies with the workload."""
        for key, req in list(self.requests.items()):
            wl = self.store.workloads.get(req.workload_key)
            if wl is None or not wl.is_quota_reserved or wl.is_finished:
                del self.requests[key]
        for key in list(self.attempts):
            wl = self.store.workloads.get(key[0])
            if wl is None or wl.is_finished:
                self.attempts.pop(key, None)
                self.retry_at.pop(key, None)
