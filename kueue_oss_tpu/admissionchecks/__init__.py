from kueue_oss_tpu.admissionchecks.provisioning import (
    ProvisioningController,
    ProvisioningRequest,
)

__all__ = ["ProvisioningController", "ProvisioningRequest"]
