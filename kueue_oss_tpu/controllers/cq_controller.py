"""ClusterQueue status reconciler.

Reference parity: pkg/controller/core/clusterqueue_controller.go — per
reconcile, compute the CQ's Active condition: a CQ is Active when every
referenced ResourceFlavor exists, every referenced AdmissionCheck
exists and is active, the CQ is not Stopped, and its cohort is
cycle-free. Inactive CQs are deactivated in the queue manager (their
pending workloads stay parked) and the kueue_cluster_queue_status gauge
flips.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu import metrics
from kueue_oss_tpu.api.types import StopPolicy
from kueue_oss_tpu.core.store import Store

ACTIVE = "Active"

# inactive reasons (clusterqueue_controller.go conditions)
R_READY = "Ready"
R_STOPPED = "Stopped"
R_FLAVOR_NOT_FOUND = "FlavorNotFound"
R_CHECK_NOT_FOUND = "AdmissionCheckNotFound"
R_CHECK_INACTIVE = "AdmissionCheckInactive"
R_COHORT_CYCLE = "CohortCycleDetected"


@dataclass
class CQStatus:
    active: bool = True
    reason: str = R_READY
    message: str = ""
    missing_flavors: list[str] = field(default_factory=list)
    missing_checks: list[str] = field(default_factory=list)


class ClusterQueueReconciler:
    """Keeps per-CQ Active conditions + status gauges in sync."""

    def __init__(self, store: Store, queues=None) -> None:
        self.store = store
        self.queues = queues
        #: last computed status per CQ
        self.status: dict[str, CQStatus] = {}

    def _cohort_has_cycle(self, name: str) -> bool:
        seen: set[str] = set()
        cur = name
        while cur:
            if cur in seen:
                return True
            seen.add(cur)
            co = self.store.cohorts.get(cur)
            cur = co.parent if co is not None else None
        return False

    def reconcile(self, cq_name: str) -> CQStatus:
        cq = self.store.cluster_queues.get(cq_name)
        if cq is None:
            self.status.pop(cq_name, None)
            metrics.cluster_queue_status.delete_matching(
                cluster_queue=cq_name)
            return CQStatus(active=False, reason="NotFound")
        st = CQStatus()
        missing_flavors = sorted({
            fq.name for rg in cq.resource_groups for fq in rg.flavors
            if fq.name not in self.store.resource_flavors})
        missing_checks = []
        inactive_checks = []
        for ac_name in cq.admission_checks:
            ac = self.store.admission_checks.get(ac_name)
            if ac is None:
                missing_checks.append(ac_name)
            elif not ac.status.active:
                inactive_checks.append(ac_name)
        if cq.stop_policy != StopPolicy.NONE:
            st = CQStatus(False, R_STOPPED, "ClusterQueue is stopped")
        elif missing_flavors:
            st = CQStatus(False, R_FLAVOR_NOT_FOUND,
                          f"missing ResourceFlavors: {missing_flavors}",
                          missing_flavors=missing_flavors)
        elif missing_checks:
            st = CQStatus(False, R_CHECK_NOT_FOUND,
                          f"missing AdmissionChecks: {missing_checks}",
                          missing_checks=missing_checks)
        elif inactive_checks:
            st = CQStatus(False, R_CHECK_INACTIVE,
                          f"inactive AdmissionChecks: {inactive_checks}",
                          missing_checks=inactive_checks)
        elif cq.cohort and self._cohort_has_cycle(cq.cohort):
            st = CQStatus(False, R_COHORT_CYCLE,
                          f"cohort {cq.cohort} is part of a cycle")
        self.status[cq_name] = st
        metrics.record_cq_labels(cq_name, cq.labels)
        metrics.cluster_queue_status.set(
            cq_name, "active", value=1 if st.active else 0)
        metrics.cluster_queue_status.set(
            cq_name, "inactive", value=0 if st.active else 1)
        # quota gauges belong to the CQ reconciler in the reference
        metrics.report_cluster_queue_quotas(
            cq_name, ((fr, cq.quota_for(fr))
                      for fr in cq.flavor_resources()))
        # an inactive CQ stops serving heads (queue manager parity)
        if self.queues is not None:
            q = self.queues.queues.get(cq_name)
            if q is not None:
                q.active = st.active and cq.stop_policy == StopPolicy.NONE
        return st

    def reconcile_all(self) -> dict[str, CQStatus]:
        for name in list(self.store.cluster_queues):
            self.reconcile(name)
        return dict(self.status)
