"""Workload lifecycle controller.

Reference parity: pkg/controller/core/workload_controller.go (1601 LoC) —
the state machine that sits between quota reservation (scheduler) and the
job actually running:

- admission-check sync: QuotaReserved + all checks Ready -> Admitted
  (workload_controller.go:785); Retry -> evict + release quota; Rejected ->
  evict + deactivate.
- check-based eviction (:752), LQ/CQ StopPolicy handling (:836-918),
- PodsReady timeout eviction with RequeuingStrategy backoff (:1004) and
  deactivation once backoffLimitCount is exhausted,
- maximum execution time (:697),
- deactivation (spec.active=false, :1057),
- finished/deactivated workload retention GC.

The reference runs as a controller-runtime reconciler on watch events plus
time-based requeues; here `reconcile(key, now)` is the event entry point and
returns the next deadline (absolute seconds) at which it must run again, so
a host loop (or test) can drive time explicitly.
"""

from __future__ import annotations

from typing import Optional

from kueue_oss_tpu.api.types import (
    AdmissionCheckState,
    CheckState,
    StopPolicy,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.config import Configuration
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.scheduler.scheduler import Scheduler


class EvictionReason:
    """Reference parity: workload_types.go WorkloadEvictedBy* reasons."""

    PREEMPTED = "Preempted"
    PODS_READY_TIMEOUT = "PodsReadyTimeout"
    ADMISSION_CHECK = "AdmissionCheck"
    CLUSTER_QUEUE_STOPPED = "ClusterQueueStopped"
    LOCAL_QUEUE_STOPPED = "LocalQueueStopped"
    DEACTIVATED = "Deactivated"
    MAX_EXEC_TIME_EXCEEDED = "MaximumExecutionTimeExceeded"


class WorkloadReconciler:
    """Drives the Workload state machine on top of the scheduler's
    eviction/requeue primitives."""

    def __init__(self, store: Store, scheduler: Scheduler,
                 config: Optional[Configuration] = None) -> None:
        self.store = store
        self.scheduler = scheduler
        self.config = config or Configuration()
        #: keys deleted by retention GC (observability/tests)
        self.gc_deleted: list[str] = []

    @staticmethod
    def _has_pending_topology(wl: Workload) -> bool:
        """workload.go HasTopologyAssignmentsPending."""
        if wl.status.admission is None:
            return False
        return any(psa.topology_assignment is None
                   and psa.delayed_topology_request == "Pending"
                   for psa in wl.status.admission.podset_assignments)

    # -- public entry points ------------------------------------------------

    def reconcile_all(self, now: float) -> Optional[float]:
        """Sweep every workload; returns the earliest next deadline."""
        deadlines = [self.reconcile(key, now)
                     for key in list(self.store.workloads)]
        due = [d for d in deadlines if d is not None]
        return min(due) if due else None

    def reconcile(self, key: str, now: float) -> Optional[float]:
        wl = self.store.workloads.get(key)
        if wl is None:
            return None

        if wl.is_finished:
            return self._gc_finished(wl, now)

        if not wl.active:
            return self._handle_deactivated(wl, now)

        if wl.is_quota_reserved:
            if self._handle_stop_policies(wl, now):
                return None
            if self._sync_admission_checks(wl, now):
                return None

        deadlines: list[float] = []
        if wl.is_admitted:
            d = self._check_max_execution_time(wl, now)
            if d is None and not wl.active:
                return None  # deactivated by max-exec-time
            if d is not None:
                deadlines.append(d)
        if wl.is_quota_reserved:
            d = self._check_pods_ready(wl, now)
            if d is not None:
                deadlines.append(d)
        return min(deadlines) if deadlines else None

    def set_pods_ready(self, key: str, ready: bool, now: float) -> None:
        """Signal from the job layer that all pods reached/left Ready.

        Reference parity: jobframework reconciler sets the PodsReady
        condition from Job.PodsReady() (reconciler.go).
        """
        wl = self.store.workloads.get(key)
        if wl is None:
            return
        prev = wl.condition(WorkloadConditionType.PODS_READY)
        was_ready = prev is not None and prev.status
        # "PodsReadyLost" marks a readiness regression, which is what the
        # recovery timeout (vs the initial timeout) applies to.
        if ready:
            reason = "PodsReady"
        elif was_ready:
            reason = "PodsReadyLost"
        elif prev is not None and not prev.status:
            reason = prev.reason  # repeated not-ready keeps the original cause
        else:
            reason = "PodsNotReady"
        wl.set_condition(WorkloadConditionType.PODS_READY, ready,
                         reason=reason, now=now)
        if ready and not was_ready:
            # readiness latency series (metrics.go ready_wait_time /
            # admitted_until_ready_wait_time)
            from kueue_oss_tpu import metrics

            cq = (wl.status.admission.cluster_queue
                  if wl.status.admission is not None else None)
            if cq:
                metrics.ready_wait_time_seconds.observe(
                    cq, value=max(now - wl.creation_time, 0.0))
                if metrics._lq_metrics_enabled():
                    metrics.local_queue_ready_wait_time_seconds.observe(
                        wl.queue_name, wl.namespace,
                        value=max(now - wl.creation_time, 0.0))
                adm = wl.condition(WorkloadConditionType.ADMITTED)
                if adm is not None and adm.status:
                    metrics.admitted_until_ready_wait_time_seconds.observe(
                        cq, value=max(now - adm.last_transition_time, 0.0))
                    if metrics._lq_metrics_enabled():
                        (metrics
                         .local_queue_admitted_until_ready_wait_time_seconds
                         .observe(wl.queue_name, wl.namespace,
                                  value=max(now - adm.last_transition_time,
                                            0.0)))
        if ready:
            # Pods came up: the PodsReady requeue/backoff history is done
            # (reference: RequeueState reset once the workload runs).
            wl.status.requeue_state = None
        self.store.update_workload(wl)

    # -- retention GC -------------------------------------------------------

    def _gc_finished(self, wl: Workload, now: float) -> Optional[float]:
        from kueue_oss_tpu import features

        pol = self.config.object_retention_policies
        if (pol is None or pol.finished_workload_retention_seconds is None
                or not features.enabled("ObjectRetentionPolicies")):
            return None
        fin = wl.condition(WorkloadConditionType.FINISHED)
        due = fin.last_transition_time + pol.finished_workload_retention_seconds
        if now >= due:
            # Store.delete_workload decrements the retained-finished
            # gauges on every deletion path
            self.store.delete_workload(wl.key)
            self.gc_deleted.append(wl.key)
            return None
        return due

    def _handle_deactivated(self, wl: Workload, now: float) -> Optional[float]:
        if wl.is_quota_reserved:
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.DEACTIVATED,
                message="The workload is deactivated", now=now, requeue=False)
            return None
        from kueue_oss_tpu import features

        pol = self.config.object_retention_policies
        if (pol is None or pol.deactivated_workload_retention_seconds is None
                or not features.enabled("ObjectRetentionPolicies")):
            return None
        ev = wl.condition(WorkloadConditionType.EVICTED)
        if ev is None:
            # Deactivated while pending (never evicted): stamp the
            # deactivation now so the retention deadline has a stable
            # anchor instead of receding on every reconcile.
            wl.set_condition(WorkloadConditionType.EVICTED, True,
                             reason=EvictionReason.DEACTIVATED,
                             message="The workload is deactivated", now=now)
            self.store.update_workload(wl)
            ev = wl.condition(WorkloadConditionType.EVICTED)
        due = ev.last_transition_time + pol.deactivated_workload_retention_seconds
        if now >= due:
            self.store.delete_workload(wl.key)
            self.gc_deleted.append(wl.key)
            return None
        return due

    # -- stop policies ------------------------------------------------------

    def _handle_stop_policies(self, wl: Workload, now: float) -> bool:
        """HoldAndDrain evicts running workloads; Hold only blocks new
        admissions (enforced queue-side via ClusterQueuePendingQueue.active).
        Reference parity: workload_controller.go:836-918."""
        cq_name = self.store.cluster_queue_for(wl)
        if cq_name is None and wl.status.admission is not None:
            cq_name = wl.status.admission.cluster_queue
        cq = self.store.cluster_queues.get(cq_name) if cq_name else None
        if cq is not None and cq.stop_policy == StopPolicy.HOLD_AND_DRAIN:
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.CLUSTER_QUEUE_STOPPED,
                message=f"ClusterQueue {cq.name} is stopped", now=now)
            return True
        lq = self.store.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        if lq is not None and lq.stop_policy == StopPolicy.HOLD_AND_DRAIN:
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.LOCAL_QUEUE_STOPPED,
                message=f"LocalQueue {lq.name} is stopped", now=now)
            return True
        return False

    # -- admission checks ---------------------------------------------------

    def _sync_admission_checks(self, wl: Workload, now: float) -> bool:
        """Returns True if the workload was evicted as a result.

        Reference parity: workload_controller.go:752-834 +
        pkg/workload/admissionchecks.go — prune/seed states against the CQ
        spec, then act on Rejected > Retry > all-Ready.
        """
        cq_name = (wl.status.admission.cluster_queue
                   if wl.status.admission is not None
                   else self.store.cluster_queue_for(wl))
        cq = self.store.cluster_queues.get(cq_name) if cq_name else None
        if cq is None:
            return False
        assigned = (wl.status.admission.assigned_flavors()
                    if wl.status.admission is not None else None)
        wanted = cq.checks_for_flavors(assigned)
        # prune states for checks no longer configured; seed missing ones
        for name in list(wl.status.admission_checks):
            if name not in wanted:
                del wl.status.admission_checks[name]
        for name in wanted:
            wl.status.admission_checks.setdefault(
                name, AdmissionCheckState(name=name))

        states = wl.status.admission_checks.values()
        rejected = [s for s in states if s.state == CheckState.REJECTED]
        if rejected:
            names = ", ".join(s.name for s in rejected)
            # Rejected is terminal: deactivate so the workload is not retried
            # (reference: workload_controller.go rejection deactivates).
            wl.active = False
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.ADMISSION_CHECK,
                message=f"Admission check(s) {names} rejected the workload",
                now=now, requeue=False, underlying_cause="Rejected")
            self.store.update_workload(wl)
            return True
        retry = [s for s in states if s.state == CheckState.RETRY]
        if retry:
            names = ", ".join(s.name for s in retry)
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.ADMISSION_CHECK,
                message=f"Admission check(s) {names} requested a retry",
                now=now, underlying_cause="Retry")
            return True
        # `states` may be empty (checks removed from the CQ after quota
        # reservation) — admitting on the vacuous all() mirrors the
        # reference, where zero pending checks means Admitted.
        if all(s.state == CheckState.READY for s in states):
            if self._has_pending_topology(wl):
                # Delayed TAS: all checks Ready but the topology is still
                # unassigned — admission waits for the scheduler's second
                # pass (workload.go NeedsSecondPass). The queue manager's
                # iteration map is the dedup: it clears when the pass
                # succeeds or the workload drops out, so re-admissions
                # re-queue cleanly.
                if not self.scheduler.queues.second_pass_pending(wl.key):
                    self.scheduler.queues.queue_second_pass(wl.key, now)
                return False
            if not wl.is_admitted and wl.is_quota_reserved:
                wl.set_condition(WorkloadConditionType.ADMITTED, True,
                                 reason="Admitted", now=now)
                self.store.update_workload(wl)
                from kueue_oss_tpu import metrics

                metrics.admitted_workload(cq_name, now - wl.creation_time)
                qr = wl.condition(WorkloadConditionType.QUOTA_RESERVED)
                if qr is not None:
                    metrics.admission_checks_wait_time_seconds.observe(
                        cq_name, value=max(now - qr.last_transition_time, 0.0))
                    if metrics._lq_metrics_enabled():
                        (metrics
                         .local_queue_admission_checks_wait_time_seconds
                         .observe(wl.queue_name, wl.namespace,
                                  value=max(now - qr.last_transition_time,
                                            0.0)))
        return False

    # -- max execution time -------------------------------------------------

    def _check_max_execution_time(self, wl: Workload,
                                  now: float) -> Optional[float]:
        """Reference parity: workload_controller.go:697 — an admitted
        workload that has run past maxExecutionTimeSeconds is deactivated."""
        if wl.max_execution_time is None:
            return None
        adm = wl.condition(WorkloadConditionType.ADMITTED)
        if adm is None:
            return None
        due = adm.last_transition_time + wl.max_execution_time
        if now >= due:
            wl.active = False
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.MAX_EXEC_TIME_EXCEEDED,
                message=(f"Exceeded the maximum execution time of "
                         f"{wl.max_execution_time:g}s"),
                now=now, requeue=False)
            self.store.update_workload(wl)
            return None
        return due

    # -- wait-for-pods-ready ------------------------------------------------

    def _check_pods_ready(self, wl: Workload, now: float) -> Optional[float]:
        """PodsReady timeout (KEP-349): a quota-reserved workload whose pods
        have not all become Ready within the timeout is evicted and requeued
        with the RequeuingStrategy backoff; once backoffLimitCount is
        exhausted it is deactivated instead.

        Reference parity: workload_controller.go:1004 + RequeueState
        (workload_types.go:774)."""
        from kueue_oss_tpu import features

        wfpr = self.config.wait_for_pods_ready
        if wfpr is None or not wfpr.enable:
            return None
        if not features.enabled("WaitForPodsReady"):
            return None
        pr = wl.condition(WorkloadConditionType.PODS_READY)
        if pr is not None and pr.status:
            return None  # pods are ready
        # The countdown starts at Admitted, not QuotaReserved: slow
        # admission checks must not eat into the PodsReady window
        # (reference: workload_controller.go admittedNotReadyWorkload).
        adm = wl.condition(WorkloadConditionType.ADMITTED)
        if adm is None or not wl.is_admitted:
            return None
        if pr is not None and not pr.status and pr.reason == "PodsReadyLost":
            # Was ready once, lost readiness: recovery timeout applies
            # (None = wait forever for recovery).
            if wfpr.recovery_timeout_seconds is None:
                return None
            due = pr.last_transition_time + wfpr.recovery_timeout_seconds
            timeout_msg = (f"Didn't recover readiness within "
                           f"{wfpr.recovery_timeout_seconds:g}s")
        else:
            due = adm.last_transition_time + wfpr.timeout_seconds
            timeout_msg = (f"Didn't become ready within "
                           f"{wfpr.timeout_seconds:g}s")
        if now < due:
            return due

        rs = wfpr.requeuing_strategy
        count = (wl.status.requeue_state.count
                 if wl.status.requeue_state is not None else 0)
        if rs.backoff_limit_count is not None and count >= rs.backoff_limit_count:
            wl.active = False
            self.scheduler.evict_workload(
                wl.key, reason=EvictionReason.DEACTIVATED,
                message=("Exceeded the PodsReady re-queue limit of "
                         f"{rs.backoff_limit_count}"),
                now=now, requeue=False, underlying_cause="RequeuingLimitExceeded")
            self.store.update_workload(wl)
            return None
        self.scheduler.evict_workload(
            wl.key, reason=EvictionReason.PODS_READY_TIMEOUT,
            message=timeout_msg,
            now=now,
            backoff_base_s=rs.backoff_base_seconds,
            backoff_max_s=rs.backoff_max_seconds)
        if rs.timestamp == "Creation":
            # Requeue ordered by creation time: rewrite the Evicted
            # transition time back to the creation timestamp so the queue
            # ordering (workload.Ordering) falls back to creation order
            # (reference: RequeuingStrategy.Timestamp=Creation).
            wl.status.conditions.pop(WorkloadConditionType.EVICTED, None)
            wl.set_condition(WorkloadConditionType.EVICTED, True,
                             reason=EvictionReason.PODS_READY_TIMEOUT,
                             message="requeued by creation timestamp",
                             now=wl.creation_time)
            self.store.update_workload(wl)
        return None
