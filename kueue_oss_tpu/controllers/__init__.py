from kueue_oss_tpu.controllers.workload_controller import (
    EvictionReason,
    WorkloadReconciler,
)
from kueue_oss_tpu.controllers.concurrent_admission import (
    ConcurrentAdmissionReconciler,
)
from kueue_oss_tpu.controllers.failure_recovery import (
    NodeFailureController,
)
from kueue_oss_tpu.controllers.cq_controller import (
    ClusterQueueReconciler,
    CQStatus,
)
from kueue_oss_tpu.controllers.core_controllers import (
    AdmissionCheckReconciler,
    CohortReconciler,
    CohortStatus,
    LocalQueueReconciler,
    LQStatus,
    ResourceFlavorReconciler,
    WorkloadPriorityClassReconciler,
)

__all__ = [
    "EvictionReason",
    "WorkloadReconciler",
    "ConcurrentAdmissionReconciler",
    "NodeFailureController",
    "ClusterQueueReconciler",
    "CQStatus",
    "AdmissionCheckReconciler",
    "CohortReconciler",
    "CohortStatus",
    "LocalQueueReconciler",
    "LQStatus",
    "ResourceFlavorReconciler",
    "WorkloadPriorityClassReconciler",
]
