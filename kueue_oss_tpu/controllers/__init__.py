from kueue_oss_tpu.controllers.workload_controller import (
    EvictionReason,
    WorkloadReconciler,
)

__all__ = ["EvictionReason", "WorkloadReconciler"]
