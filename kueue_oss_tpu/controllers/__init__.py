from kueue_oss_tpu.controllers.workload_controller import (
    EvictionReason,
    WorkloadReconciler,
)
from kueue_oss_tpu.controllers.concurrent_admission import (
    ConcurrentAdmissionReconciler,
)
from kueue_oss_tpu.controllers.failure_recovery import (
    NodeFailureController,
)

__all__ = [
    "EvictionReason",
    "WorkloadReconciler",
    "ConcurrentAdmissionReconciler",
    "NodeFailureController",
]
