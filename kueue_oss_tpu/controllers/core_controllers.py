"""Core object reconcilers: LocalQueue, Cohort, AdmissionCheck,
ResourceFlavor, WorkloadPriorityClass.

Reference parity: pkg/controller/core/{localqueue_controller.go,
cohort_controller.go, admissioncheck_controller.go,
resourceflavor_controller.go, workloadpriorityclass_controller.go}.
Each reconciler computes the object's STATUS from the store the way the
reference computes it from informer caches, and keeps the dependent
caches (queue manager, CQ Active conditions) notified.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import features, metrics
from kueue_oss_tpu.api.types import StopPolicy
from kueue_oss_tpu.core.store import Store

ACTIVE = "Active"


# ---------------------------------------------------------------------------
# LocalQueue
# ---------------------------------------------------------------------------


@dataclass
class LQStatus:
    """localqueue_controller.go Reconcile (:176-240): counts + Active."""

    active: bool = False
    reason: str = ""
    message: str = ""
    pending_workloads: int = 0
    reserving_workloads: int = 0
    admitted_workloads: int = 0
    #: flavors usable through the parent CQ (ExposeFlavorsInLocalQueue)
    flavors: list[str] = field(default_factory=list)
    #: AFS consumed-usage sample (admissionFairSharing status)
    fair_sharing_usage: dict[str, float] = field(default_factory=dict)


class LocalQueueReconciler:
    """Maintains LocalQueue status: workload counts, the Active
    condition derived from the parent CQ, exposed flavors, and the AFS
    usage sample (localqueue_controller.go:176-240)."""

    def __init__(self, store: Store, queues=None, cq_reconciler=None,
                 afs=None) -> None:
        self.store = store
        self.queues = queues
        self.cq_reconciler = cq_reconciler
        self.afs = afs
        self.status: dict[str, LQStatus] = {}

    def _counts_by_lq(self) -> dict[tuple[str, str], tuple[int, int, int]]:
        """One pass over workloads: (namespace, queue) -> (pending,
        reserving, admitted). Keeps reconcile_all at O(W + LQ), not
        O(LQ x W)."""
        out: dict[tuple[str, str], list[int]] = {}
        for wl in self.store.workloads.values():
            if wl.is_finished:
                continue
            c = out.setdefault((wl.namespace, wl.queue_name), [0, 0, 0])
            if wl.is_quota_reserved:
                c[1] += 1
                if wl.is_admitted:
                    c[2] += 1
            else:
                c[0] += 1
        return {k: tuple(v) for k, v in out.items()}

    def reconcile(self, lq_key: str, now: float = 0.0,
                  counts=None) -> LQStatus:
        lq = self.store.local_queues.get(lq_key)
        if lq is None:
            self.status.pop(lq_key, None)
            return LQStatus(active=False, reason="NotFound")
        st = LQStatus()

        cq = self.store.cluster_queues.get(lq.cluster_queue)
        if cq is None:
            st.reason, st.message = ("ClusterQueueDoesNotExist",
                                     "Can't submit new workloads to "
                                     "clusterQueue")
        elif lq.stop_policy != StopPolicy.NONE:
            st.reason, st.message = ("Stopped",
                                     "LocalQueue is stopped")
        else:
            cq_active = True
            if self.cq_reconciler is not None:
                cq_st = self.cq_reconciler.status.get(lq.cluster_queue)
                if cq_st is None:
                    cq_st = self.cq_reconciler.reconcile(lq.cluster_queue)
                cq_active = cq_st.active
            if not cq_active:
                st.reason, st.message = ("ClusterQueueIsInactive",
                                         "Can't submit new workloads to "
                                         "clusterQueue")
            else:
                st.active, st.reason, st.message = (
                    True, "Ready", "Can submit new workloads to "
                    "clusterQueue")

        # workload counts (localqueue_controller.go status update)
        if counts is None:
            counts = self._counts_by_lq()
        (st.pending_workloads, st.reserving_workloads,
         st.admitted_workloads) = counts.get(
            (lq.namespace, lq.name), (0, 0, 0))

        # flavors usable from this queue (ExposeFlavorsInLocalQueue)
        if cq is not None and features.enabled("ExposeFlavorsInLocalQueue"):
            seen: list[str] = []
            for rg in cq.resource_groups:
                for fq in rg.flavors:
                    if fq.name not in seen:
                        seen.append(fq.name)
            st.flavors = seen

        # AFS consumed-usage sample (localqueue_controller.go:227-239)
        if self.afs is not None and features.enabled(
                "AdmissionFairSharing"):
            st.fair_sharing_usage = self.afs.lq_usage(lq_key, now)

        self.status[lq_key] = st
        if metrics._lq_metrics_enabled():
            metrics.local_queue_status.set(
                lq.name, lq.namespace, "active",
                value=1.0 if st.active else 0.0)
        return st

    def reconcile_all(self, now: float = 0.0) -> dict[str, LQStatus]:
        for key in list(self.status):
            if key not in self.store.local_queues:
                self.status.pop(key, None)
        counts = self._counts_by_lq()
        return {key: self.reconcile(key, now, counts=counts)
                for key in self.store.local_queues}


# ---------------------------------------------------------------------------
# Cohort
# ---------------------------------------------------------------------------


@dataclass
class CohortStatus:
    """cohort_controller.go Reconcile: validity + fair-sharing share."""

    active: bool = True
    reason: str = "Ready"
    message: str = ""
    #: rounded weighted share when fair sharing is on (status.fairSharing)
    weighted_share: Optional[int] = None


class CohortReconciler:
    """Validates cohort parent edges and publishes the subtree's
    fair-sharing weighted share (cohort_controller.go)."""

    def __init__(self, store: Store, fair_sharing_enabled: bool = False,
                 snapshot_fn=None) -> None:
        self.store = store
        self.fair_sharing_enabled = fair_sharing_enabled
        #: () -> Snapshot, for weighted-share computation
        self.snapshot_fn = snapshot_fn
        self.status: dict[str, CohortStatus] = {}

    def _has_cycle(self, name: str) -> bool:
        seen: set[str] = set()
        cur: Optional[str] = name
        while cur:
            if cur in seen:
                return True
            seen.add(cur)
            co = self.store.cohorts.get(cur)
            cur = co.parent if co is not None else None
        return False

    def reconcile(self, name: str) -> CohortStatus:
        st = CohortStatus()
        if name not in self.store.cohorts:
            self.status.pop(name, None)
            return CohortStatus(active=False, reason="NotFound")
        if self._has_cycle(name):
            st = CohortStatus(
                active=False, reason="CohortCycleDetected",
                message=f"cohort {name} is part of a parent cycle")
        elif self.fair_sharing_enabled and self.snapshot_fn is not None:
            from kueue_oss_tpu.core.quota import dominant_resource_share

            snap = self.snapshot_fn()
            node = snap.forest.nodes.get(f"cohort/{name}")
            if node is not None:
                drs = dominant_resource_share(node)
                st.weighted_share = drs.rounded_weighted_share()
        self.status[name] = st
        return st

    def reconcile_all(self) -> dict[str, CohortStatus]:
        return {name: self.reconcile(name)
                for name in list(self.store.cohorts)}


# ---------------------------------------------------------------------------
# AdmissionCheck
# ---------------------------------------------------------------------------


class AdmissionCheckReconciler:
    """Maintains per-check Active conditions: a check is Active when a
    controller is registered for its controllerName
    (admissioncheck_controller.go:90-124); flips feed the CQ
    reconciler the way the reference notifies the cache."""

    def __init__(self, store: Store, cq_reconciler=None) -> None:
        self.store = store
        self.cq_reconciler = cq_reconciler
        #: controllerName values with a live controller
        self.registered_controllers: set[str] = set()
        self.active: dict[str, bool] = {}

    def register_controller(self, controller_name: str) -> None:
        self.registered_controllers.add(controller_name)

    def unregister_controller(self, controller_name: str) -> None:
        self.registered_controllers.discard(controller_name)

    def reconcile(self, name: str) -> bool:
        ac = self.store.admission_checks.get(name)
        if ac is None:
            self.active.pop(name, None)
            return False
        is_active = (not ac.controller_name
                     or ac.controller_name in self.registered_controllers)
        was = self.active.get(name)
        self.active[name] = is_active
        ac.status.active = is_active
        # `was is None` (first reconcile) must notify too: the check's
        # default-True status may have let referencing CQs go Active
        # before this reconciler ever ran
        if was != is_active and self.cq_reconciler is not None:
            # notify CQs referencing this check (NotifyAdmissionCheckUpdate)
            for cq in self.store.cluster_queues.values():
                if name in getattr(cq, "admission_checks", []):
                    self.cq_reconciler.reconcile(cq.name)
        return is_active

    def reconcile_all(self) -> dict[str, bool]:
        return {name: self.reconcile(name)
                for name in list(self.store.admission_checks)}


# ---------------------------------------------------------------------------
# ResourceFlavor
# ---------------------------------------------------------------------------


class ResourceFlavorReconciler:
    """Finalizer semantics: a flavor referenced by any ClusterQueue
    cannot be deleted; deletion is deferred until the last reference is
    gone (resourceflavor_controller.go Reconcile)."""

    def __init__(self, store: Store, cq_reconciler=None) -> None:
        self.store = store
        self.cq_reconciler = cq_reconciler
        #: flavors whose deletion awaits release
        self.pending_deletion: set[str] = set()

    def in_use_by(self, flavor: str) -> list[str]:
        out = []
        for cq in self.store.cluster_queues.values():
            for rg in cq.resource_groups:
                if any(fq.name == flavor for fq in rg.flavors):
                    out.append(cq.name)
                    break
        return sorted(out)

    def request_deletion(self, flavor: str) -> bool:
        """True if deleted now; False if deferred behind references."""
        if flavor not in self.store.resource_flavors:
            return True
        if self.in_use_by(flavor):
            self.pending_deletion.add(flavor)
            return False
        self._delete(flavor)
        return True

    def _delete(self, flavor: str) -> None:
        self.store.resource_flavors.pop(flavor, None)
        self.pending_deletion.discard(flavor)
        if self.cq_reconciler is not None:
            for cq in self.store.cluster_queues.values():
                self.cq_reconciler.reconcile(cq.name)

    def reconcile_all(self) -> None:
        for flavor in list(self.pending_deletion):
            if not self.in_use_by(flavor):
                self._delete(flavor)


# ---------------------------------------------------------------------------
# WorkloadPriorityClass
# ---------------------------------------------------------------------------


class WorkloadPriorityClassReconciler:
    """Propagates priority-class value changes to the workloads that
    reference the class (workloadpriorityclass_controller.go — the
    reference re-enqueues owning workloads on update)."""

    def __init__(self, store: Store, queues=None) -> None:
        self.store = store
        self.queues = queues

    def reconcile(self, name: str) -> int:
        """Sync priorities from the class; returns workloads updated."""
        pc = self.store.priority_classes.get(name)
        if pc is None:
            return 0
        n = 0
        for wl in self.store.workloads.values():
            if wl.priority_class == name and wl.priority != pc.value:
                wl.priority = pc.value
                self.store.update_workload(wl)
                n += 1
        return n

    def reconcile_all(self) -> int:
        """One pass over workloads (O(W + classes), not classes x W)."""
        classes = self.store.priority_classes
        n = 0
        for wl in list(self.store.workloads.values()):
            pc = classes.get(wl.priority_class) if wl.priority_class \
                else None
            if pc is not None and wl.priority != pc.value:
                wl.priority = pc.value
                self.store.update_workload(wl)
                n += 1
        return n
