"""Concurrent admission variants (KEP-8691, fork-new).

Reference parity: pkg/controller/concurrentadmission/controller.go — a
parent workload fans out one *variant* workload per ResourceFlavor of its
ClusterQueue; each variant is pinned to its flavor. The scheduler admits
whichever variant fits first; the controller then deactivates variants
pinned to less favorable flavors (higher flavor index) and keeps more
favorable ones active so a later migration can move the job up the flavor
order (scheduler.go:386-392,456-461 hooks, implemented in
Scheduler._process_entry).
"""

from __future__ import annotations

from typing import Optional

from kueue_oss_tpu.api.types import (
    PodSet,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.store import Store


def variant_name(parent: Workload, flavor: str) -> str:
    """jobframework.GetWorkloadNameForVariant analog."""
    return f"{parent.name}-{flavor}"


def flavor_order_of(cq) -> dict[str, int]:
    """Favorability = index in the CQ's first resource group's flavor list
    (lower = more favorable)."""
    if not cq.resource_groups:
        return {}
    return {fq.name: i for i, fq in enumerate(cq.resource_groups[0].flavors)}


def variants_for(store: Store, parent: Workload) -> list[Workload]:
    return [wl for wl in store.workloads.values()
            if wl.parent_workload == parent.key]


def admitted_variant(variants: list[Workload]) -> Optional[Workload]:
    for v in variants:
        if v.is_admitted and v.active:
            return v
    return None


class ConcurrentAdmissionReconciler:
    """Drives the parent↔variant state machine over the store."""

    def __init__(self, store: Store, scheduler) -> None:
        self.store = store
        self.scheduler = scheduler

    def reconcile_all(self, now: float) -> None:
        for wl in list(self.store.workloads.values()):
            if wl.ca_parent:
                self.reconcile(wl.key, now)

    def reconcile(self, parent_key: str, now: float) -> None:
        parent = self.store.workloads.get(parent_key)
        if parent is None or not parent.ca_parent:
            return
        cq_name = self.store.cluster_queue_for(parent)
        cq = self.store.cluster_queues.get(cq_name) if cq_name else None
        if cq is None:
            return
        order = flavor_order_of(cq)
        variants = sorted(
            variants_for(self.store, parent),
            key=lambda v: order.get(v.allowed_flavor or "", len(order)))

        have = {v.allowed_flavor for v in variants}
        missing = [f for f in sorted(order, key=order.get) if f not in have]
        if missing and parent.active and not parent.is_finished:
            self._create_variants(parent, missing, now)
            variants = sorted(
                variants_for(self.store, parent),
                key=lambda v: order.get(v.allowed_flavor or "", len(order)))

        if self._sync_variant_eviction(parent, variants, now):
            return

        if not parent.active or parent.is_finished:
            reason = ("ParentFinished" if parent.is_finished
                      else "ParentDeactivated")
            for v in variants:
                self._deactivate_variant(v, reason, now)
            return

        admitted = admitted_variant(variants)
        if admitted is not None:
            admitted_idx = order.get(admitted.allowed_flavor or "", 0)
            for v in variants:
                idx = order.get(v.allowed_flavor or "", len(order))
                if idx > admitted_idx:
                    # Less favorable than the winner: stand down.
                    self._deactivate_variant(
                        v, "DeactivatedVariant", now,
                        message=f"Less favorable than admitted variant "
                                f"{admitted.name}")
                elif idx < admitted_idx and not v.active:
                    # More favorable: stays in the race for migration.
                    self._activate_variant(v, now)
        else:
            for v in variants:
                if not v.active and not v.is_finished:
                    self._activate_variant(v, now)

        self._sync_parent_status(parent, admitted, now)

    # -- helpers ------------------------------------------------------------

    def _create_variants(self, parent: Workload, flavors: list[str],
                         now: float) -> None:
        for flavor in flavors:
            v = Workload(
                name=variant_name(parent, flavor),
                namespace=parent.namespace,
                queue_name=parent.queue_name,
                priority=parent.priority,
                priority_class=parent.priority_class,
                podsets=[PodSet(
                    name=ps.name, count=ps.count,
                    requests=dict(ps.requests), min_count=ps.min_count,
                    topology_request=ps.topology_request,
                    node_selector=dict(ps.node_selector),
                    tolerations=list(ps.tolerations),
                ) for ps in parent.podsets],
                creation_time=parent.creation_time,
                parent_workload=parent.key,
                allowed_flavor=flavor,
                owner=parent.owner,
            )
            self.store.add_workload(v)

    def _sync_variant_eviction(self, parent: Workload,
                               variants: list[Workload], now: float) -> bool:
        """A parent that mirrors an admission whose variant lost it gets
        evicted too (controller.go syncVariantEvictionStatus). The winning
        variant's eviction clears its quota in the same step here, so the
        trigger is 'parent reserved but no variant currently admitted'."""
        if not parent.is_quota_reserved:
            return False
        if admitted_variant(variants) is not None:
            return False
        parent.set_condition(
            WorkloadConditionType.EVICTED, True,
            reason="VariantEvicted",
            message="Admitted variant was evicted", now=now)
        parent.set_condition(
            WorkloadConditionType.QUOTA_RESERVED, False,
            reason="VariantEvicted", now=now)
        parent.set_condition(
            WorkloadConditionType.ADMITTED, False,
            reason="VariantEvicted", now=now)
        parent.status.admission = None
        self.store.update_workload(parent)
        # Stop this pass (reference: "return to wait for parent to lose
        # quota"); the next reconcile re-activates variants as needed.
        return True

    def _deactivate_variant(self, v: Workload, reason: str, now: float,
                            message: str = "") -> None:
        if not v.active:
            return
        v.active = False
        if v.is_quota_reserved:
            self.scheduler.evict_workload(
                v.key, reason=reason, message=message or reason, now=now,
                requeue=False)
        else:
            # The store update event already removes the now-inactive
            # variant from the pending queues.
            self.store.update_workload(v)

    def _activate_variant(self, v: Workload, now: float) -> None:
        v.active = True
        v.set_condition(WorkloadConditionType.EVICTED, False,
                        reason="ActivatedVariant", now=now)
        self.store.update_workload(v)

    def _sync_parent_status(self, parent: Workload,
                            admitted: Optional[Workload], now: float) -> None:
        """Mirror the winning variant's admission onto the parent
        (controller.go syncAdmissionStatus)."""
        if admitted is None:
            return
        if not parent.is_admitted:
            parent.status.admission = admitted.status.admission
            parent.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                                 reason="VariantAdmitted", now=now)
            parent.set_condition(WorkloadConditionType.ADMITTED, True,
                                 reason="VariantAdmitted", now=now)
            self.store.update_workload(parent)
        elif parent.status.admission is not admitted.status.admission:
            parent.status.admission = admitted.status.admission
            self.store.update_workload(parent)
