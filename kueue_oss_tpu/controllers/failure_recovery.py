"""Node-failure detection and recovery.

Reference parity: the TAS node watchers marking workload nodes unhealthy
(pkg/controller/tas, gates TASFailedNodeReplacement*) plus
pkg/controller/failurerecovery/pod_termination_controller.go:60-263 —
pods stuck Terminating on NotReady/unreachable nodes are force-released
after a grace period so the workload can reschedule.

Flow: a node NotReady (or deleted) past the grace period is appended to
the UnhealthyNodes of every admitted workload whose topology assignment
uses it. With TASFailedNodeReplacement on, an in-place single-node
replacement is attempted against a fresh snapshot (the second-pass
analog, tas_flavor_snapshot.go:614-758); when replacement is impossible
the workload is evicted — immediately under TASFailedNodeReplacementFailFast,
otherwise after the recovery timeout — releasing its quota the way the
reference's force-deletion releases stuck pods.
"""

from __future__ import annotations

from kueue_oss_tpu import features
from kueue_oss_tpu.api.types import Workload
from kueue_oss_tpu.core.snapshot import build_snapshot
from kueue_oss_tpu.core.store import Store


class NodeFailureController:
    def __init__(self, store: Store, scheduler,
                 grace_period_s: float = 30.0,
                 recovery_timeout_s: float = 300.0) -> None:
        self.store = store
        self.scheduler = scheduler
        self.grace_period_s = grace_period_s
        self.recovery_timeout_s = recovery_timeout_s
        #: node name -> first time it was observed NotReady/missing
        self._not_ready_since: dict[str, float] = {}
        #: workload key -> time its node was declared unhealthy
        self._unhealthy_since: dict[str, float] = {}

    # -- node health tracking ----------------------------------------------

    def _failed_nodes(self, now: float) -> set[str]:
        """Nodes NotReady (or referenced by assignments but deleted) for
        longer than the grace period."""
        observed: set[str] = set()
        for node in self.store.nodes.values():
            if not node.ready:
                observed.add(node.name)
        for wl in self.store.admitted_workloads():
            for name in self._assigned_nodes(wl):
                if name not in self.store.nodes:
                    observed.add(name)
        for name in observed:
            self._not_ready_since.setdefault(name, now)
        for name in list(self._not_ready_since):
            if name not in observed:
                del self._not_ready_since[name]  # recovered
        return {name for name, since in self._not_ready_since.items()
                if now - since >= self.grace_period_s}

    @staticmethod
    def _assigned_nodes(wl: Workload) -> set[str]:
        out: set[str] = set()
        if wl.status.admission is None:
            return out
        for psa in wl.status.admission.podset_assignments:
            ta = psa.topology_assignment
            if ta is None:
                continue
            for dom in ta.domains:
                if dom.values:
                    out.add(dom.values[-1])  # host level is last
        return out

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, now: float) -> None:
        failed = self._failed_nodes(now)
        for wl in list(self.store.admitted_workloads()):
            # Prune nodes that recovered (Ready again) from the unhealthy
            # list before acting — a flapping node must not strand the
            # workload in a permanently-unhealthy state.
            if wl.status.unhealthy_nodes:
                still_bad = [
                    n for n in wl.status.unhealthy_nodes
                    if n not in self.store.nodes
                    or not self.store.nodes[n].ready]
                if still_bad != wl.status.unhealthy_nodes:
                    wl.status.unhealthy_nodes = still_bad
                    self.store.update_workload(wl)
                if not still_bad:
                    self._unhealthy_since.pop(wl.key, None)
            bad = self._assigned_nodes(wl) & failed
            new = sorted(bad - set(wl.status.unhealthy_nodes))
            if new:
                wl.status.unhealthy_nodes.extend(new)
                self.store.update_workload(wl)
            if not wl.status.unhealthy_nodes:
                continue
            # Anchor the recovery-timeout clock at first observation by
            # this controller instance (covers pre-existing unhealthy
            # state after a controller restart).
            self._unhealthy_since.setdefault(wl.key, now)
            self._try_recover(wl, now)

    def _try_recover(self, wl: Workload, now: float) -> None:
        replaced = False
        if (features.enabled("TASFailedNodeReplacement")
                and len(wl.status.unhealthy_nodes) == 1):
            replaced = self._attempt_replacement(wl, now)
        if replaced:
            wl.status.unhealthy_nodes = []
            self._unhealthy_since.pop(wl.key, None)
            self.store.update_workload(wl)
            return
        fail_fast = features.enabled("TASFailedNodeReplacementFailFast")
        waited = now - self._unhealthy_since.get(wl.key, now)
        if fail_fast or waited >= self.recovery_timeout_s:
            # Stuck on a dead node: release the workload so it can be
            # rescheduled (failurerecovery force-delete analog).
            self._unhealthy_since.pop(wl.key, None)
            self.scheduler.evict_workload(
                wl.key, reason="NodeFailures",
                message=f"node(s) {wl.status.unhealthy_nodes} failed and "
                        "no replacement was possible",
                now=now, underlying_cause="NodeFailures")

    # -- in-place replacement (second-pass analog) --------------------------

    def _attempt_replacement(self, wl: Workload, now: float) -> bool:
        cq_name = (wl.status.admission.cluster_queue
                   if wl.status.admission is not None else None)
        if cq_name is None:
            return False
        snapshot = build_snapshot(self.store)
        cq = snapshot.cluster_queue(cq_name)
        if cq is None:
            return False
        from kueue_oss_tpu import tas as tas_pkg

        tas_requests = tas_pkg.requests_from_admission(wl, cq)
        if not tas_requests:
            return False
        # Current usage (own included) stays charged: _replace_unhealthy
        # re-places only the failed node's pods, and the surviving domains
        # must keep occupying their capacity.
        result = cq.find_topology_assignments_for_workload(
            tas_requests, workload=wl)
        by_name = {}
        for ps_name, res in result.items():
            if res.failure:
                return False
            by_name[ps_name] = res.assignment
        for psa in wl.status.admission.podset_assignments:
            if psa.topology_assignment is not None and psa.name in by_name:
                psa.topology_assignment = by_name[psa.name]
        return True
