"""PersistenceManager: WAL wiring, checkpoints, recovery.

One manager owns one durability directory:

    <dir>/checkpoint-<n>.ckpt   atomic snapshots (checkpoint.py)
    <dir>/wal-<n>.log           records appended AFTER checkpoint n

``attach(store)`` subscribes to ``Store._emit``: every mutation event
becomes one WAL ``event`` record carrying the full post-mutation
object. Scheduler decision paths additionally call ``intent()`` BEFORE
mutating — the intent is fsynced (a write barrier) and carries the
workload's pre-mutation resource_version, so recovery can verify which
decisions applied (a following event at rv+1) and which the crash ate
(the scheduler simply redoes those from the recovered state).

Checkpoints rotate the WAL: sync the active segment, write
checkpoint n+1 atomically, open wal-(n+1).log, then delete segments
and checkpoints the retention window no longer needs. A crash at any
point leaves a recoverable prefix: an unpublished checkpoint temp file
is never considered, and an unrotated WAL still pairs with the
previous checkpoint.

Recovery = newest valid checkpoint + replay of its WAL segment,
tolerant of a torn tail. Replay applies events RAW (no version bumps,
no metric side effects) with a resource-version guard so records that
raced on the emit path converge to the newest state. With
``emit=True`` every applied object is re-emitted through the store's
watch stream, so a promoted replica's watch-driven caches
(QueueManager heaps) warm during the replay itself.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Optional

from kueue_oss_tpu import metrics
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.persist import checkpoint as ckpt
from kueue_oss_tpu.persist import codec, hooks
from kueue_oss_tpu.persist.wal import FSYNC_BATCH, WriteAheadLog, replay_wal

_SEG = re.compile(r"^wal-(\d+)\.log$")
_OBS = re.compile(r"^(journal|ledger)-(\d+)\.jsonl$")


def _segment_path(dir_path: str, seg: int) -> str:
    return os.path.join(dir_path, f"wal-{seg:08d}.log")


def _obs_path(dir_path: str, kind: str, ckpt_id: int) -> str:
    return os.path.join(dir_path, f"{kind}-{ckpt_id:08d}.jsonl")


def apply_event(store: Store, verb: str, kind: str, obj_dict: dict,
                emit: bool = False) -> bool:
    """Apply one WAL event record to the store, raw.

    Objects land verbatim (their recorded post-mutation state). For
    workloads, a record older than the stored resource_version is
    skipped: watchers run outside the store lock, so two racing writes
    can reach the WAL in either order — last-state-wins converges both
    orders to the same store. Returns True when the record changed the
    store.
    """
    if kind not in codec.KINDS:
        return False
    attr, _cls, key_of = codec.KINDS[kind]
    obj = codec.from_dict(kind, obj_dict)
    key = key_of(obj)
    changed = True
    with store._lock:
        target = getattr(store, attr)
        if verb == "delete":
            if kind == "Workload":
                live = target.get(key)
                if (live is not None
                        and live.resource_version > obj.resource_version):
                    # the record order raced a newer re-insert (watchers
                    # run outside the store lock): last state wins, the
                    # stale delete is dropped — mirroring the update
                    # branch's guard
                    return False
            existed = target.pop(key, None) is not None
            if kind == "Workload":
                store._admitted.pop(key, None)
                store._admitted_infos.pop(key, None)
                store._finished_counted.discard(key)
            elif kind == "ClusterQueue":
                store.cq_generation.pop(key, None)
            changed = existed
        else:
            if kind == "Workload":
                live = target.get(key)
                if (live is not None
                        and live.resource_version > obj.resource_version):
                    return False
                target[key] = obj
                store._index_workload(obj)
                if obj.is_finished:
                    store._finished_counted.add(key)
            else:
                target[key] = obj
                if kind == "ClusterQueue":
                    store.cq_generation[key] = (
                        store.cq_generation.get(key, 0) + 1)
    if changed and emit:
        store._emit(verb, kind, obj)
    return changed


def materialize_chain(chain: list[tuple[dict, bytes]]) -> Store:
    """Rebuild a Store from a resolved checkpoint chain
    (``checkpoint.newest_valid_chain`` order: full base first).

    The base loads through ``codec.store_from_dict``; each incremental
    then upserts its ``changed`` objects, removes its ``deleted`` keys
    and replaces the store-level maps it carries whole. Indexes and
    the uid floor are recomputed once at the end — the result is
    byte-identical (canonical_dump) to the store the full-dump path
    would have checkpointed at the same instant (property-tested in
    tests/test_streaming.py).
    """
    base_meta, base_state = chain[0]
    store = codec.store_from_dict(json.loads(base_state))
    for meta, state in chain[1:]:
        data = json.loads(state)
        with store._lock:
            for kind, objs in data.get("changed", {}).items():
                if kind not in codec.KINDS:
                    continue
                attr, _cls, _key_of = codec.KINDS[kind]
                target = getattr(store, attr)
                for key, od in objs.items():
                    target[key] = codec.from_dict(kind, od)
            for kind, keys in data.get("deleted", {}).items():
                if kind not in codec.KINDS:
                    continue
                attr, _cls, _key_of = codec.KINDS[kind]
                target = getattr(store, attr)
                for key in keys:
                    target.pop(key, None)
            store.namespaces = {
                ns: dict(labels) for ns, labels
                in data.get("namespaces", {}).items()}
            store.cq_generation = {
                k: int(v) for k, v
                in data.get("cq_generation", {}).items()}
    with store._lock:
        codec.rebuild_indexes(store)
    codec.advance_uid_floor(max(
        (wl.uid for wl in store.workloads.values()), default=0))
    return store


@dataclass
class RecoveryResult:
    store: Store
    checkpoint_id: int = 0
    replayed_events: int = 0
    replayed_intents: int = 0
    unapplied_intents: int = 0
    fence_violations: int = 0
    torn_tail: bool = False
    #: obs rings restored from the journal/ledger dumps written at
    #: checkpoint time (docs/OBSERVABILITY.md "Cluster health & SLOs")
    journal_events_restored: int = 0
    ledger_rows_restored: int = 0

    def to_dict(self) -> dict:
        return {"checkpoint_id": self.checkpoint_id,
                "replayed_events": self.replayed_events,
                "replayed_intents": self.replayed_intents,
                "unapplied_intents": self.unapplied_intents,
                "fence_violations": self.fence_violations,
                "torn_tail": self.torn_tail,
                "journal_events_restored": self.journal_events_restored,
                "ledger_rows_restored": self.ledger_rows_restored}


class PersistenceManager:
    def __init__(self, dir_path: str, fsync: str = FSYNC_BATCH,
                 batch_records: int = 64,
                 checkpoint_interval_records: int = 10_000,
                 checkpoint_interval_seconds: float = 300.0,
                 keep_checkpoints: int = 2,
                 audit_interval_seconds: float = 0.0,
                 audit_auto_heal: bool = False,
                 persist_obs: bool = True,
                 incremental: bool = False,
                 full_checkpoint_every: int = 16,
                 ship_to: Optional[str] = None,
                 ship_compact: bool = True,
                 clock=time.monotonic) -> None:
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self.fsync = fsync
        #: incremental checkpoints (docs/DURABILITY.md): delta against
        #: the previous checkpoint keyed by the event-driven dirty
        #: sets, making sub-second cadences affordable (a full 50k-
        #: workload serialize costs seconds; a <5% dirty delta costs a
        #: small fraction of that). Every full_checkpoint_every-th
        #: checkpoint (and the first after attach/recovery, whose
        #: dirty baseline is unknown) is a full dump, bounding chain
        #: length and recovery fan-in.
        self.incremental = incremental
        self.full_checkpoint_every = max(1, int(full_checkpoint_every))
        #: per-kind dirty/deleted keys since the last checkpoint —
        #: maintained by the same watch events the WAL logs, so the
        #: delta is exactly what the WAL suffix would replay
        self._dirty: dict[str, set] = {}
        self._deleted: dict[str, set] = {}
        #: True only while dirty tracking has been continuous since a
        #: checkpoint THIS manager wrote (the delta baseline)
        self._baseline_ok = False
        self._incr_since_full = 0
        #: WAL log shipping to a warm standby (persist/shipping.py):
        #: every flush ships the synced tail, every rotation ships the
        #: sealed (compacted) segment + checkpoint
        self.shipper = None
        if ship_to:
            from kueue_oss_tpu.persist.shipping import LogShipper

            self.shipper = LogShipper(ship_to, compact=ship_compact)
        #: dump/restore the obs journal + cycle-ledger rings alongside
        #: checkpoints so explain/replay and per-cycle health records
        #: survive restarts (closes the ROADMAP durability item)
        self.persist_obs = persist_obs
        self.batch_records = batch_records
        self.checkpoint_interval_records = checkpoint_interval_records
        self.checkpoint_interval_seconds = checkpoint_interval_seconds
        self.keep_checkpoints = max(1, keep_checkpoints)
        #: background invariant-auditor cadence; attach() starts the
        #: thread when > 0 (PersistenceConfig.audit_interval_seconds)
        self.audit_interval_seconds = audit_interval_seconds
        self.audit_auto_heal = audit_auto_heal
        self.auditor = None
        self.clock = clock
        self._lock = threading.RLock()
        self.store: Optional[Store] = None
        self._replaying = False
        self._records_since_ckpt = 0
        self._last_ckpt_at = clock()
        # a crash between checkpoint temp-write and publish leaves the
        # temp file behind; it is never trusted, so sweep it on start
        for name in os.listdir(dir_path):
            if ".ckpt.tmp." in name:
                try:
                    os.unlink(os.path.join(dir_path, name))
                except OSError:
                    pass
        ckpts = ckpt.list_checkpoints(dir_path)
        self.segment = ckpts[0][0] if ckpts else 0
        self.wal = WriteAheadLog(_segment_path(dir_path, self.segment),
                                 fsync=fsync, batch_records=batch_records)
        if self.shipper is not None:
            self._bootstrap_shipping()

    def _bootstrap_shipping(self) -> None:
        """Ship the pre-existing durable state once: every published
        checkpoint and every sealed segment, so a standby attached to
        a mid-life primary can bootstrap (checkpoint chain + suffix)
        instead of needing segment zero."""
        for _ckpt_id, path in ckpt.list_checkpoints(self.dir):
            try:
                self.shipper.ship_checkpoint(path)
            except OSError:
                pass
        for name in sorted(os.listdir(self.dir)):
            m = _SEG.match(name)
            if m and int(m.group(1)) < self.segment:
                try:
                    self.shipper.ship_sealed(
                        int(m.group(1)), os.path.join(self.dir, name))
                except OSError:
                    pass

    @classmethod
    def from_config(cls, cfg) -> "PersistenceManager":
        """Build from config.PersistenceConfig (dir required)."""
        if not cfg.dir:
            raise ValueError("persistence.dir is required")
        return cls(cfg.dir, fsync=cfg.fsync,
                   batch_records=cfg.batch_records,
                   checkpoint_interval_records=(
                       cfg.checkpoint_interval_records),
                   checkpoint_interval_seconds=(
                       cfg.checkpoint_interval_seconds),
                   keep_checkpoints=cfg.keep_checkpoints,
                   audit_interval_seconds=cfg.audit_interval_seconds,
                   audit_auto_heal=cfg.audit_auto_heal,
                   incremental=cfg.incremental_checkpoints,
                   full_checkpoint_every=cfg.full_checkpoint_every,
                   ship_to=cfg.ship_to,
                   ship_compact=cfg.ship_compact)

    # -- logging -----------------------------------------------------------

    def attach(self, store: Store) -> None:
        """Subscribe to the store's watch stream and become its
        ``store.persistence`` handle (the scheduler and solver engine
        find the intent/flush surface there). With a configured audit
        cadence, the background invariant auditor starts here too."""
        self.store = store
        store.persistence = self
        store.watch(self._on_event)
        if self.audit_interval_seconds > 0 and self.auditor is None:
            from kueue_oss_tpu.persist.auditor import InvariantAuditor

            self.auditor = InvariantAuditor(
                store, auto_heal=self.audit_auto_heal)
            self.auditor.start(interval_s=self.audit_interval_seconds)

    def _on_event(self, event) -> None:
        if self._replaying:
            return
        verb, kind, obj = event
        if kind not in codec.KINDS:
            return
        rec = {"t": "event", "verb": verb, "kind": kind,
               "obj": codec.to_dict(obj)}
        with self._lock:
            self.wal.append(rec, kind="event")
            self._records_since_ckpt += 1
            if self.incremental:
                _attr, _cls, key_of = codec.KINDS[kind]
                key = key_of(obj)
                if verb == "delete":
                    self._dirty.get(kind, set()).discard(key)
                    self._deleted.setdefault(kind, set()).add(key)
                else:
                    self._deleted.get(kind, set()).discard(key)
                    self._dirty.setdefault(kind, set()).add(key)

    def intent(self, op: str, wl_key: str, rv: int, *, cycle: int = 0,
               cluster_queue: str = "", detail: Optional[dict] = None
               ) -> None:
        """Durable decision record, written BEFORE the store mutation.

        ``rv`` is the workload's pre-mutation resource_version — the
        fence ``update_workload_if`` preconditions on; the mutation the
        intent announces lands at rv+1, which is how recovery tells an
        applied decision from a lost one.

        Durability follows the configured fsync policy: the intent is
        appended to the same WAL strictly before its event, so file
        order alone guarantees recovery never sees an event without
        its fence — a per-intent fsync under group commit would buy
        nothing (this control plane has no external side effects
        between intent and apply) while costing one fsync per admitted
        workload on drain-heavy cycles.
        """
        rec = {"t": "intent", "op": op, "wl": wl_key, "rv": int(rv),
               "cycle": int(cycle), "cq": cluster_queue}
        if detail:
            rec["detail"] = detail
        with self._lock:
            self.wal.append(rec, kind="intent")
            self._records_since_ckpt += 1
        hooks.crash_if("post_fsync_pre_apply")

    def flush(self) -> None:
        """Cycle-end group commit + checkpoint cadence check. With a
        shipper attached, the freshly durable tail ships before the
        cadence check — failover cost stays bounded by one flush."""
        with self._lock:
            self.wal.sync()
            if self.shipper is not None:
                try:
                    self.shipper.ship_tail(self.segment, self.wal.path,
                                           self.wal.synced_size)
                except OSError:
                    pass  # a dead standby must never stall the plane
        self.maybe_checkpoint()

    # -- checkpoints -------------------------------------------------------

    def maybe_checkpoint(self) -> bool:
        if self.store is None:
            return False
        with self._lock:
            due = (self._records_since_ckpt
                   >= self.checkpoint_interval_records)
            if (not due and self.checkpoint_interval_seconds > 0
                    and self._records_since_ckpt > 0):
                due = (self.clock() - self._last_ckpt_at
                       >= self.checkpoint_interval_seconds)
            if not due:
                return False
        self.checkpoint()
        return True

    def _incremental_state(self, base_id: int) -> bytes:
        """Delta payload against checkpoint ``base_id``: the dirty
        keys' full post-mutation objects + deleted keys, plus the
        (small) store-level maps carried whole. Byte-stable like the
        full dump — canonical JSON of a sorted structure."""
        store = self.store
        changed: dict[str, dict] = {}
        deleted: dict[str, list] = {}
        with store._lock:
            for kind, keys in self._dirty.items():
                if not keys:
                    continue
                attr, _cls, _key_of = codec.KINDS[kind]
                target = getattr(store, attr)
                out: dict[str, dict] = {}
                for key in keys:
                    obj = target.get(key)
                    if obj is None:
                        # raced a delete whose event we also saw; the
                        # deleted set already covers it
                        deleted.setdefault(kind, []).append(key)
                    else:
                        out[key] = codec.to_dict(obj)
                if out:
                    changed[kind] = out
            for kind, keys in self._deleted.items():
                if keys:
                    deleted.setdefault(kind, []).extend(keys)
            payload = {
                "version": 1,
                "base": int(base_id),
                "changed": changed,
                "deleted": {k: sorted(set(v))
                            for k, v in deleted.items()},
                "namespaces": {ns: dict(labels) for ns, labels
                               in store.namespaces.items()},
                "cq_generation": dict(store.cq_generation),
            }
        return codec.canonical_json(payload)

    def checkpoint(self, force_full: bool = False) -> int:
        """Atomic checkpoint + WAL rotation; returns the new id.

        With ``incremental`` enabled, the payload is a delta against
        the previous checkpoint (tracked dirty keys) unless the chain
        budget is spent, the baseline is unknown (first checkpoint of
        this manager's life, or right after a recovery), or
        ``force_full``.
        """
        if self.store is None:
            raise RuntimeError("no store attached")
        t0 = time.monotonic()
        with self._lock:
            self.wal.sync()
            incr = (self.incremental and self._baseline_ok
                    and not force_full
                    and self._incr_since_full + 1
                    < self.full_checkpoint_every)
            extra_meta = None
            if incr:
                state = self._incremental_state(self.segment)
                extra_meta = {"kind": "incremental",
                              "base": int(self.segment)}
            else:
                state = codec.canonical_dump(self.store)
            new_id = self.segment + 1
            try:
                # open the NEW segment before publishing the
                # checkpoint: if this fails (ENOSPC, EMFILE) nothing
                # was published and appends continue into the old
                # segment, still covered by the old checkpoint. The
                # reverse order would strand post-checkpoint records
                # in a segment recovery never replays. A stray empty
                # wal-(n+1).log from a crash between these steps is
                # harmless — replay visits it and finds nothing.
                new_wal = WriteAheadLog(
                    _segment_path(self.dir, new_id),
                    fsync=self.fsync, batch_records=self.batch_records)
                try:
                    ckpt_path = ckpt.write_checkpoint(
                        self.dir, new_id, state,
                        extra_meta=extra_meta)
                except BaseException:
                    new_wal.close()
                    raise
            except Exception:
                metrics.checkpoints_total.inc("failed")
                raise
            # rotate: records from here on belong to the new segment
            old_wal, self.wal = self.wal, new_wal
            old_wal.close()
            old_path = _segment_path(self.dir, self.segment)
            old_seg = self.segment
            ckpt.fsync_dir(self.dir)
            self.segment = new_id
            self._records_since_ckpt = 0
            self._last_ckpt_at = self.clock()
            # the dirty baseline resets: the checkpoint just written
            # covers everything tracked so far
            self._dirty = {}
            self._deleted = {}
            self._baseline_ok = True
            self._incr_since_full = (self._incr_since_full + 1
                                     if incr else 0)
            if self.shipper is not None:
                # rotation shipping: seal (compact) the outgoing
                # segment, then the checkpoint — best-effort, a dead
                # standby never unpublishes a checkpoint
                try:
                    self.shipper.ship_sealed(old_seg, old_path)
                    self.shipper.ship_checkpoint(ckpt_path)
                except OSError:
                    pass
            if not incr:
                # obs rings ride FULL checkpoints only: bounded rings
                # re-dumped at sub-second incremental cadence would
                # dominate the bytes the delta just saved
                self._dump_obs_rings(new_id)
            self._prune(new_id)
        metrics.checkpoints_total.inc(
            "incremental" if incr else "written")
        metrics.checkpoint_bytes.set(
            "incremental" if incr else "full", value=len(state))
        metrics.checkpoint_duration_seconds.observe(
            value=time.monotonic() - t0)
        return new_id

    def _dump_obs_rings(self, ckpt_id: int) -> None:
        """Persist the decision journal and the cycle ledger next to
        the checkpoint (dump_jsonl is already atomic + dir-fsynced).
        Best-effort: the checkpoint itself is the durability contract;
        a failed ring dump is logged via the failed counter but must
        never unpublish a checkpoint that already landed."""
        if not self.persist_obs:
            return
        from kueue_oss_tpu import obs

        # each ring dumps in its own try: a journal ENOSPC must not
        # also cost the ledger its dump for this checkpoint
        for kind, ring in (("journal", obs.recorder),
                           ("ledger", obs.cycle_ledger)):
            try:
                ring.dump_jsonl(_obs_path(self.dir, kind, ckpt_id))
            except OSError:
                metrics.checkpoints_total.inc(f"obs_{kind}_dump_failed")

    def _prune(self, newest_id: int) -> None:
        """WAL truncation on checkpoint success: drop checkpoints
        beyond the retention window and every WAL segment older than
        the oldest retained checkpoint. Retention closes over delta
        chains: a retained incremental keeps its full base (and every
        intermediate link) alive regardless of the window — pruning a
        base would orphan every incremental above it."""
        listed = ckpt.list_checkpoints(self.dir)
        retained: set[int] = set()
        for ckpt_id, _path in listed[:self.keep_checkpoints]:
            retained |= ckpt.chain_ids(self.dir, ckpt_id)
        oldest_kept = min(retained, default=newest_id)
        for ckpt_id, path in listed:
            if ckpt_id in retained:
                continue
            try:
                os.unlink(path)
            except OSError:
                pass
        for name in os.listdir(self.dir):
            m = _SEG.match(name)
            if m and int(m.group(1)) < oldest_kept:
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass
            mo = _OBS.match(name)
            if mo and int(mo.group(2)) < oldest_kept:
                # obs ring dumps retire with their checkpoint
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass

    # -- recovery ----------------------------------------------------------

    def recover(self, store: Optional[Store] = None,
                emit: bool = False) -> RecoveryResult:
        """Rebuild state: newest valid checkpoint + WAL suffix replay.

        ``store=None`` builds a fresh Store. Passing an existing store
        (a promoted replica warming up) SYNCS it to durable state —
        upserts for everything durable, deletes for anything the store
        holds that durable state does not (a re-promoted ex-leader may
        carry objects deleted during its time as follower); with
        ``emit=True`` every applied change re-emits through the watch
        stream so watch-driven caches warm in the same pass.
        """
        chain = ckpt.newest_valid_chain(self.dir)
        loaded = chain is not None
        # durable state is always materialized into a fresh raw store
        # first — a pure function of checkpoint + log, independent of
        # whatever the target store currently holds
        result = RecoveryResult(store=Store())
        # any pre-recovery dirty baseline is void: the next checkpoint
        # after a recovery is always a full dump
        self._baseline_ok = False
        self._dirty = {}
        self._deleted = {}
        self._replaying = True
        try:
            if loaded:
                result.checkpoint_id = int(chain[-1][0]["id"])
                result.store = materialize_chain(chain)
            self._replay_segments(result, emit=False,
                                  start=result.checkpoint_id)
            # the active segment's torn tail may have been truncated
            # away when this manager opened it — still a torn tail
            result.torn_tail = (result.torn_tail
                                or self.wal.truncated_bytes > 0)
            # the uid floor must cover WAL-replayed workloads too (and
            # WAL-only recoveries, which never touch the checkpoint
            # branch): a re-issued uid would alias queue-order
            # tie-breaks and session slots
            codec.advance_uid_floor(max(
                (wl.uid for wl in result.store.workloads.values()),
                default=0))
            if store is not None:
                self._sync_into(store, result.store, emit=emit)
                result.store = store
        finally:
            self._replaying = False
        self._restore_obs_rings(result)
        metrics.recovery_total.inc(
            "checkpoint" if loaded else
            ("wal_only" if result.replayed_events else "empty"))
        metrics.recovery_replayed_records.set(
            value=result.replayed_events + result.replayed_intents)
        return result

    def _restore_obs_rings(self, result: RecoveryResult) -> None:
        """Restore the decision journal and cycle ledger from the
        newest ring dumps in the durability dir, so ``explain`` /
        journal replay and per-cycle health records survive a restart.
        Loaders are torn-line tolerant; a missing dump (pre-upgrade
        dir, or rings disabled at dump time) restores nothing."""
        if not self.persist_obs:
            return
        from kueue_oss_tpu import obs

        # each ring restores from ITS OWN newest dump: a failed ledger
        # dump at checkpoint N must not hide the intact ledger-(N-1)
        # behind a journal-N that did land
        newest: dict[str, int] = {}
        for n in os.listdir(self.dir):
            m = _OBS.match(n)
            if m and int(m.group(2)) > newest.get(m.group(1), -1):
                newest[m.group(1)] = int(m.group(2))
        if "journal" in newest:
            result.journal_events_restored = obs.recorder.restore(
                obs.load_jsonl(_obs_path(self.dir, "journal",
                                         newest["journal"])))
            # the SLO windows die with the process; rebuild them from
            # the restored journal's recorded waits so burn state (and
            # a firing alert) survives the restart (docs/DURABILITY.md)
            obs.slo_engine.replay_journal(obs.recorder.events())
        if "ledger" in newest:
            result.ledger_rows_restored = obs.cycle_ledger.restore(
                obs.load_ledger_jsonl(_obs_path(self.dir, "ledger",
                                                newest["ledger"])))

    @staticmethod
    def _sync_into(target: Store, durable: Store, emit: bool) -> None:
        """Make `target` mirror `durable`: delete extras, upsert the
        rest, all raw (no version bumps), then re-emit each change so
        the target's watchers track the sync. Emission follows the
        store convention (outside the lock, after the mutation)."""
        events: list[tuple[str, str, object]] = []
        with target._lock:
            for kind, (attr, _cls, _key_of) in codec.KINDS.items():
                src = getattr(durable, attr)
                dst = getattr(target, attr)
                for key in [k for k in dst if k not in src]:
                    gone = dst.pop(key)
                    if kind == "ClusterQueue":
                        target.cq_generation.pop(key, None)
                    events.append(("delete", kind, gone))
                for key, obj in src.items():
                    dst[key] = obj
                    events.append(("update", kind, obj))
            target.namespaces = {ns: dict(labels) for ns, labels
                                 in durable.namespaces.items()}
            target.cq_generation = dict(durable.cq_generation)
            codec.rebuild_indexes(target)
        if emit:
            for verb, kind, obj in events:
                target._emit(verb, kind, obj)

    def _replay_segments(self, result: RecoveryResult, emit: bool,
                         start: int) -> None:
        seg_ids = sorted(
            int(m.group(1)) for m in
            (_SEG.match(n) for n in os.listdir(self.dir)) if m)
        #: intent fences awaiting their apply event: wl key -> [rv]
        pending: dict[str, list[int]] = {}
        for seg in seg_ids:
            if seg < start:
                continue
            records, torn = replay_wal(_segment_path(self.dir, seg))
            result.torn_tail = result.torn_tail or torn
            for rec in records:
                if rec.get("t") == "intent":
                    result.replayed_intents += 1
                    pending.setdefault(rec["wl"], []).append(
                        int(rec["rv"]))
                    continue
                if rec.get("t") != "event":
                    continue
                result.replayed_events += 1
                kind, verb = rec["kind"], rec["verb"]
                if kind == "Workload":
                    key = rec["obj"].get("namespace", "") + "/" + \
                        rec["obj"].get("name", "")
                    fences = pending.get(key)
                    if fences:
                        rv = int(rec["obj"].get("resource_version", 0))
                        if verb == "delete" or rv == fences[0] + 1:
                            fences.pop(0)
                        elif rv > fences[0] + 1:
                            # the fence's mutation was skipped but a
                            # LATER write landed: the optimistic
                            # precondition was violated
                            result.fence_violations += 1
                            fences.pop(0)
                        if not fences:
                            pending.pop(key, None)
                apply_event(result.store, verb, kind, rec["obj"],
                            emit=emit)
        result.unapplied_intents = sum(len(v) for v in pending.values())

    def close(self) -> None:
        if self.auditor is not None:
            self.auditor.stop()
        # detach from the store: a scheduler that keeps cycling after
        # close() must fall back to the no-persistence path, not write
        # intents into a closed WAL
        store = getattr(self, "store", None)
        if store is not None:
            if getattr(store, "persistence", None) is self:
                store.persistence = None
            if self._on_event in store._watchers:
                store._watchers.remove(self._on_event)
        with self._lock:
            self.wal.close()
            if self.shipper is not None:
                # a clean shutdown leaves the standby fully caught up
                try:
                    self.shipper.ship_tail(self.segment, self.wal.path,
                                           self.wal.synced_size)
                except OSError:
                    pass
