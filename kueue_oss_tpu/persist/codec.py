"""Canonical serialization of API objects and whole stores.

Every durability surface (WAL event records, checkpoints, the crash
harness's byte-identity assertions) needs ONE encoding with two
properties:

- **round-trip fidelity**: decode(encode(obj)) reconstructs the object
  exactly, including optimistic-concurrency tokens (resource_version),
  uids, condition transition times, and nested assignment state — a
  recovered store must be indistinguishable from the one that crashed;
- **byte stability**: encoding the same logical state twice yields the
  same bytes (sorted keys, compact separators, no NaN), so "recovered
  == no-crash" is a byte comparison, not a fuzzy diff.

Encoding is ``dataclasses.asdict`` (tuples become JSON lists); decoding
is a generic typed walk over each dataclass's resolved field hints, so
the codec tracks the API model in ``api/types.py`` without a hand-kept
field list per kind. A test in tests/test_persist.py round-trips
randomized stores to keep that promise honest.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import types as _pytypes
import typing

from kueue_oss_tpu.api import types as api
from kueue_oss_tpu.core.store import Store

#: kind name -> (Store attribute, dataclass, key function)
KINDS = {
    "ClusterQueue": ("cluster_queues", api.ClusterQueue,
                     lambda o: o.name),
    "Cohort": ("cohorts", api.Cohort, lambda o: o.name),
    "LocalQueue": ("local_queues", api.LocalQueue, lambda o: o.key),
    "ResourceFlavor": ("resource_flavors", api.ResourceFlavor,
                       lambda o: o.name),
    "Topology": ("topologies", api.Topology, lambda o: o.name),
    "AdmissionCheck": ("admission_checks", api.AdmissionCheck,
                       lambda o: o.name),
    "WorkloadPriorityClass": ("priority_classes",
                              api.WorkloadPriorityClass,
                              lambda o: o.name),
    "Node": ("nodes", api.Node, lambda o: o.name),
    "Workload": ("workloads", api.Workload, lambda o: o.key),
}


def kind_of(obj) -> str | None:
    """The KINDS name for an API object instance, or None."""
    for kind, (_, cls, _key) in KINDS.items():
        if type(obj) is cls:
            return kind
    return None


def to_dict(obj) -> dict:
    return dataclasses.asdict(obj)


# -- generic typed decode ----------------------------------------------------

_HINTS: dict[type, dict] = {}


def _hints(cls) -> dict:
    if cls not in _HINTS:
        # resolves the `from __future__ import annotations` strings
        _HINTS[cls] = typing.get_type_hints(cls)
    return _HINTS[cls]


def _decode(tp, v):
    if v is None:
        return None
    origin = typing.get_origin(tp)
    if origin is typing.Union or origin is _pytypes.UnionType:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        return _decode(args[0], v) if args else v
    if origin is list:
        args = typing.get_args(tp)
        et = args[0] if args else typing.Any
        return [_decode(et, x) for x in v]
    if origin is tuple:
        args = typing.get_args(tp)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_decode(args[0], x) for x in v)
        if args:
            return tuple(_decode(t, x) for t, x in zip(args, v))
        return tuple(v)
    if origin is dict:
        args = typing.get_args(tp)
        vt = args[1] if len(args) == 2 else typing.Any
        return {k: _decode(vt, x) for k, x in v.items()}
    if dataclasses.is_dataclass(tp) and isinstance(tp, type):
        hints = _hints(tp)
        kwargs = {
            f.name: _decode(hints[f.name], v[f.name])
            for f in dataclasses.fields(tp)
            if f.init and f.name in v
        }
        return tp(**kwargs)
    return v


def from_dict(kind: str, data: dict):
    """Decode one API object of `kind` from its to_dict() form."""
    _, cls, _key = KINDS[kind]
    return _decode(cls, data)


# -- whole-store form --------------------------------------------------------


def store_to_dict(store: Store) -> dict:
    """The store's full durable state as one plain dict."""
    with store._lock:
        out: dict = {
            "version": 1,
            "namespaces": {ns: dict(labels)
                           for ns, labels in store.namespaces.items()},
            "cq_generation": dict(store.cq_generation),
        }
        for kind, (attr, _cls, _key) in KINDS.items():
            out[kind] = {key: to_dict(obj)
                         for key, obj in getattr(store, attr).items()}
        return out


def canonical_json(data) -> bytes:
    return json.dumps(data, sort_keys=True, separators=(",", ":"),
                      allow_nan=False).encode()


def canonical_dump(store: Store) -> bytes:
    """Byte-stable dump of the store — the crash harness's equality
    currency ("recovered == no-crash" is a byte comparison)."""
    return canonical_json(store_to_dict(store))


def store_from_dict(data: dict, store: Store | None = None) -> Store:
    """Rebuild a Store from store_to_dict() output.

    Objects land verbatim (no resource_version bumps, no priority
    resolution — they already carry their post-write state), the
    admitted/finished indexes are rebuilt from the restored workloads,
    and the process-wide uid counter is advanced past every restored
    uid so new workloads cannot collide with recovered ones.
    """
    out = store if store is not None else Store()
    with out._lock:
        metrics_were = out._metrics_enabled
        out._metrics_enabled = False
        out.namespaces = {ns: dict(labels)
                          for ns, labels in data.get("namespaces", {}).items()}
        out.cq_generation = {k: int(v)
                             for k, v in data.get("cq_generation", {}).items()}
        for kind, (attr, _cls, _key) in KINDS.items():
            target = getattr(out, attr)
            target.clear()
            for key, od in data.get(kind, {}).items():
                target[key] = from_dict(kind, od)
        rebuild_indexes(out)
        out._metrics_enabled = metrics_were
    advance_uid_floor(max((wl.uid for wl in out.workloads.values()),
                          default=0))
    return out


def rebuild_indexes(store: Store) -> None:
    """Recompute the admitted index, the cached-info side table and the
    finished-transition set from the workloads dict alone (recovery and
    the auditor's auto-heal share this)."""
    store._admitted.clear()
    store._admitted_infos.clear()
    store._finished_counted = {
        k for k, wl in store.workloads.items() if wl.is_finished}
    for wl in store.workloads.values():
        if wl.is_quota_reserved and not wl.is_finished:
            store._admitted[wl.key] = wl


def advance_uid_floor(floor: int) -> None:
    """Ensure freshly created Workloads get uids strictly above `floor`
    (recovery must not let the process-wide counter re-issue restored
    uids — queue ordering ties break on uid)."""
    if floor <= 0:
        return
    probe = next(api._uid_counter)
    nxt = max(probe, floor + 1)
    api._uid_counter = itertools.count(nxt)
