"""Invariant auditor: recompute admission accounting, diff the store.

The control plane's accounting (the store's admitted index, the
snapshot forest's per-CQ usage, cohort subtree rollups) is DERIVED
state — the durable truth is the admission records on the workloads
themselves. After a recovery, a leader failover, or simply months of
churn, the two can drift (a missed index update, a replayed event
applied twice, a bug). The auditor recomputes everything derivable
from the admitted workloads via the ``core/quota.py`` formulas and
diffs it against what the store's accounting path reports:

  admitted_index    -- Store._admitted vs the reserved-and-not-finished
                       predicate over the workloads dict
  finished_tracking -- Store._finished_counted vs the FINISHED condition
  usage_mismatch    -- per-CQ (flavor, resource) usage summed from
                       admission.podset_assignments.resource_usage vs
                       the snapshot forest built from store accounting
  cohort_usage      -- cohort-node usage after the bottom-up
                       QuotaForest.refresh rollup, both sides
  subtree_quota     -- cohort/CQ subtree quota, both sides
  admission_ref     -- an admitted workload charging a ClusterQueue
                       that no longer exists
  podset_mismatch   -- admission podset assignments not matching the
                       workload's podsets

Each violation bumps ``kueue_invariant_violations_total{check}``.
``auto_heal`` rebuilds the store's derived indexes from the workloads
dict (the only safe rebuild — spec/usage divergence is reported, never
silently rewritten) and re-audits.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from kueue_oss_tpu import metrics
from kueue_oss_tpu.core.quota import QuotaForest
from kueue_oss_tpu.core.store import Store


@dataclass
class Violation:
    check: str
    subject: str  # workload key / CQ / cohort the violation hangs on
    detail: str = ""
    expected: object = None
    actual: object = None

    def to_dict(self) -> dict:
        return {"check": self.check, "subject": self.subject,
                "detail": self.detail,
                "expected": repr(self.expected),
                "actual": repr(self.actual)}


def _nonzero(usage: dict) -> dict:
    return {fr: v for fr, v in usage.items() if v}


def recompute_cq_usage(store: Store) -> dict[str, dict]:
    """Per-CQ (flavor, resource) usage from the admission records of
    every reserved-and-not-finished workload — the durable ground
    truth, independent of any index or cache.

    Reclaimable pods release their share of a running admission
    (workload_info applies status.reclaimablePods when building usage),
    so the recompute scales each podset's recorded usage by the still-
    held pod count — the same ``scaled_to`` arithmetic, applied to the
    admission record instead of the cached info."""
    from kueue_oss_tpu import features

    reclaim_on = features.enabled("ReclaimablePods")
    usage: dict[str, dict] = {}
    for wl in store.workloads.values():
        if not wl.is_quota_reserved or wl.is_finished:
            continue
        adm = wl.status.admission
        if adm is None:
            continue
        rp = wl.status.reclaimable_pods if reclaim_on else {}
        cq = usage.setdefault(adm.cluster_queue, {})
        for psa in adm.podset_assignments:
            reclaimed = rp.get(psa.name, 0) if rp else 0
            for resource, qty in psa.resource_usage.items():
                flavor = psa.flavors.get(resource)
                if flavor is None:
                    continue
                if reclaimed and psa.count:
                    qty = (qty // psa.count) * max(
                        0, psa.count - reclaimed)
                fr = (flavor, resource)
                cq[fr] = cq.get(fr, 0) + qty
    return usage


class InvariantAuditor:
    """Audit on demand or on a background cadence."""

    def __init__(self, store: Store, auto_heal: bool = False) -> None:
        self.store = store
        self.auto_heal = auto_heal
        self.last_violations: list[Violation] = []
        self.audits_run = 0
        self.heals_run = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one audit pass ----------------------------------------------------

    def audit(self) -> list[Violation]:
        # The pass holds the store lock (an RLock, so the snapshot/info
        # paths that re-enter it are fine) so dict iteration never
        # races a store write. The lock is NOT sufficient against the
        # scheduler's in-place object mutations (conditions flip before
        # update_workload takes the lock), which is why the background
        # cadence uses audit_confirmed() — a violation must survive two
        # consecutive passes before it is reported or healed.
        with self.store._lock:
            out = self._audit_locked()
        return self._finish(out)

    def audit_confirmed(self) -> list[Violation]:
        """Two-pass audit for concurrent callers: only violations that
        indict the same (check, subject) in BOTH passes survive.

        A scheduler thread mutates workload objects in place before
        the store write lands, so a single pass can catch a torn
        half-written decision and indict a healthy store; the window
        is microseconds, so any such phantom resolves by the second
        pass. Real drift is persistent and survives both."""
        with self.store._lock:
            first = {(v.check, v.subject) for v in self._audit_locked()}
        if not first:
            return self._finish([])
        with self.store._lock:
            second = self._audit_locked()
        return self._finish(
            [v for v in second if (v.check, v.subject) in first])

    def _audit_locked(self) -> list[Violation]:
        out: list[Violation] = []
        store = self.store
        workloads = dict(store.workloads)
        indexed = set(store._admitted)
        finished_counted = set(store._finished_counted)

        expected_admitted = {
            k for k, wl in workloads.items()
            if wl.is_quota_reserved and not wl.is_finished}
        for k in sorted(expected_admitted - indexed):
            out.append(Violation(
                "admitted_index", k,
                "reserved workload missing from the admitted index"))
        for k in sorted(indexed - expected_admitted):
            out.append(Violation(
                "admitted_index", k,
                "admitted index holds a non-reserved workload"))

        expected_finished = {
            k for k, wl in workloads.items() if wl.is_finished}
        for k in sorted(expected_finished ^ finished_counted):
            out.append(Violation(
                "finished_tracking", k,
                "FINISHED condition and the finished-transition set "
                "disagree",
                expected=k in expected_finished,
                actual=k in finished_counted))

        for k, wl in sorted(workloads.items()):
            adm = wl.status.admission
            if adm is None or not wl.is_quota_reserved:
                continue
            if adm.cluster_queue not in store.cluster_queues:
                out.append(Violation(
                    "admission_ref", k,
                    f"admission charges missing ClusterQueue "
                    f"{adm.cluster_queue!r}"))
            ps_names = [ps.name for ps in wl.podsets]
            psa_names = [psa.name for psa in adm.podset_assignments]
            if sorted(ps_names) != sorted(psa_names):
                out.append(Violation(
                    "podset_mismatch", k,
                    "admission podset assignments do not cover the "
                    "workload's podsets",
                    expected=ps_names, actual=psa_names))

        # ground truth vs store accounting: same quota formulas, two
        # input paths — admission records vs the admitted-info cache
        truth_usage = recompute_cq_usage(store)
        truth = QuotaForest()
        try:
            truth.build(store.cluster_queues.values(),
                        store.cohorts.values(),
                        cq_usage={cq: u for cq, u in truth_usage.items()
                                  if cq in store.cluster_queues})
        except Exception as e:
            out.append(Violation("forest_build", "-", str(e)))
            return out
        from kueue_oss_tpu.core.snapshot import build_snapshot

        accounted = build_snapshot(store).forest
        for name, node in sorted(truth.cqs.items()):
            acc = accounted.cqs.get(name)
            acc_usage = _nonzero(acc.usage) if acc is not None else {}
            if _nonzero(node.usage) != acc_usage:
                out.append(Violation(
                    "usage_mismatch", name,
                    "per-CQ usage recomputed from admission records "
                    "disagrees with store accounting",
                    expected=_nonzero(node.usage), actual=acc_usage))
        for key, node in sorted(truth.nodes.items()):
            if node.is_cq:
                continue
            acc = accounted.nodes.get(key)
            if acc is None:
                out.append(Violation(
                    "cohort_usage", key,
                    "cohort present in recompute but not in accounting"))
                continue
            if _nonzero(node.usage) != _nonzero(acc.usage):
                out.append(Violation(
                    "cohort_usage", key,
                    "cohort usage rollup disagrees",
                    expected=_nonzero(node.usage),
                    actual=_nonzero(acc.usage)))
            if _nonzero(node.subtree_quota) != _nonzero(acc.subtree_quota):
                out.append(Violation(
                    "subtree_quota", key,
                    "cohort subtree quota disagrees",
                    expected=_nonzero(node.subtree_quota),
                    actual=_nonzero(acc.subtree_quota)))
        return out

    def _finish(self, out: list[Violation]) -> list[Violation]:
        self.audits_run += 1
        metrics.invariant_audits_total.inc()
        for v in out:
            metrics.invariant_violations_total.inc(v.check)
        metrics.invariant_last_violations.set(value=len(out))
        self.last_violations = out
        if out and self.auto_heal and self.heal():
            # post-heal re-check: what remains is spec/usage divergence
            # a rebuild cannot fix. Refresh the gauge and the public
            # list, but do NOT re-increment the counters — that would
            # count one incident twice per pass.
            with self.store._lock:
                out = self._audit_locked()
            metrics.invariant_last_violations.set(value=len(out))
            self.last_violations = out
        return out

    def heal(self) -> bool:
        """Rebuild the derived indexes from the workloads dict. Returns
        True when a heal ran (index-class violations present)."""
        if not any(v.check in ("admitted_index", "finished_tracking",
                               "usage_mismatch")
                   for v in self.last_violations):
            return False
        from kueue_oss_tpu.persist.codec import rebuild_indexes

        with self.store._lock:
            rebuild_indexes(self.store)
        self.heals_run += 1
        metrics.invariant_heals_total.inc()
        return True

    # -- background cadence ------------------------------------------------

    def start(self, interval_s: float = 60.0) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.audit_confirmed()
                except Exception:
                    # the auditor observes; it must never take the
                    # control plane down with it. An internal crash is
                    # an auditor defect, not state drift — it must not
                    # pollute the "must stay 0" violations series.
                    metrics.invariant_audit_errors_total.inc()

        self._thread = threading.Thread(
            target=loop, name="kueue-invariant-auditor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
