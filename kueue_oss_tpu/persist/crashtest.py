"""Crash-point chaos driver: kill -9 the control plane, then prove
recovery is byte-identical to never having crashed.

Run as a subprocess (``python -m kueue_oss_tpu.persist.crashtest``) by
tests/test_persist.py and docs/ROBUSTNESS.md operators:

  --phase run      build the deterministic scenario from scratch with
                   persistence attached and play it to completion,
                   writing <dir>/final.dump (the canonical store
                   bytes). With KUEUE_CRASH_POINT armed in the
                   environment the process SIGKILLs itself at the named
                   point instead of finishing — that IS the test run.
  --phase recover  recover the store from <dir> (newest valid
                   checkpoint + WAL suffix), then REPLAY the same
                   scenario script on top. Every step is idempotent
                   (ensure-object guards, finish-if-not-finished), so
                   from any crash point the rerun converges to the
                   no-crash end state; the phase writes
                   <dir>/final.dump and prints a JSON status line.

The equality contract: a baseline ``run`` (no crash) and a
``run``-crashed-then-``recover`` sequence must produce byte-identical
final.dump files. Determinism is engineered, not hoped for: every
virtual timestamp is a fixed phase constant (tick=0 cycles), workload
uids are assigned explicitly, and the scheduler/solver paths are the
deterministic production code the rebuild tests already pin down.

``--solver`` routes the T2/T3 admission floods through SolverEngine
drains (sessions enabled), which makes two more assertions available
to the recover phase: the first post-restart drain's session frame is
a full SYNC (resident device state is gone by design), and the
invariant auditor reports zero violations over the recovered store.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kueue_oss_tpu.persist import hooks

T1, T2, T3, T4 = 20.0, 30.0, 40.0, 50.0


def _mk_wl(name: str, uid: int, lq: str, cpu_m: int, prio: int,
           created: float):
    from kueue_oss_tpu.api.types import PodSet, Workload

    return Workload(
        name=name, namespace="default", queue_name=lq, priority=prio,
        uid=uid, creation_time=created,
        podsets=[PodSet(name="main", count=1, requests={"cpu": cpu_m})])


def ensure_objects(store) -> None:
    """Cluster topology; guarded so a recovery rerun emits no events
    (re-upserting identical specs would still bump cq_generation)."""
    from kueue_oss_tpu.api.types import (
        ClusterQueue,
        Cohort,
        FlavorQuotas,
        LocalQueue,
        PreemptionPolicy,
        PreemptionPolicyValue,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
    )

    if "default" not in store.resource_flavors:
        store.upsert_resource_flavor(ResourceFlavor(name="default"))
    if "pool" not in store.cohorts:
        store.upsert_cohort(Cohort(name="pool"))
    for cq_name in ("cq-a", "cq-b"):
        if cq_name in store.cluster_queues:
            continue
        store.upsert_cluster_queue(ClusterQueue(
            name=cq_name, cohort="pool",
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(name="default", resources=[
                    ResourceQuota(name="cpu", nominal=8000)])])],
            preemption=PreemptionPolicy(
                within_cluster_queue=(
                    PreemptionPolicyValue.LOWER_PRIORITY)),
        ))
    for lq_name, cq_name in (("lq-a", "cq-a"), ("lq-b", "cq-b")):
        if f"default/{lq_name}" not in store.local_queues:
            store.upsert_local_queue(
                LocalQueue(name=lq_name, cluster_queue=cq_name))


#: (name, uid, local queue, cpu millicores, priority, arrival phase)
BATCH_A = [(f"a{i}", 10 + i, "lq-a", 2000, 0, T1) for i in range(4)] + \
          [(f"b{i}", 20 + i, "lq-b", 2000, 0, T1) for i in range(4)]
BATCH_B = [("high0", 30, "lq-a", 4000, 100, T2),
           ("high1", 31, "lq-a", 4000, 100, T2),
           ("b4", 32, "lq-b", 2000, 0, T2),
           ("b5", 33, "lq-b", 2000, 0, T2)]


def ensure_batch(store, batch) -> int:
    added = 0
    for name, uid, lq, cpu_m, prio, created in batch:
        if f"default/{name}" not in store.workloads:
            store.add_workload(_mk_wl(name, uid, lq, cpu_m, prio,
                                      created))
            added += 1
    return added


def settle(sched, engine, now: float) -> None:
    if engine is not None:
        from kueue_oss_tpu.solver.resilience import SolverUnavailable
        from kueue_oss_tpu.solver.tensors import UnsupportedProblem

        try:
            engine.drain(now=now)
        except (SolverUnavailable, UnsupportedProblem):
            pass  # host cycles mop up below, same as production
    sched.run_until_quiet(now=now, max_cycles=300, tick=0.0)


#: jobs that complete at T3 — a FIXED list, because job completion is
#: an external event (the job controller's), not a function of store
#: state: deriving the set from the live state would make a recovery
#: rerun pick differently once durable progress moved past T3
FINISH_AT_T3 = ["default/b0", "default/b1", "default/b2"]


def finish_jobs(store, sched, keys, now: float) -> list[str]:
    done = []
    for key in keys:
        wl = store.workloads.get(key)
        if wl is not None and not wl.is_finished:
            sched.finish_workload(key, now=now)
            done.append(key)
    return done


def play(store, sched, engine, manager) -> None:
    """The scenario script — every step idempotent, timestamps fixed."""
    ensure_objects(store)
    ensure_batch(store, BATCH_A)
    settle(sched, engine, T1)
    manager.checkpoint()  # mid-scenario checkpoint: recovery spans both
    ensure_batch(store, BATCH_B)
    settle(sched, engine, T2)
    finish_jobs(store, sched, FINISH_AT_T3, T3)
    settle(sched, engine, T3)
    settle(sched, engine, T4)
    manager.flush()


def _build_control_plane(store, solver: bool):
    from kueue_oss_tpu.core.queue_manager import QueueManager
    from kueue_oss_tpu.scheduler.scheduler import Scheduler

    queues = QueueManager(store)
    sched = Scheduler(store, queues)
    engine = None
    if solver:
        from kueue_oss_tpu.solver.engine import SolverEngine

        engine = SolverEngine(store, queues, scheduler=sched)
    return sched, engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True)
    ap.add_argument("--phase", choices=("run", "recover"),
                    required=True)
    ap.add_argument("--solver", action="store_true",
                    help="route admission floods through SolverEngine "
                         "drains (sessions on)")
    args = ap.parse_args(argv)

    from kueue_oss_tpu.core.store import Store
    from kueue_oss_tpu.persist import (
        InvariantAuditor,
        PersistenceManager,
        canonical_dump,
    )

    status: dict = {"phase": args.phase, "solver": args.solver}
    if args.phase == "run":
        hooks.arm_from_env()
        manager = PersistenceManager(args.dir, fsync="always",
                                     checkpoint_interval_seconds=0.0)
        store = Store()
        manager.attach(store)
        sched, engine = _build_control_plane(store, args.solver)
    else:
        manager = PersistenceManager(args.dir, fsync="always",
                                     checkpoint_interval_seconds=0.0)
        rr = manager.recover()
        store = rr.store
        manager.attach(store)
        sched, engine = _build_control_plane(store, args.solver)
        status.update(rr.to_dict())
        if engine is not None:
            # resident device/sidecar session state is gone by design;
            # the first post-restart drain must open with a full SYNC
            engine.reset_sessions(reason="restart")

    play(store, sched, engine, manager)

    if engine is not None:
        sess = engine._delta_sessions.get("lean") or \
            engine._delta_sessions.get("full")
        status["session_full_syncs"] = (
            sess.full_syncs if sess is not None else 0)
        status["session_first_frame_sync"] = (
            sess is not None and sess.full_syncs >= 1)
    violations = InvariantAuditor(store).audit()
    status["audit_violations"] = [v.to_dict() for v in violations]

    dump = canonical_dump(store)
    out = os.path.join(args.dir, "final.dump")
    with open(out, "wb") as f:
        f.write(dump)
    status["dump"] = out
    status["dump_bytes"] = len(dump)
    status["completed"] = True
    print(json.dumps(status), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
