"""WAL log shipping to a warm standby (docs/DURABILITY.md).

The durable control plane's failover cost used to be a full recovery:
newest checkpoint + replay of the whole active segment. Log shipping
shrinks it to the **unsynced tail**: the primary continuously copies
its durable WAL prefix (and every published checkpoint) into a standby
directory, and a follower replays shipped records into a live store as
they arrive — promotion only applies whatever landed since the last
catch-up tick.

Three shipping streams, all modeled as directory-to-directory byte
copies (a production deployment points the target at replicated
storage or wraps ``LogShipper`` over a network transport; the
correctness story — what is shipped when, and what the follower does
with it — is identical):

- **tail**: after every group commit, the active segment's synced
  suffix ``[shipped, synced_size)`` is appended to the standby's copy.
  Only durable bytes ship, so the standby can never be *ahead* of what
  the primary would itself recover.
- **sealed**: on rotation, the outgoing segment finishes shipping and
  is marked complete. A sealed segment that never tail-shipped (the
  shipper attached mid-life, or a bootstrap over an existing dir) is
  **compacted** first: per-key last-state-wins drops superseded event
  records and satisfied intents (``compact_records``) — the follower's
  replay applies last-state-wins anyway, so the recovered store is
  byte-identical while the shipped bytes shrink with churn. Segments
  with a partial standby copy ship their remaining tail verbatim
  (appending to a compacted prefix would corrupt frame offsets).
- **checkpoint**: every published checkpoint (full or incremental)
  copies over, so a cold standby can bootstrap without segment zero.

``WarmStandby`` is the follower: a live Store fed by ``catch_up()``
(resumable per-segment frame cursors via ``wal.scan_records``), with
``promote()`` = one final catch-up. The SIGKILL failover test proves
the promoted store is byte-identical to what the dead primary's own
recovery would produce, and that promotion replayed only the tail.
"""

from __future__ import annotations

import os
import re
from typing import Optional

from kueue_oss_tpu import metrics
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.persist import checkpoint as ckpt
from kueue_oss_tpu.persist import codec
from kueue_oss_tpu.persist import wal as wal_mod
from kueue_oss_tpu.util.fsutil import fsync_dir

_SEG = re.compile(r"^wal-(\d+)\.log$")
_CKPT = re.compile(r"^checkpoint-(\d+)\.ckpt$")


def compact_records(records: list[dict]) -> tuple[list[dict], int]:
    """Per-key log compaction: keep only the LAST event per
    (kind, key) — replay is last-state-wins, so the surviving suffix
    recovers the identical store — and drop intents whose fence was
    satisfied inside the segment (a following event at rv+1, or a
    delete). Unmatched trailing intents survive so the follower's
    recovery diagnostics (unapplied_intents) still see them.
    Survivors keep their relative order. Returns (kept, dropped)."""
    last_event: dict[tuple[str, str], int] = {}
    for i, rec in enumerate(records):
        if rec.get("t") == "event":
            key = _event_key(rec)
            if key is not None:
                last_event[key] = i
    satisfied: set[int] = set()
    pending: dict[str, list[tuple[int, int]]] = {}
    for i, rec in enumerate(records):
        if rec.get("t") == "intent":
            pending.setdefault(rec.get("wl", ""), []).append(
                (i, int(rec.get("rv", -1))))
        elif rec.get("t") == "event" and rec.get("kind") == "Workload":
            obj = rec.get("obj") or {}
            wl_key = (obj.get("namespace", "") + "/"
                      + obj.get("name", ""))
            fences = pending.get(wl_key)
            if fences:
                idx, rv = fences[0]
                orv = int(obj.get("resource_version", 0))
                if rec.get("verb") == "delete" or orv >= rv + 1:
                    satisfied.add(idx)
                    fences.pop(0)
    kept: list[dict] = []
    for i, rec in enumerate(records):
        t = rec.get("t")
        if t == "event":
            key = _event_key(rec)
            if key is not None and last_event.get(key) != i:
                continue
        elif t == "intent" and i in satisfied:
            continue
        kept.append(rec)
    return kept, len(records) - len(kept)


def _event_key(rec: dict) -> Optional[tuple[str, str]]:
    kind = rec.get("kind", "")
    if kind not in codec.KINDS:
        return None
    obj = rec.get("obj") or {}
    if kind in ("Workload", "LocalQueue"):
        key = obj.get("namespace", "") + "/" + obj.get("name", "")
    else:
        key = obj.get("name", "")
    return kind, key


class LogShipper:
    """Primary-side shipping into a standby directory."""

    def __init__(self, target_dir: str, compact: bool = True) -> None:
        self.target = target_dir
        self.compact = compact
        os.makedirs(target_dir, exist_ok=True)
        #: seg id -> bytes shipped so far (tail cursor). A restarted
        #: shipper resumes from the TARGET file's size — tail copies
        #: are verbatim prefixes, so the existing bytes are the cursor
        self._shipped: dict[int, int] = {}
        #: segments fully shipped + sealed (in-memory fast path; the
        #: durable record is the target-side .sealed marker, so a
        #: restarted shipper never re-ships — or worse, appends
        #: verbatim source bytes after a shorter compacted copy)
        self._sealed: set[int] = set()
        self.shipped_bytes = 0
        self.compaction_dropped = 0

    def _target_seg(self, seg_id: int) -> str:
        return os.path.join(self.target, f"wal-{seg_id:08d}.log")

    def _seal_marker(self, seg_id: int) -> str:
        return self._target_seg(seg_id) + ".sealed"

    def _is_sealed(self, seg_id: int) -> bool:
        if seg_id in self._sealed:
            return True
        if os.path.exists(self._seal_marker(seg_id)):
            self._sealed.add(seg_id)
            return True
        return False

    def _done(self, seg_id: int) -> int:
        """Bytes already on the target (verbatim-prefix invariant)."""
        done = self._shipped.get(seg_id)
        if done is None:
            try:
                done = os.path.getsize(self._target_seg(seg_id))
            except OSError:
                done = 0
            self._shipped[seg_id] = done
        return done

    def ship_tail(self, seg_id: int, path: str, synced_len: int) -> int:
        """Append the segment's durable suffix to the standby copy;
        returns bytes shipped this call."""
        if self._is_sealed(seg_id):
            return 0
        done = self._done(seg_id)
        if synced_len <= done:
            return 0
        with open(path, "rb") as src:
            src.seek(done)
            payload = src.read(synced_len - done)
        tgt = self._target_seg(seg_id)
        with open(tgt, "ab") as dst:
            dst.write(payload)
            dst.flush()
            os.fsync(dst.fileno())
        self._shipped[seg_id] = done + len(payload)
        self.shipped_bytes += len(payload)
        metrics.wal_shipped_bytes_total.inc("tail", by=len(payload))
        return len(payload)

    def ship_sealed(self, seg_id: int, path: str) -> None:
        """Finish a rotated segment. Copies with ANY existing target
        bytes (tail-shipped this life or a previous one — verbatim
        prefixes by invariant) get their remaining durable bytes
        appended verbatim; only untouched segments ship compacted
        (per-key last-state-wins). A .sealed marker on the target
        makes completion durable across shipper restarts — appending
        verbatim source bytes after a shorter compacted copy would
        corrupt the follower's frame stream."""
        if self._is_sealed(seg_id):
            return
        try:
            size = wal_mod.valid_prefix_len(path)
        except OSError:
            return
        done = self._done(seg_id)
        if done > 0 and not self._target_is_prefix(seg_id, path, done):
            # the target is a COMPLETE compacted copy whose .sealed
            # marker was lost to a crash between the atomic publish
            # and the marker write: compaction lands via os.replace,
            # so a non-prefix target can only be the whole compacted
            # stream — appending verbatim source bytes after it would
            # corrupt the follower's frames. Just restore the marker.
            self._sealed.add(seg_id)
            with open(self._seal_marker(seg_id), "wb"):
                pass
            fsync_dir(self.target)
            return
        if done > 0 or not self.compact:
            self.ship_tail(seg_id, path, size)
        else:
            records, _torn = wal_mod.replay_wal(path)
            kept, dropped = compact_records(records)
            payload = b"".join(wal_mod.encode_frame(r) for r in kept)
            tgt = self._target_seg(seg_id)
            tmp = f"{tgt}.tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as dst:
                    dst.write(payload)
                    dst.flush()
                    os.fsync(dst.fileno())
                os.replace(tmp, tgt)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            self._shipped[seg_id] = size
            self.shipped_bytes += len(payload)
            self.compaction_dropped += dropped
            metrics.wal_shipped_bytes_total.inc(
                "sealed", by=len(payload))
            metrics.wal_compaction_dropped_total.inc(by=dropped)
        self._sealed.add(seg_id)
        with open(self._seal_marker(seg_id), "wb"):
            pass
        fsync_dir(self.target)

    def _target_is_prefix(self, seg_id: int, path: str,
                          done: int) -> bool:
        """Whether the target's bytes are a verbatim prefix of the
        source (the tail-shipping invariant). Runs only on the
        rotation-rare sealed path."""
        try:
            with open(self._target_seg(seg_id), "rb") as t, \
                    open(path, "rb") as s:
                while done > 0:
                    chunk = t.read(min(done, 1 << 20))
                    if not chunk or s.read(len(chunk)) != chunk:
                        return False
                    done -= len(chunk)
            return True
        except OSError:
            return False

    def ship_checkpoint(self, path: str) -> None:
        """Copy one published checkpoint file (atomic on the target:
        temp + replace, the checkpoint writer's own discipline)."""
        name = os.path.basename(path)
        tgt = os.path.join(self.target, name)
        tmp = f"{tgt}.tmp.{os.getpid()}"
        try:
            with open(path, "rb") as src, open(tmp, "wb") as dst:
                data = src.read()
                dst.write(data)
                dst.flush()
                os.fsync(dst.fileno())
            os.replace(tmp, tgt)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        fsync_dir(self.target)
        self.shipped_bytes += len(data)
        metrics.wal_shipped_bytes_total.inc("checkpoint", by=len(data))


class WarmStandby:
    """Follower: continuous replay of a shipped directory into a live
    store, so failover applies only the not-yet-replayed tail.

    ``catch_up()`` is idempotent and cheap when nothing new arrived
    (one listdir + per-segment cursor checks); call it on any cadence.
    ``promote()`` is the failover: one final catch-up, then the store
    is the recovered state — byte-identical to what the dead
    primary's own ``PersistenceManager.recover()`` would produce from
    its durable prefix.
    """

    def __init__(self, dir_path: str) -> None:
        self.dir = dir_path
        self.store = Store()
        self._bootstrapped = False
        self._start_segment = 0
        #: seg id -> applied byte offset (frame-boundary cursor)
        self._cursor: dict[int, int] = {}
        self.records_applied = 0
        self.last_catch_up_records = 0
        #: standby-side GC of superseded shipped files (segments the
        #: bootstrap anchor retired, checkpoints outside the newest
        #: chain); on by default — the standby directory otherwise
        #: grows without bound while the primary's own retention only
        #: prunes the SOURCE directory
        self.prune = True
        self.rebootstraps = 0
        self.pruned_files = 0

    def _bootstrap(self) -> None:
        """Load the newest shipped checkpoint chain (if any) once;
        segments older than it never replay. A standby attached to a
        mid-life primary must wait for its first shipped checkpoint —
        replaying a history that starts past segment zero would build
        a partial store, so bootstrap retries until either a
        checkpoint or segment zero is visible."""
        if self._bootstrapped:
            return
        chain = ckpt.newest_valid_chain(self.dir)
        if chain is not None:
            from kueue_oss_tpu.persist.manager import (
                materialize_chain,
            )

            self.store = materialize_chain(chain)
            self._start_segment = int(chain[-1][0]["id"])
        elif not os.path.exists(
                os.path.join(self.dir, "wal-00000000.log")):
            return  # mid-life attach: wait for the first checkpoint
        self._bootstrapped = True

    def _replay_position(self) -> int:
        """The segment catch-up would touch next: every present
        segment before it is fully consumed. An absent or unreadable
        segment IS the position — replay cannot get past it (in-order
        stop), which is exactly what a superseding checkpoint heals."""
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return self._start_segment
        segs = sorted(int(m.group(1)) for m in
                      (_SEG.match(n) for n in names) if m)
        pos = self._start_segment
        for seg in segs:
            if seg < pos:
                continue
            if seg > pos:
                break  # shipped history has a hole: stuck before it
            try:
                size = os.path.getsize(
                    os.path.join(self.dir, f"wal-{seg:08d}.log"))
            except OSError:
                break
            if self._cursor.get(seg, 0) < size:
                break  # unconsumed bytes: replay works here next
            pos = seg + 1
        return pos

    def _maybe_rebootstrap(self) -> None:
        """Auto-re-bootstrap: a newer shipped checkpoint whose anchor
        segment is MORE THAN ONE segment ahead of the replay frontier
        supersedes it — the chain already embodies every record the
        standby would have replayed to get there, including segments
        that never shipped or sit behind an unreadable one (catch_up's
        in-order stop). Re-materializing from the chain is one bounded
        rebuild instead of a long — or permanently wedged — segment
        replay. Steady-state tailing never re-bootstraps: each
        rotation's checkpoint anchors exactly one segment past the
        frontier, and that boundary keeps the cheap replay path."""
        chain = ckpt.newest_valid_chain(self.dir)
        if chain is None:
            return
        new_start = int(chain[-1][0]["id"])
        if new_start <= self._replay_position() + 1:
            return
        from kueue_oss_tpu.persist.manager import materialize_chain

        self.store = materialize_chain(chain)
        self._start_segment = new_start
        self._cursor = {s: off for s, off in self._cursor.items()
                        if s >= new_start}
        self.rebootstraps += 1
        metrics.wal_standby_rebootstraps_total.inc()

    def _prune_superseded(self) -> None:
        """Standby-side GC: shipped segments older than the bootstrap
        anchor never replay again, and checkpoint files outside the
        newest valid chain can never be materialized (the chain's base
        is a full dump). Deleting them bounds the standby directory to
        the live chain + replayable segments; nothing catch_up() could
        still read is ever removed. ``.sealed`` markers of pruned
        segments are KEPT — they are zero-byte, and a restarted
        shipper sharing the directory uses them to know a sealed
        segment completed (deleting one would trigger a pointless
        re-ship of a segment this standby already retired)."""
        if not self.prune or not self._bootstrapped:
            return
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return
        chain = ckpt.newest_valid_chain(self.dir)
        keep_ckpts = (None if chain is None
                      else {int(m["id"]) for m, _ in chain})
        removed = 0
        for n in names:
            m = _SEG.match(n)
            if m is not None:
                if int(m.group(1)) < self._start_segment:
                    removed += self._rm(n)
                continue
            c = _CKPT.match(n)
            if (c is not None and keep_ckpts is not None
                    and int(c.group(1)) not in keep_ckpts):
                removed += self._rm(n)
        if removed:
            self.pruned_files += removed
            metrics.wal_standby_pruned_total.inc(by=removed)
            fsync_dir(self.dir)

    def _rm(self, name: str) -> int:
        try:
            os.unlink(os.path.join(self.dir, name))
            return 1
        except OSError:
            return 0

    def catch_up(self) -> int:
        """Apply every newly shipped complete frame; returns records
        applied this call. Before bootstrap succeeds (mid-life attach
        still waiting for its first shipped checkpoint) nothing
        replays — advancing segment cursors against an empty store
        would permanently skip those frames once the checkpoint
        arrives. Each call also re-bootstraps from a superseding
        shipped checkpoint (``_maybe_rebootstrap``) and prunes files
        the bootstrap anchor retired (``_prune_superseded``)."""
        self._bootstrap()
        if not self._bootstrapped:
            return 0
        self._maybe_rebootstrap()
        applied = 0
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return 0
        seg_ids = sorted(int(m.group(1)) for m in
                         (_SEG.match(n) for n in names) if m)
        from kueue_oss_tpu.persist.manager import apply_event

        for seg in seg_ids:
            if seg < self._start_segment:
                continue
            path = os.path.join(self.dir, f"wal-{seg:08d}.log")
            start = self._cursor.get(seg, 0)
            try:
                frames = wal_mod.scan_records(path, start)
                for off, length, rec in frames:
                    if rec.get("t") == "event":
                        apply_event(self.store, rec["verb"],
                                    rec["kind"], rec["obj"])
                    self._cursor[seg] = off + length
                    applied += 1
            except OSError:
                # STOP at the first unreadable segment: replaying a
                # later segment now and this one on a retry would
                # apply older records after newer ones (last-state-
                # wins converges per key, but cross-key order — and
                # hence the promoted dump — would diverge)
                break
        self.records_applied += applied
        self.last_catch_up_records = applied
        self._prune_superseded()
        return applied

    def promote(self) -> tuple[Store, int]:
        """Failover: final catch-up (the unsynced tail), then the
        store is live. Returns (store, tail records replayed)."""
        tail = self.catch_up()
        codec.advance_uid_floor(max(
            (wl.uid for wl in self.store.workloads.values()),
            default=0))
        codec.rebuild_indexes(self.store)
        return self.store, tail
