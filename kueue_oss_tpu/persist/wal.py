"""CRC-framed write-ahead log (docs/DURABILITY.md).

Frame layout, all integers big-endian:

    magic  2B  b"KW"
    ver    1B  0x01
    len    4B  payload byte length
    crc    4B  zlib.crc32 of the payload
    payload    canonical JSON record

Records are appended by the PersistenceManager: ``event`` records carry
the full post-mutation object from ``Store._emit`` (state replay);
``intent`` records fence scheduler decisions (admit/evict/preempt)
BEFORE the store mutation they announce, carrying the workload's
pre-mutation resource_version — the same optimistic-concurrency token
``Store.update_workload_if`` preconditions on, so recovery can tell an
applied decision (a following event at rv+1) from one the crash ate.

Durability policy (`fsync`):

  always -- fsync after every append (the crash harness's setting:
            every acknowledged record survives SIGKILL)
  batch  -- group commit: fsync every `batch_records` appends and on
            explicit sync() — the scheduler flushes at cycle end, so at
            most one cycle's tail is exposed to a crash (default; the
            <5% wal_overhead_pct budget lives here)
  off    -- never fsync (bench twins, throwaway dirs)

These three modes are also the PERSISTENCE degradation ladder
(docs/ROBUSTNESS.md): an ``os.fsync`` failure (sick disk, full
filesystem, injected chaos via ``fsync_fault``) drops the effective
policy one rung and raises ``fsync_degraded`` — a second failure drops
to off and raises the ``wal_off`` alarm. The configured policy is
remembered; once the restore cooldown elapses a single probe fsync
(the controller's unified half-open discipline) restores it.

Intents follow the same policy: they are appended to the same file
strictly before the event they fence, so ORDER (not an extra fsync)
is what guarantees recovery never sees an event without its intent.

Replay tolerates a torn tail: a short header, short payload, bad magic
or CRC mismatch ends the scan at the last complete record (exactly the
state an interrupted append leaves behind).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Iterator, Optional

from kueue_oss_tpu import metrics, resilience
from kueue_oss_tpu.persist import hooks

MAGIC = b"KW"
VERSION = 1
_HEADER = struct.Struct(">2sBII")

FSYNC_ALWAYS = "always"
FSYNC_BATCH = "batch"
FSYNC_OFF = "off"
FSYNC_MODES = (FSYNC_ALWAYS, FSYNC_BATCH, FSYNC_OFF)


def encode_frame(record: dict) -> bytes:
    # ONE canonical encoding across every durability surface: WAL
    # payloads and checkpoint dumps must stay byte-aligned for the
    # same object (persist/codec.py owns the settings)
    from kueue_oss_tpu.persist.codec import canonical_json

    payload = canonical_json(record)
    return _HEADER.pack(MAGIC, VERSION, len(payload),
                        zlib.crc32(payload)) + payload


class WriteAheadLog:
    """One append-only segment file."""

    def __init__(self, path: str, fsync: str = FSYNC_BATCH,
                 batch_records: int = 64) -> None:
        if fsync not in FSYNC_MODES:
            raise ValueError(f"fsync {fsync!r} not in {FSYNC_MODES}")
        self.path = path
        self.fsync = fsync
        #: the operator-configured policy `maybe_restore` returns to
        #: after the degradation ladder dropped `self.fsync` below it
        self._configured_fsync = fsync
        #: chaos seam: the next N fsync attempts fail as if the disk
        #: were sick (drives the persistence ladder deterministically)
        self.fsync_fault = 0
        #: quiet period before a degraded policy gets one probe fsync
        self.restore_cooldown_s = resilience.wal_restore_cooldown_s
        self.batch_records = max(1, int(batch_records))
        # A crash can leave a torn frame at the tail; appending after
        # it would strand every later record behind an unreadable
        # frame, so re-opening a segment first truncates it back to
        # its last complete frame.
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        self.truncated_bytes = 0
        if size:
            valid = valid_prefix_len(path)
            if valid < size:
                self.truncated_bytes = size - valid
                with open(path, "r+b") as f:
                    f.truncate(valid)
                    f.flush()
                    os.fsync(f.fileno())
        # buffering=0: a record handed to the OS survives a SIGKILL of
        # THIS process even before fsync; only power loss can eat it.
        self._f = open(path, "ab", buffering=0)
        self._unsynced = 0
        self.records_appended = 0
        self.bytes_appended = 0
        #: absolute file size / synced watermark — the log shipper's
        #: read bounds (tail shipping copies [shipped, synced_size))
        self.size = size - self.truncated_bytes
        self.synced_size = self.size

    def append(self, record: dict, kind: str = "event",
               sync: Optional[bool] = None) -> int:
        """Append one record; returns bytes written. `sync=True` forces
        the record durable before returning (intents)."""
        frame = encode_frame(record)
        if hooks.should_fire("pre_fsync"):
            # the record never reaches disk: durable state = everything
            # before it (deterministic stand-in for a lost page cache).
            # Close before killing: under mode="raise" a survivor must
            # not keep appending a CRC-valid log with a silently
            # dropped record in the middle (same discipline as
            # torn_tail below).
            self._fsync()
            self._f.close()
            hooks.kill()
        if hooks.should_fire("torn_tail"):
            # half a frame lands durably, then the power cut. Close the
            # handle first: under mode="raise" (in-process tests) a
            # survivor must not keep appending past a durable torn
            # frame — replay would stop there and silently lose every
            # later record; a closed file fails the next append loudly.
            self._f.write(frame[:max(1, len(frame) // 2)])
            if self.fsync != FSYNC_OFF:
                os.fsync(self._f.fileno())
            self._f.close()
            hooks.kill()
        self._f.write(frame)
        self._unsynced += 1
        self.records_appended += 1
        self.bytes_appended += len(frame)
        self.size += len(frame)
        metrics.wal_records_total.inc(kind)
        metrics.wal_bytes_total.inc(by=len(frame))
        force = sync if sync is not None else (self.fsync == FSYNC_ALWAYS)
        if force or (self.fsync == FSYNC_BATCH
                     and self._unsynced >= self.batch_records):
            self._fsync()
        return len(frame)

    def sync(self) -> None:
        """Group-commit barrier: make every appended record durable.
        Doubles as the degraded-policy restore point — the scheduler
        calls this at every cycle end, so a healed disk is re-probed
        on the admission cadence without a dedicated timer."""
        if self._unsynced:
            self._fsync()
        if self.fsync != self._configured_fsync:
            self.maybe_restore()

    def _fsync(self) -> None:
        if self.fsync == FSYNC_OFF:
            self._unsynced = 0
            # the shipping watermark still advances: fsync=off means
            # "trust the page cache", not "never replicate"
            self.synced_size = self.size
            return
        if self.fsync_fault > 0:
            self.fsync_fault -= 1
            self._degrade(OSError("injected fsync fault (chaos)"))
            return
        try:
            os.fsync(self._f.fileno())
        except OSError as e:
            self._degrade(e)
            return
        self._unsynced = 0
        self.synced_size = self.size
        metrics.wal_fsyncs_total.inc()

    def _degrade(self, err: BaseException) -> None:
        """An fsync failed: drop one durability rung rather than crash
        the admission loop — degraded-but-sound beats wedged. The
        ladder is fsync-always -> batch -> wal-off(+alarm); every hop
        is metered, journaled, and owned by the degradation
        controller, and ``maybe_restore`` walks back up after a quiet
        cooldown."""
        metrics.wal_fsync_faults_total.inc()
        ctl = resilience.controller
        if self.fsync == FSYNC_ALWAYS:
            self.fsync = FSYNC_BATCH
            ctl.report(
                resilience.PERSISTENCE, "fsync_degraded", True,
                reason=f"fsync failed ({err!r}); durability drops to "
                       "group commit")
        else:
            self.fsync = FSYNC_OFF
            ctl.report(
                resilience.PERSISTENCE, "fsync_degraded", True,
                reason=f"fsync failed ({err!r})")
            ctl.report(
                resilience.PERSISTENCE, "wal_off", True,
                reason=f"group commit failed too ({err!r}); WAL "
                       "durability OFF — page cache only (alarm)")
        # the failed barrier's records stay page-cache-only, exactly
        # like fsync=off: the watermark advances so shipping and the
        # group-commit counter don't wedge on an unreachable barrier
        self._unsynced = 0
        self.synced_size = self.size

    def maybe_restore(self) -> bool:
        """One timed half-open probe of a degraded durability policy:
        once ``restore_cooldown_s`` has passed since the last fault,
        a single caller attempts a real fsync; success restores the
        configured policy and clears the ladder conditions, failure
        restarts the cooldown."""
        if self.fsync == self._configured_fsync:
            return False
        ctl = resilience.controller
        cond = ("wal_off" if self.fsync == FSYNC_OFF
                else "fsync_degraded")
        if not ctl.begin_probe(resilience.PERSISTENCE, cond,
                               self.restore_cooldown_s):
            return False
        try:
            if self.fsync_fault > 0:
                self.fsync_fault -= 1
                raise OSError("injected fsync fault (chaos)")
            os.fsync(self._f.fileno())
        except OSError:
            metrics.wal_fsync_faults_total.inc()
            ctl.end_probe(resilience.PERSISTENCE, cond, success=False)
            return False
        self.fsync = self._configured_fsync
        self._unsynced = 0
        self.synced_size = self.size
        metrics.wal_fsyncs_total.inc()
        for c in ("wal_off", "fsync_degraded"):
            if ctl.active(resilience.PERSISTENCE, c):
                ctl.report(
                    resilience.PERSISTENCE, c, False,
                    reason="probe fsync succeeded; configured "
                           f"policy {self._configured_fsync!r} restored")
        return True

    def close(self) -> None:
        if self._f.closed:
            return
        self.sync()
        self._f.close()


def _scan(path: str, start: int = 0) -> Iterator[tuple[int, int, dict]]:
    """The ONE frame scanner: yield (offset, frame length, record) for
    each fully valid frame, stopping at the first invalid one. Every
    consumer (replay, truncation boundaries, reopen-truncation, the
    warm standby's incremental catch-up) shares these validity rules —
    a frame one path accepts and another rejects would let appends
    continue past a frame recovery stops at, permanently hiding later
    records. ``start`` must be a frame boundary (the standby resumes
    from its applied offset)."""
    with open(path, "rb") as f:
        f.seek(start)
        off = start
        while True:
            header = f.read(_HEADER.size)
            if len(header) < _HEADER.size:
                return
            try:
                magic, ver, length, crc = _HEADER.unpack(header)
            except struct.error:
                return
            if magic != MAGIC or ver != VERSION:
                return
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return
            try:
                rec = json.loads(payload)
            except ValueError:
                return
            yield off, _HEADER.size + length, rec
            off += _HEADER.size + length


def replay_wal(path: str) -> tuple[list[dict], bool]:
    """Read every complete record; returns (records, torn_tail).

    torn_tail is True when the file ends in an incomplete or corrupt
    frame — expected after a crash mid-append, and the reason WAL
    replay stops at the last complete record instead of raising.
    """
    records: list[dict] = []
    end = 0
    try:
        size = os.path.getsize(path)
    except OSError:
        return records, False
    for off, length, rec in _scan(path):
        records.append(rec)
        end = off + length
    return records, end < size


def valid_prefix_len(path: str) -> int:
    """Byte length of the longest complete-frame prefix."""
    return sum(n for _off, n in iter_frames(path))


def iter_frames(path: str) -> Iterator[tuple[int, int]]:
    """(offset, frame length) of each complete frame — the truncation
    property test cuts the file at every one of these boundaries."""
    for off, length, _rec in _scan(path):
        yield off, length


def scan_records(path: str, start: int = 0
                 ) -> Iterator[tuple[int, int, dict]]:
    """Public offset-resumable scan: (offset, frame length, record)
    from ``start`` (a frame boundary) — the warm standby's
    incremental replay cursor (persist/shipping.py)."""
    return _scan(path, start)
