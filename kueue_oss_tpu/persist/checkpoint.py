"""Atomic store checkpoints (docs/DURABILITY.md).

A checkpoint file is one header line of JSON metadata (id, payload
sha256, payload size) followed by the canonical store dump. The write
is crash-atomic: same-directory temp file, flush + fsync, ``os.replace``
onto the final name, then a **directory fsync** so the rename itself
survives power loss (the same fix applied to ``obs.dump_jsonl`` — an
fsynced file behind an un-fsynced rename is not durable).

Loading validates the sha256 over the payload; a torn or corrupt
checkpoint (a crash between temp-write and replace leaves only the
temp file, which is never considered) is skipped and the next-newest
one is used — recovery never trusts an unverified snapshot.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Optional

from kueue_oss_tpu.persist import hooks
from kueue_oss_tpu.util.fsutil import fsync_dir

__all__ = ["CorruptCheckpoint", "checkpoint_path", "fsync_dir",
           "list_checkpoints", "load_checkpoint", "newest_valid",
           "write_checkpoint"]

_NAME = re.compile(r"^checkpoint-(\d+)\.ckpt$")


class CorruptCheckpoint(ValueError):
    pass


def checkpoint_path(dir_path: str, ckpt_id: int) -> str:
    return os.path.join(dir_path, f"checkpoint-{ckpt_id:08d}.ckpt")


def write_checkpoint(dir_path: str, ckpt_id: int, state: bytes,
                     extra_meta: Optional[dict] = None) -> str:
    meta = {
        "version": 1,
        "id": int(ckpt_id),
        "sha256": hashlib.sha256(state).hexdigest(),
        "size": len(state),
    }
    if extra_meta:
        meta.update(extra_meta)
    path = checkpoint_path(dir_path, ckpt_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(json.dumps(meta, sort_keys=True,
                               separators=(",", ":")).encode())
            f.write(b"\n")
            f.write(state)
            f.flush()
            os.fsync(f.fileno())
        hooks.crash_if("mid_checkpoint")
        os.replace(tmp, path)
        fsync_dir(dir_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(path: str) -> tuple[dict, bytes]:
    """Returns (meta, state bytes); raises CorruptCheckpoint when the
    header is unparseable or the payload fails its hash."""
    with open(path, "rb") as f:
        header = f.readline()
        state = f.read()
    try:
        meta = json.loads(header)
    except ValueError as e:
        raise CorruptCheckpoint(f"{path}: bad header: {e}") from e
    if not isinstance(meta, dict) or "sha256" not in meta:
        raise CorruptCheckpoint(f"{path}: header is not checkpoint meta")
    if len(state) != meta.get("size"):
        raise CorruptCheckpoint(
            f"{path}: payload {len(state)}B != declared {meta.get('size')}B")
    if hashlib.sha256(state).hexdigest() != meta["sha256"]:
        raise CorruptCheckpoint(f"{path}: payload hash mismatch")
    return meta, state


def list_checkpoints(dir_path: str) -> list[tuple[int, str]]:
    """(id, path) of every checkpoint file, newest first."""
    out = []
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return out
    for name in names:
        m = _NAME.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, name)))
    out.sort(reverse=True)
    return out


def newest_valid(dir_path: str) -> Optional[tuple[dict, bytes]]:
    """The newest checkpoint that passes validation, or None."""
    for _ckpt_id, path in list_checkpoints(dir_path):
        try:
            return load_checkpoint(path)
        except (CorruptCheckpoint, OSError):
            continue
    return None
