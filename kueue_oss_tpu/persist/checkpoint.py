"""Atomic store checkpoints (docs/DURABILITY.md).

A checkpoint file is one header line of JSON metadata (id, payload
sha256, payload size) followed by the canonical store dump. The write
is crash-atomic: same-directory temp file, flush + fsync, ``os.replace``
onto the final name, then a **directory fsync** so the rename itself
survives power loss (the same fix applied to ``obs.dump_jsonl`` — an
fsynced file behind an un-fsynced rename is not durable).

Loading validates the sha256 over the payload; a torn or corrupt
checkpoint (a crash between temp-write and replace leaves only the
temp file, which is never considered) is skipped and the next-newest
one is used — recovery never trusts an unverified snapshot.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Optional

from kueue_oss_tpu.persist import hooks
from kueue_oss_tpu.util.fsutil import fsync_dir

__all__ = ["CorruptCheckpoint", "chain_ids", "checkpoint_path",
           "fsync_dir", "is_incremental", "list_checkpoints",
           "load_checkpoint", "load_checkpoint_meta", "newest_valid",
           "newest_valid_chain", "write_checkpoint"]

_NAME = re.compile(r"^checkpoint-(\d+)\.ckpt$")


class CorruptCheckpoint(ValueError):
    pass


def checkpoint_path(dir_path: str, ckpt_id: int) -> str:
    return os.path.join(dir_path, f"checkpoint-{ckpt_id:08d}.ckpt")


def write_checkpoint(dir_path: str, ckpt_id: int, state: bytes,
                     extra_meta: Optional[dict] = None) -> str:
    meta = {
        "version": 1,
        "id": int(ckpt_id),
        "sha256": hashlib.sha256(state).hexdigest(),
        "size": len(state),
    }
    if extra_meta:
        meta.update(extra_meta)
    path = checkpoint_path(dir_path, ckpt_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(json.dumps(meta, sort_keys=True,
                               separators=(",", ":")).encode())
            f.write(b"\n")
            f.write(state)
            f.flush()
            os.fsync(f.fileno())
        hooks.crash_if("mid_checkpoint")
        os.replace(tmp, path)
        fsync_dir(dir_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint_meta(path: str) -> dict:
    """Header-only read: the metadata line without the payload.

    The chain-closure prune path runs on EVERY checkpoint — at the
    sub-second cadences incremental checkpoints enable, re-reading and
    re-hashing each retained chain's multi-MB full base would dwarf
    the delta write the cadence just saved; link resolution only
    needs ``kind``/``base``. Payload integrity is still verified
    wherever the payload is actually used (load_checkpoint)."""
    with open(path, "rb") as f:
        header = f.readline()
    try:
        meta = json.loads(header)
    except ValueError as e:
        raise CorruptCheckpoint(f"{path}: bad header: {e}") from e
    if not isinstance(meta, dict) or "sha256" not in meta:
        raise CorruptCheckpoint(f"{path}: header is not checkpoint meta")
    return meta


def load_checkpoint(path: str) -> tuple[dict, bytes]:
    """Returns (meta, state bytes); raises CorruptCheckpoint when the
    header is unparseable or the payload fails its hash."""
    with open(path, "rb") as f:
        header = f.readline()
        state = f.read()
    try:
        meta = json.loads(header)
    except ValueError as e:
        raise CorruptCheckpoint(f"{path}: bad header: {e}") from e
    if not isinstance(meta, dict) or "sha256" not in meta:
        raise CorruptCheckpoint(f"{path}: header is not checkpoint meta")
    if len(state) != meta.get("size"):
        raise CorruptCheckpoint(
            f"{path}: payload {len(state)}B != declared {meta.get('size')}B")
    if hashlib.sha256(state).hexdigest() != meta["sha256"]:
        raise CorruptCheckpoint(f"{path}: payload hash mismatch")
    return meta, state


def list_checkpoints(dir_path: str) -> list[tuple[int, str]]:
    """(id, path) of every checkpoint file, newest first."""
    out = []
    try:
        names = os.listdir(dir_path)
    except FileNotFoundError:
        return out
    for name in names:
        m = _NAME.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(dir_path, name)))
    out.sort(reverse=True)
    return out


def newest_valid(dir_path: str) -> Optional[tuple[dict, bytes]]:
    """The newest checkpoint that passes validation, or None."""
    for _ckpt_id, path in list_checkpoints(dir_path):
        try:
            return load_checkpoint(path)
        except (CorruptCheckpoint, OSError):
            continue
    return None


def is_incremental(meta: dict) -> bool:
    return meta.get("kind") == "incremental"


def newest_valid_chain(dir_path: str
                       ) -> Optional[list[tuple[dict, bytes]]]:
    """The newest checkpoint whose whole delta chain validates,
    resolved full-base-first: ``[full, incr, ..., newest]``.

    Incremental checkpoints (docs/DURABILITY.md "Incremental
    checkpoints") carry ``meta["kind"] == "incremental"`` and a
    ``meta["base"]`` pointer at the checkpoint they delta against.
    A candidate with a corrupt or missing link anywhere in its chain
    is skipped entirely and the next-newest candidate is tried —
    recovery never materializes a partial chain.
    """
    by_id = dict(list_checkpoints(dir_path))
    for ckpt_id in sorted(by_id, reverse=True):
        chain: list[tuple[dict, bytes]] = []
        cur: Optional[int] = ckpt_id
        ok = True
        seen: set[int] = set()
        while cur is not None:
            path = by_id.get(cur)
            if path is None or cur in seen:
                ok = False
                break
            seen.add(cur)
            try:
                meta, state = load_checkpoint(path)
            except (CorruptCheckpoint, OSError):
                ok = False
                break
            chain.append((meta, state))
            cur = (int(meta["base"]) if is_incremental(meta)
                   else None)
        if ok and chain:
            chain.reverse()
            return chain
    return None


def chain_ids(dir_path: str, ckpt_id: int) -> set[int]:
    """Checkpoint ids in ``ckpt_id``'s delta chain (itself included),
    or just {ckpt_id} when the chain cannot be resolved — the prune
    path's retention closure (a full base outlives the window while
    a retained incremental still points at it)."""
    by_id = dict(list_checkpoints(dir_path))
    out: set[int] = set()
    cur: Optional[int] = ckpt_id
    while cur is not None and cur not in out and cur in by_id:
        out.add(cur)
        try:
            meta = load_checkpoint_meta(by_id[cur])
        except (CorruptCheckpoint, OSError):
            break
        cur = int(meta["base"]) if is_incremental(meta) else None
    return out or {ckpt_id}
