"""Durable control plane: write-ahead log, checkpoints, recovery, auditor.

The reference Kueue survives controller restarts because the apiserver
(etcd) is its durable store — the cache and queues rebuild from watches
on start. This reproduction keeps the whole control plane in process
memory, so until this subsystem a crash lost every admission decision
ever made. ``persist`` closes that gap (docs/DURABILITY.md):

- :mod:`codec` — canonical (byte-stable) serialization of every API
  object and of a whole :class:`~kueue_oss_tpu.core.store.Store`;
- :mod:`wal` — a CRC-framed, fsynced write-ahead log fed by
  ``Store._emit`` events plus scheduler decision intents;
- :mod:`checkpoint` — atomic periodic checkpoints (tmp file +
  ``os.replace`` + directory fsync) with WAL truncation on success;
- :mod:`manager` — :class:`PersistenceManager`, the wiring: store
  watch -> WAL, intent fencing, checkpoint cadence, and recovery
  (newest valid checkpoint + replay of the WAL suffix, tolerant of a
  torn tail);
- :mod:`auditor` — :class:`InvariantAuditor`, recomputing per-CQ usage
  and cohort subtree quota from admitted workloads via the
  ``core/quota.py`` formulas and diffing against store accounting;
- :mod:`hooks` — named crash points for the chaos harness
  (``kueue_oss_tpu/chaos`` ``CrashPointInjector`` +
  ``persist/crashtest.py`` subprocess driver);
- :mod:`shipping` — :class:`LogShipper` (continuous WAL tail +
  sealed-segment + checkpoint shipping with per-key compaction) and
  :class:`WarmStandby` (follower replay; failover = the unsynced
  tail).
"""

from kueue_oss_tpu.persist.auditor import InvariantAuditor, Violation
from kueue_oss_tpu.persist.checkpoint import fsync_dir
from kueue_oss_tpu.persist.codec import (
    canonical_dump,
    from_dict,
    store_from_dict,
    store_to_dict,
    to_dict,
)
from kueue_oss_tpu.persist.manager import (
    PersistenceManager,
    RecoveryResult,
    apply_event,
    materialize_chain,
)
from kueue_oss_tpu.persist.shipping import (
    LogShipper,
    WarmStandby,
    compact_records,
)
from kueue_oss_tpu.persist.wal import WriteAheadLog, replay_wal

__all__ = [
    "InvariantAuditor",
    "LogShipper",
    "PersistenceManager",
    "RecoveryResult",
    "Violation",
    "WarmStandby",
    "WriteAheadLog",
    "apply_event",
    "canonical_dump",
    "compact_records",
    "from_dict",
    "fsync_dir",
    "materialize_chain",
    "replay_wal",
    "store_from_dict",
    "store_to_dict",
    "to_dict",
]
