"""Named crash points for durability chaos (docs/DURABILITY.md).

The crash-point harness must be able to kill the control plane at the
exact instants where write-ahead logging is allowed to lose or keep a
record — the recovery contract is defined BY those instants. Each point
is a named call site inside the persistence write paths; arming one
(directly, via :class:`kueue_oss_tpu.chaos.CrashPointInjector`, or via
the ``KUEUE_CRASH_POINT`` env consumed by ``persist/crashtest.py``)
makes the ``after``-th hit of that site terminate the process with
SIGKILL — indistinguishable from a power cut, no atexit, no flush.

Points (see docs/ROBUSTNESS.md fault taxonomy):

  pre_fsync            -- a WAL record was handed to append() but dies
                          before it becomes durable (simulated
                          deterministically: the record is never
                          written, then SIGKILL)
  torn_tail            -- half of a WAL frame reaches disk durably,
                          then SIGKILL (torn write at the tail)
  post_fsync_pre_apply -- a decision intent is durable but the process
                          dies before the store mutation applies
  mid_checkpoint       -- SIGKILL after the checkpoint temp file is
                          written but before os.replace publishes it
  mid_drain            -- SIGKILL after the first N solver-plan
                          admissions committed to the store (a drain
                          interrupted halfway through its apply loop)
  sidecar_session_store -- SIGKILL inside the solver sidecar's LRU
                          session store, after a DELTA frame's dirty
                          rows were applied to the resident problem but
                          before the epoch advanced / the checksum was
                          verified (a torn session tail; RESYNC must
                          rebuild byte-identical state)

``mode="raise"`` swaps SIGKILL for a :class:`CrashPoint` exception so
in-process tests can exercise a point without a subprocess.

The fast path matters: ``crash_if`` is called from WAL appends and the
solver apply loop, so the disarmed check is one module-global read.
"""

from __future__ import annotations

import os
import signal
from typing import Optional

CRASH_POINTS = ("pre_fsync", "torn_tail", "post_fsync_pre_apply",
                "mid_checkpoint", "mid_drain", "sidecar_session_store")

KILL = "kill"
RAISE = "raise"


class CrashPoint(RuntimeError):
    """Raised instead of SIGKILL under mode="raise"."""


_armed: Optional[str] = None
_after: int = 0
_mode: str = KILL


def arm(point: str, after: int = 0, mode: str = KILL) -> None:
    """Arm `point`: its (after+1)-th hit fires."""
    global _armed, _after, _mode
    if point not in CRASH_POINTS:
        raise ValueError(f"unknown crash point {point!r}; "
                         f"one of {CRASH_POINTS}")
    if mode not in (KILL, RAISE):
        raise ValueError(f"unknown crash mode {mode!r}")
    _armed, _after, _mode = point, int(after), mode


def disarm() -> None:
    global _armed, _after
    _armed, _after = None, 0


def arm_from_env(environ=os.environ) -> Optional[str]:
    """Arm from KUEUE_CRASH_POINT / KUEUE_CRASH_AFTER / KUEUE_CRASH_MODE
    (the subprocess driver's interface). Returns the armed point."""
    point = environ.get("KUEUE_CRASH_POINT")
    if point:
        arm(point, after=int(environ.get("KUEUE_CRASH_AFTER", "0")),
            mode=environ.get("KUEUE_CRASH_MODE", KILL))
    return point


def should_fire(point: str) -> bool:
    """True when `point` is armed and its countdown just hit zero —
    consumes one countdown tick per armed hit. Call sites that need
    special pre-kill behavior (the WAL's torn write) branch on this
    and then call :func:`kill` themselves."""
    global _after, _armed
    if _armed != point:
        return False
    if _after > 0:
        _after -= 1
        return False
    _armed = None  # fire exactly once
    return True


def kill() -> None:
    """Terminate the way a power cut would (or raise under test mode)."""
    if _mode == RAISE:
        raise CrashPoint("injected crash")
    os.kill(os.getpid(), signal.SIGKILL)


def crash_if(point: str) -> None:
    """The standard call site: fire-and-kill when armed."""
    if _armed is not None and should_fire(point):
        kill()
