"""Deterministic fault-injection harness for the solver backend.

The resilience layer (solver/resilience.py, SolverClient deadlines and
retries, the engine's plan-sanity guard) claims the control plane
survives a crashing, hanging, or garbage-spewing solver sidecar without
stalling admissions. This module *proves* it: a seeded injector decides,
per request, which failure mode the sidecar exhibits, and a chaos
server wraps the real solve path with those faults. The same injector
drives the `chaos`-marked tests (tier-1: fully deterministic, injected
clocks, no sleeps in the fast subset) and bench.py's chaos scenario.

Failure modes (FAULTS):

  ok            -- serve the request normally
  crash_pre     -- close the connection before reading the request
  crash         -- read the request, then die without replying
                   (sidecar killed mid-request: client sees EOF)
  hang          -- hold the connection open and never reply (client's
                   per-call deadline is the only way out)
  truncate      -- declare a full frame but send only part of it
  oversize      -- declare a frame above the client's max-frame guard
  garble        -- well-framed response whose npz payload is noise
  corrupt_plan  -- a *decodable* plan with out-of-bounds indices and
                   admitted null/padding rows (exercises the engine's
                   plan-sanity guard, not the transport)
  slow          -- delay the (correct) response by ``slow_s``

Node flap (the non-sidecar failure in the model) is injected by
``NodeFlapInjector`` against the store's node objects. Accelerator
device loss and mesh shrink (the multi-chip failure modes) are
injected by ``MeshFaultInjector`` through the engine's
``solve_fault_hook`` seam, driving the mesh -> single-chip -> host
fallback chain deterministically.

Control-plane crash/restart (the durability failure modes,
docs/DURABILITY.md) is injected by ``CrashPointInjector`` through the
``persist.hooks`` crash points (pre_fsync, torn_tail,
post_fsync_pre_apply, mid_checkpoint, mid_drain); the subprocess
driver ``python -m kueue_oss_tpu.persist.crashtest`` pairs each kill
with a recovery run and asserts byte-identical convergence.
"""

from __future__ import annotations

import io
import json
import random
import socketserver
import struct
import time
from typing import Optional

import numpy as np

from kueue_oss_tpu.solver.service import (
    SolverServer,
    _recv,
    _send,
    deserialize_problem,
    respond,
)

OK = "ok"
CRASH_PRE = "crash_pre"
CRASH = "crash"
HANG = "hang"
TRUNCATE = "truncate"
OVERSIZE = "oversize"
GARBLE = "garble"
CORRUPT_PLAN = "corrupt_plan"
SLOW = "slow"

FAULTS = (OK, CRASH_PRE, CRASH, HANG, TRUNCATE, OVERSIZE, GARBLE,
          CORRUPT_PLAN, SLOW)

#: ceiling on how long a "hang" holds its connection open server-side;
#: the client's deadline fires long before this in any sane config —
#: it only bounds thread lifetime if a test dies mid-hang
_HANG_CAP_S = 30.0


class FaultInjector:
    """Seeded per-request fault decisions, usable two ways.

    - ``schedule``: an explicit fault sequence consumed in order
      (deterministic tests: "crash, then serve"). After the schedule is
      exhausted the injector falls through to the random mode.
    - ``weights``: {fault: weight} sampled with the seeded RNG (chaos
      sweeps in bench.py). With neither, every request is served.

    ``injected`` counts what was actually injected, for assertions and
    the bench JSON tail.
    """

    def __init__(self, schedule=(), seed: int = 0,
                 weights: Optional[dict] = None,
                 slow_s: float = 0.01) -> None:
        for f in list(schedule) + list(weights or {}):
            if f not in FAULTS:
                raise ValueError(f"unknown fault {f!r}; one of {FAULTS}")
        self.schedule = list(schedule)
        self._i = 0
        self._rng = random.Random(seed)
        self.weights = dict(weights or {})
        self.slow_s = slow_s
        self.injected: dict[str, int] = {}

    def next_fault(self) -> str:
        if self._i < len(self.schedule):
            fault = self.schedule[self._i]
            self._i += 1
        elif self.weights:
            fault = self._rng.choices(
                list(self.weights), weights=list(self.weights.values()))[0]
        else:
            fault = OK
        self.injected[fault] = self.injected.get(fault, 0) + 1
        return fault

    def faults_injected(self) -> int:
        """Requests that got anything other than normal service."""
        return sum(n for f, n in self.injected.items() if f != OK)


def _corrupt_plan_response(header: dict, blob: bytes,
                           server=None) -> tuple[dict, bytes]:
    """A decodable response whose plan violates every invariant the
    sanity guard checks: all rows (null + padding included) admitted,
    flavor options far out of range.

    Session frames are covered too: a SYNC/legacy request carries the
    problem inline; for a DELTA the workload-axis width comes from the
    server's resident session (no session -> an in-band resync, which
    is itself a valid fault for the client's fallback path)."""
    if header.get("kind") == "delta":
        sess = (server.get_session(str(header.get("sid", "")))
                if server is not None else None)
        if sess is None or sess.kwargs is None:
            return {"ok": False, "resync": "session_missing"}, b""
        W1 = sess.kwargs["wl_cqid"].shape[0]
        return _corrupt_plan_arrays(header, W1)
    problem = deserialize_problem(header["meta"], blob)
    W1 = problem.wl_cqid.shape[0]
    return _corrupt_plan_arrays(header, W1)


def _corrupt_plan_arrays(header: dict, W1: int) -> tuple[dict, bytes]:
    admitted = np.ones(W1, dtype=bool)
    parked = np.zeros(W1, dtype=bool)
    admit_round = np.zeros(W1, dtype=np.int32)
    rounds = np.int32(1)
    if header["full"]:
        g = max(1, int(header.get("g_max", 1)))
        opt = np.full((W1, g), 1 << 20, dtype=np.int32)
        names = ["admitted", "opt", "admit_round", "parked", "rounds",
                 "usage", "wl_usage", "victim_reason"]
        arrays = [admitted, opt, admit_round, parked, rounds,
                  np.zeros(1, np.int32), np.zeros(1, np.int32),
                  np.zeros(W1, np.int32)]
    else:
        opt = np.full((W1,), 1 << 20, dtype=np.int32)
        names = ["admitted", "opt", "admit_round", "parked", "rounds",
                 "usage"]
        arrays = [admitted, opt, admit_round, parked, rounds,
                  np.zeros(1, np.int32)]
    buf = io.BytesIO()
    np.savez(buf, **dict(zip(names, arrays)))
    return {"ok": True, "names": names}, buf.getvalue()


class _ChaosHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:  # noqa: C901 - one branch per fault
        injector: FaultInjector = self.server.injector
        fault = injector.next_fault()
        if fault == CRASH_PRE:
            return
        try:
            header, blob = _recv(self.request, self.server.max_frame_bytes)
        except ConnectionError:
            return
        if fault == CRASH:
            return
        if fault == HANG:
            try:
                # never reply; unblock (and release the thread) when the
                # client's deadline fires and it closes the socket
                self.request.settimeout(_HANG_CAP_S)
                self.request.recv(1)
            except OSError:
                pass
            return
        if fault == OVERSIZE:
            h = json.dumps({"ok": True, "names": ["admitted"]}).encode()
            try:
                self.request.sendall(
                    struct.pack(">II", len(h), 0xFFFF_FFF0))
                self.request.sendall(h)
            except OSError:
                pass
            return
        if fault == TRUNCATE:
            h = json.dumps({"ok": True, "names": ["admitted"]}).encode()
            try:
                # declare 128 payload bytes, deliver 64, close
                self.request.sendall(struct.pack(">II", len(h), 128))
                self.request.sendall(h)
                self.request.sendall(b"\x00" * 64)
            except OSError:
                pass
            return
        if fault == GARBLE:
            junk = bytes(injector._rng.getrandbits(8) for _ in range(96))
            try:
                _send(self.request,
                      {"ok": True, "names": ["admitted", "opt"]}, junk)
            except OSError:
                pass
            return
        if fault == CORRUPT_PLAN:
            try:
                resp_h, resp_b = _corrupt_plan_response(
                    header, blob, self.server)
                _send(self.request, resp_h, resp_b)
            except OSError:
                pass
            return
        if fault == SLOW:
            time.sleep(injector.slow_s)
        # healthy tail: the production respond path, shared verbatim
        # (session frames included: the chaos server inherits the
        # production session store)
        respond(self.request, header, blob, self.server)


class ChaosSolverServer(SolverServer):
    """A SolverServer whose handler consults a FaultInjector per request.

    Drop-in for the production sidecar in tests and bench runs:
    ``ChaosSolverServer(path, FaultInjector(schedule=["crash", "ok"]))``.
    """

    def __init__(self, socket_path: str, injector: FaultInjector,
                 max_frame_bytes: Optional[int] = None) -> None:
        super().__init__(socket_path, max_frame_bytes=max_frame_bytes)
        self.injector = injector
        self.RequestHandlerClass = _ChaosHandler


class MeshFaultInjector:
    """Deterministic device-loss / mesh-shrink injection for the
    engine's multi-chip drain arms (docs/ROBUSTNESS.md "Mesh faults").

    Wires itself into ``SolverEngine.solve_fault_hook`` — the hook runs
    immediately before each local solve, tagged with the arm about to
    execute, so raising there is indistinguishable from the XLA runtime
    erroring at dispatch time (the closest a virtual-device test rig
    gets to yanking a chip). The engine's contract under test:

      mesh fault   -> the SAME drain re-runs on the single-chip arm
                      (solver_fallback_total{reason="mesh_error"});
      both arms    -> SolverUnavailable, and the scheduler finishes the
                      admission round on host cycles
                      (reason="device_error") — the full
                      mesh -> single-chip -> host chain, never silent;
      mesh shrink  -> refresh_mesh(max_devices=n) re-detects a narrower
                      mesh; the next drain re-pads, the session rides
                      the forced full sync, plans stay bit-identical.
    """

    def __init__(self, engine) -> None:
        self.engine = engine
        self._mesh_faults = 0
        self._all_faults = 0
        self.injected: dict[str, int] = {}
        engine.solve_fault_hook = self._hook

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def _hook(self, arm: str) -> None:
        if self._all_faults > 0:
            if arm == "single":
                self._all_faults -= 1  # terminal arm = one drain
            self._count(f"{arm}_lost")
            raise RuntimeError(
                f"injected device loss ({arm} arm unavailable)")
        if arm == "mesh" and self._mesh_faults > 0:
            self._mesh_faults -= 1
            self._count("mesh_lost")
            raise RuntimeError("injected mesh device loss")

    def lose_mesh(self, times: int = 1) -> None:
        """The next ``times`` mesh-arm solves fail (ICI/device loss)."""
        self._mesh_faults += int(times)

    def lose_all(self, times: int = 1) -> None:
        """The next ``times`` drains fail on EVERY local arm — the
        whole accelerator is gone; only host cycles remain."""
        self._all_faults += int(times)

    def shrink(self, n_devices: int) -> int:
        """Shrink the engine's mesh to ``n_devices`` (a partial device
        loss); returns the re-detected width."""
        self._count(f"shrink_{n_devices}")
        return self.engine.refresh_mesh(max_devices=n_devices)

    def restore(self) -> int:
        """Heal: clear pending faults and re-detect the full mesh."""
        self._mesh_faults = 0
        self._all_faults = 0
        return self.engine.refresh_mesh()

    def faults_injected(self) -> int:
        return sum(self.injected.values())


class CrashPointInjector:
    """Kill -9 the control plane at a named durability point
    (docs/DURABILITY.md; docs/ROBUSTNESS.md fault taxonomy).

    Two usage modes:

    - **subprocess** (the restart fault): ``env()`` returns the
      environment that arms the point inside a child control plane —
      ``persist/crashtest.py`` consumes it, SIGKILLs itself at the
      point, and a second invocation with ``--phase recover`` proves
      recovery. This is the production-faithful mode: the process
      really dies, nothing flushes.
    - **in-process** (unit tests): ``arm(mode="raise")`` makes the
      point raise :class:`kueue_oss_tpu.persist.hooks.CrashPoint`
      instead of killing, so a test can assert on the half-written
      state directly.

    Points: pre_fsync, torn_tail, post_fsync_pre_apply,
    mid_checkpoint, mid_drain (``persist.hooks.CRASH_POINTS``).
    """

    def __init__(self, point: str, after: int = 0,
                 mode: str = "kill") -> None:
        from kueue_oss_tpu.persist import hooks

        if point not in hooks.CRASH_POINTS:
            raise ValueError(f"unknown crash point {point!r}; "
                             f"one of {hooks.CRASH_POINTS}")
        self.point = point
        self.after = int(after)
        self.mode = mode

    def arm(self) -> "CrashPointInjector":
        from kueue_oss_tpu.persist import hooks

        hooks.arm(self.point, after=self.after, mode=self.mode)
        return self

    def disarm(self) -> None:
        from kueue_oss_tpu.persist import hooks

        hooks.disarm()

    def __enter__(self) -> "CrashPointInjector":
        return self.arm()

    def __exit__(self, *exc) -> None:
        self.disarm()

    def env(self) -> dict:
        """Environment arming this point in a child process."""
        return {"KUEUE_CRASH_POINT": self.point,
                "KUEUE_CRASH_AFTER": str(self.after),
                "KUEUE_CRASH_MODE": self.mode}


def __getattr__(name: str):
    # campaign composition layer (chaos/campaign.py) — lazy so that
    # importing the injectors never pulls the scheduler stack
    if name in ("ChaosCampaign", "CampaignSpec", "CampaignResult",
                "PROFILES", "PROFILE_SUBSYSTEM", "run_campaign"):
        from kueue_oss_tpu.chaos import campaign

        return getattr(campaign, name)
    raise AttributeError(name)


class NodeFlapInjector:
    """Seeded node-readiness flapping against the store.

    ``flap_down`` marks nodes NotReady (specific names, or a seeded
    sample); ``flap_up`` restores them. Pairing the two inside/outside
    the failure controller's grace period drives the flap-recovery path
    (controllers/failure_recovery.py) deterministically.
    """

    def __init__(self, store, seed: int = 0) -> None:
        self.store = store
        self._rng = random.Random(seed)
        self._down: list[str] = []

    def flap_down(self, count: int = 1,
                  names: Optional[list[str]] = None) -> list[str]:
        if names is None:
            pool = sorted(n for n, node in self.store.nodes.items()
                          if node.ready)
            names = self._rng.sample(pool, min(count, len(pool)))
        for n in names:
            node = self.store.nodes[n]
            node.ready = False
            self.store.upsert_node(node)
        self._down.extend(names)
        return list(names)

    def flap_up(self, names: Optional[list[str]] = None) -> list[str]:
        if names is None:
            names, self._down = self._down, []
        else:
            self._down = [n for n in self._down if n not in names]
        for n in names:
            node = self.store.nodes.get(n)
            if node is not None:
                node.ready = True
                self.store.upsert_node(node)
        return list(names)


class ClusterLossInjector:
    """Federation member-loss faults (docs/FEDERATION.md, ROBUSTNESS.md).

    Drives the three ways a federated fleet loses a member, against a
    live ``MultiKueueController`` (and optionally the shared farm's
    ``SolverServer``):

    - **worker silent-drop**: the worker stops heartbeating
      (``active=False``, ``last_seen`` frozen) without any cleanup —
      the hub must re-dispatch its workloads only after
      ``worker_lost_timeout_s`` elapses (workload.go remote-lost);
    - **farm-tenant eviction**: the shared sidecar drops every
      resident session of one tenant (capacity reclaim / chaos); the
      tenant's next frame must heal through RESYNC with zero impact on
      its neighbors' sessions;
    - **hub-link flap**: a drop/restore pair inside the grace window,
      which must NOT trigger re-dispatch.

    Deterministic: no clocks read here — callers pass ``now`` exactly
    like the controller's reconcile loop, so the grace-window boundary
    is driven, not raced. ``injected`` counts by fault kind.
    """

    def __init__(self, controller, farm_server=None,
                 seed: int = 0) -> None:
        self.controller = controller
        self.farm_server = farm_server
        self._rng = random.Random(seed)
        self.injected: dict[str, int] = {}

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def _cluster(self, name: Optional[str]):
        clusters = self.controller.clusters
        if name is None:
            pool = sorted(n for n, c in clusters.items() if c.active)
            if not pool:
                raise ValueError("no active worker to drop")
            name = pool[self._rng.randrange(len(pool))]
        return clusters[name]

    def drop_worker(self, name: Optional[str] = None) -> str:
        """Silent worker loss: stops heartbeating, state intact."""
        cluster = self._cluster(name)
        cluster.active = False
        self._count("worker_drop")
        return cluster.name

    def restore_worker(self, name: str, now: float) -> str:
        """The worker reconnects; its next reconcile marks it seen."""
        cluster = self.controller.clusters[name]
        cluster.active = True
        cluster.mark_seen(now)
        self._count("worker_restore")
        return name

    def flap_worker(self, name: str, now: float) -> str:
        """Drop + immediate restore (a link flap INSIDE the grace
        window when the caller reconciles before the timeout)."""
        self.drop_worker(name)
        self._count("worker_flap")
        return self.restore_worker(name, now)

    def evict_farm_tenant(self, tenant: str) -> int:
        """Drop every resident farm session of one tenant; returns the
        eviction count (metrics count reason=tenant_evicted)."""
        if self.farm_server is None:
            raise ValueError("no farm server wired to this injector")
        self._count("tenant_evict")
        return self.farm_server.drop_tenant(tenant)

    def faults_injected(self) -> int:
        return sum(self.injected.values())
