"""Composed-fault chaos campaigns with a convergence oracle.

The injectors in :mod:`kueue_oss_tpu.chaos` each prove one failure mode
in isolation. Real incidents are not that polite: a pod loss lands
while the solver mesh is half-broken and the disk is sick. A
**campaign** composes several injectors into a seeded multi-fault storm
against a live control plane and then asks the question none of the
single-fault tests can: *after the storm passes, does the system
converge back to exactly the state a fault-free run would have
produced?*

The convergence oracle (docs/ROBUSTNESS.md "Chaos campaigns"):

1. **Byte identity** — a fault-free *twin* plane replays the same
   external trace (arrivals, node flaps) with no injected faults; after
   the storm the faulted plane's store must become bit-identical to the
   twin's (``persist.codec.canonical_dump``) within
   ``convergence_bound`` recovery cycles. This works because parked /
   skipped workloads get no store writes, every admission writes
   exactly once with fixed reason strings regardless of the arm that
   found it (host cycle, batched solve, streamed micro-drain), and the
   campaign drives a constant virtual ``now`` — so *when* and *how* a
   workload was admitted leaves no residue, only *that* it was.
2. **Zero invariant violations** — ``persist.auditor.InvariantAuditor``
   over the converged store.
3. **Monotone recovery** — once the storm ends, the max degradation
   level (:mod:`kueue_oss_tpu.resilience`) never rises again and ends
   at 0; every transition is on the controller's history for the
   bench tail / assertions.

Everything is deterministic: faults and flap schedules are drawn from
``random.Random(seed)`` at plan time, the controller's cooldown clock
is virtual (stepped ``clock_step_s`` per cycle, so half-open re-probes
heal on a driven schedule), and availability wall time is the only
real-clock read (reporting only, never control flow).

Each plane runs under its own :class:`resilience.DegradationController`
(via ``resilience.use``), so a campaign never leaks degraded state into
the process-wide controller, and twin/faulted ladders cannot alias.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import resilience
from kueue_oss_tpu.api.types import (
    ClusterQueue,
    FlavorQuotas,
    LocalQueue,
    Node,
    PodSet,
    ResourceFlavor,
    ResourceGroup,
    ResourceQuota,
    Workload,
)
from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.persist.auditor import InvariantAuditor
from kueue_oss_tpu.persist.codec import canonical_dump
from kueue_oss_tpu.scheduler.scheduler import Scheduler
from kueue_oss_tpu.solver.resilience import SolverUnavailable

SOLVER_STORM = "solver-storm"
POD_LOSS = "pod-loss"
FED_PARTITION = "fed-partition"
KILL_STORM = "kill-storm"

#: every campaign profile bench.py's chaoscampaign scenario sweeps
PROFILES = (SOLVER_STORM, POD_LOSS, FED_PARTITION, KILL_STORM)

#: which degradation subsystem each profile storms — the smoke tests
#: assert transition events landed HERE, not just somewhere
PROFILE_SUBSYSTEM = {
    SOLVER_STORM: resilience.SOLVER,
    POD_LOSS: resilience.STREAMING,
    FED_PARTITION: resilience.FEDERATION,
    KILL_STORM: resilience.PERSISTENCE,
}


@dataclass
class CampaignSpec:
    """One seeded campaign: shape, storm schedule, oracle bounds."""

    profile: str
    seed: int = 0
    #: cycles under fire; arrivals are spread across these
    storm_cycles: int = 12
    #: recovery cycles the oracle allows before declaring divergence
    convergence_bound: int = 16
    n_cqs: int = 4
    quota: int = 32
    #: total demand; must fit capacity (n_cqs * quota) so the twin's
    #: terminal state is "everything admitted" — the oracle's anchor
    n_workloads: int = 96
    n_nodes: int = 4
    #: the constant virtual admission clock (byte identity needs every
    #: plane to stamp the same ``now`` into conditions)
    now: float = 1000.0
    #: virtual seconds per cycle on the controller clock — drives the
    #: half-open cooldown probes (mesh retry, WAL restore)
    clock_step_s: float = 30.0
    #: kill-storm: directory for the durable plane (required there)
    persistence_dir: Optional[str] = None

    def __post_init__(self) -> None:
        if self.profile not in PROFILES:
            raise ValueError(
                f"unknown profile {self.profile!r}; one of {PROFILES}")
        if self.n_workloads > self.n_cqs * self.quota:
            raise ValueError("campaign demand must fit capacity "
                             "(the twin must terminate fully admitted)")
        if self.profile == KILL_STORM and not self.persistence_dir:
            raise ValueError("kill-storm needs spec.persistence_dir")


@dataclass
class CampaignResult:
    profile: str
    seed: int
    converged: bool = False
    #: recovery cycles until byte identity + level 0 (0 = converged at
    #: the heal boundary); convergence_bound when it never did
    convergence_cycles: int = 0
    recovered_identical: bool = False
    #: kill-storm only: close + recover from disk == live store
    durable_identical: Optional[bool] = None
    max_degradation_level: int = 0
    #: admitting cycles / cycles with eligible pending work
    availability: float = 1.0
    unavailable_cycles: int = 0
    unavailable_wall_ms: float = 0.0
    invariant_violations: int = 0
    monotone_recovery: bool = True
    levels_zero: bool = False
    faults_injected: int = 0
    transitions: dict = field(default_factory=dict)
    twin_cycles: int = 0
    storm_cycles: int = 0

    @property
    def ok(self) -> bool:
        """The full oracle: converged bit-identical, clean audit,
        monotone recovery, ladder back at 0 (and the durable state
        agreeing, where a durable plane ran)."""
        return (self.converged and self.recovered_identical
                and self.invariant_violations == 0
                and self.monotone_recovery and self.levels_zero
                and self.durable_identical is not False)

    def to_dict(self) -> dict:
        return {
            "profile": self.profile,
            "seed": self.seed,
            "converged": self.converged,
            "convergence_cycles": self.convergence_cycles,
            "recovered_identical": self.recovered_identical,
            "durable_identical": self.durable_identical,
            "max_degradation_level": self.max_degradation_level,
            "availability": round(self.availability, 4),
            "unavailable_cycles": self.unavailable_cycles,
            "unavailable_wall_ms": round(self.unavailable_wall_ms, 3),
            "invariant_violations": self.invariant_violations,
            "monotone_recovery": self.monotone_recovery,
            "levels_zero": self.levels_zero,
            "faults_injected": self.faults_injected,
            "transitions": dict(self.transitions),
            "twin_cycles": self.twin_cycles,
            "storm_cycles": self.storm_cycles,
        }


class _Plane:
    """One live control plane (store + scheduler [+ engine/persist])."""

    def __init__(self, spec: CampaignSpec, clock,
                 persistence: bool = False) -> None:
        self.spec = spec
        self.store = Store()
        self.manager = None
        if persistence:
            from kueue_oss_tpu.persist.manager import PersistenceManager

            # attach BEFORE seeding: only watched events reach the WAL,
            # and the seed objects must be recoverable too
            self.manager = PersistenceManager(
                spec.persistence_dir, fsync="always")
            self.manager.attach(self.store)
            # restore probes on the campaign's virtual cadence
            self.manager.wal.restore_cooldown_s = 2 * spec.clock_step_s
        self.store.upsert_resource_flavor(ResourceFlavor(name="f"))
        for i in range(spec.n_nodes):
            self.store.upsert_node(Node(
                name=f"node{i}", allocatable={"cpu": 1_000_000}))
        for i in range(spec.n_cqs):
            self.store.upsert_cluster_queue(ClusterQueue(
                name=f"cq{i}", resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="f", resources=[
                        ResourceQuota(name="cpu",
                                      nominal=spec.quota)])])]))
            self.store.upsert_local_queue(LocalQueue(
                name=f"lq{i}", cluster_queue=f"cq{i}"))
        self.queues = QueueManager(self.store)
        solver = spec.profile in (SOLVER_STORM, POD_LOSS)
        self.sched = Scheduler(
            self.store, self.queues, clock=clock,
            solver="auto" if solver else None,
            solver_min_backlog=0,
            streaming=(spec.profile == POD_LOSS))
        self.engine = self.sched._solver_engine() if solver else None
        if self.engine is not None:
            self.engine.health.clock = clock
        self.arrived = 0

    def admitted(self) -> int:
        return sum(1 for w in self.store.workloads.values()
                   if w.is_quota_reserved)

    def step(self, now: float, full_solve: bool = True) -> int:
        """One admission pass through every configured arm; returns
        workloads newly admitted. ``full_solve=False`` keeps the cycle
        on the streamed micro-drain path (pod-loss storms stretch the
        armed window across several cycles so node flaps land MID
        window and trip the structural/stream fences)."""
        before = self.admitted()
        if self.engine is not None:
            if full_solve:
                try:
                    self.engine.drain(now=now, verify=True)
                except SolverUnavailable:
                    pass  # the storm's point: host cycles must carry on
            if self.spec.profile == POD_LOSS:
                self.sched.micro_drain(now)
                if not full_solve:
                    return self.admitted() - before
        self.sched.schedule(now=now)
        return self.admitted() - before


class ChaosCampaign:
    """Run one :class:`CampaignSpec` end to end and judge convergence.

    The fault-free twin runs FIRST (its terminal dump is the oracle's
    target), then the faulted plane: storm cycles with composed
    injected faults, an explicit heal (the chaos source goes away —
    recovery itself still rides the controller's cooldown probes),
    then recovery cycles until byte identity or the bound.
    """

    def __init__(self, spec: CampaignSpec) -> None:
        self.spec = spec
        rng = random.Random(spec.seed)
        #: cycle -> [(workload name, lq index)] — shared external trace
        self.arrivals: dict[int, list] = {}
        for i in range(spec.n_workloads):
            c = i * spec.storm_cycles // spec.n_workloads
            self.arrivals.setdefault(c, []).append(
                (f"w{i}", i % spec.n_cqs, i + 1, float(i)))
        #: cycle -> [(op, node name)] — replayed in BOTH planes (the
        #: flap is an external event; the twin sees the same cluster)
        self.flaps: dict[int, list] = {}
        if spec.profile == POD_LOSS:
            for c in range(0, max(1, spec.storm_cycles - 2), 3):
                name = f"node{rng.randrange(spec.n_nodes)}"
                self.flaps.setdefault(c, []).append(("down", name))
                self.flaps.setdefault(c + 2, []).append(("up", name))
        #: cycle -> [fault action] — the storm schedule (faulted only)
        self.fault_plan: dict[int, list] = {}
        if spec.profile == SOLVER_STORM:
            for c in range(spec.storm_cycles):
                for _ in range(1 + (rng.random() < 0.5)):
                    self.fault_plan.setdefault(c, []).append(rng.choice(
                        ("mesh", "all", "breaker", "relax")))
        elif spec.profile == FED_PARTITION:
            for c in range(spec.storm_cycles):
                if rng.random() < 0.6:
                    self.fault_plan.setdefault(c, []).append(
                        ("throttle", rng.choice(("blue", "red"))))
        elif spec.profile == KILL_STORM:
            for c in range(spec.storm_cycles):
                if rng.random() < 0.5:
                    self.fault_plan.setdefault(c, []).append("fsync")
            self.fault_plan.setdefault(
                spec.storm_cycles // 2, []).append("crash")
        self._vnow = 0.0
        self.result = CampaignResult(
            profile=spec.profile, seed=spec.seed,
            storm_cycles=spec.storm_cycles,
            convergence_cycles=spec.convergence_bound)

    # virtual controller/scheduler clock (injected everywhere)
    def _clock(self) -> float:
        return self._vnow

    # -- trace replay -------------------------------------------------

    def _apply_trace(self, plane: _Plane, cycle: int) -> None:
        for name, lq, uid, t in self.arrivals.get(cycle, ()):
            plane.store.add_workload(Workload(
                name=name, queue_name=f"lq{lq}", uid=uid,
                creation_time=t,
                podsets=[PodSet(name="main", count=1,
                                requests={"cpu": 1})]))
            plane.arrived += 1
        for op, name in self.flaps.get(cycle, ()):
            node = plane.store.nodes[name]
            node.ready = op == "up"
            plane.store.upsert_node(node)

    # -- the twin -----------------------------------------------------

    def _run_twin(self) -> bytes:
        spec = self.spec
        self._vnow = 0.0
        with resilience.use(resilience.DegradationController(
                clock=self._clock)):
            plane = _Plane(spec, self._clock)
            cycle = 0
            while True:
                self._vnow += spec.clock_step_s
                self._apply_trace(plane, cycle)
                plane.step(spec.now)
                cycle += 1
                if (cycle >= spec.storm_cycles
                        and plane.admitted() >= spec.n_workloads):
                    break
                if cycle > spec.storm_cycles + 200:
                    raise RuntimeError(
                        "fault-free twin failed to quiesce — the "
                        "campaign shape is broken, not the plane")
            self.result.twin_cycles = cycle
            return canonical_dump(plane.store)

    # -- fault actions ------------------------------------------------

    def _inject(self, plane: _Plane, farm, mesh_inj, cycle: int) -> None:
        res = self.result
        for action in self.fault_plan.get(cycle, ()):
            res.faults_injected += 1
            if action == "mesh":
                mesh_inj.lose_mesh(1)
            elif action == "all":
                mesh_inj.lose_all(1)
            elif action == "breaker":
                for _ in range(plane.engine.health.failure_threshold):
                    plane.engine.health.record_failure()
            elif action == "relax":
                plane.engine._note_relax_failure(
                    RuntimeError("injected relax fault (campaign)"),
                    "relax_error")
            elif action == "fsync":
                plane.manager.wal.fsync_fault += 1
            elif action == "crash":
                from kueue_oss_tpu.chaos import CrashPointInjector
                from kueue_oss_tpu.persist import hooks

                with CrashPointInjector("mid_checkpoint", mode="raise"):
                    try:
                        plane.manager.checkpoint(force_full=True)
                    except hooks.CrashPoint:
                        pass  # the checkpoint died; WAL still rules
            elif isinstance(action, tuple) and action[0] == "throttle":
                farm.force_throttle(action[1], times=1)

    def _heal(self, plane: _Plane, farm, mesh_inj) -> None:
        """The chaos source stops. Conditions clear through the same
        paths production healing uses (probe fsyncs, refresh_mesh,
        breaker success, a served farm grant) — never by resetting the
        controller."""
        spec = self.spec
        if mesh_inj is not None:
            mesh_inj.restore()
            plane.engine.health.record_success()
            if resilience.controller.active(resilience.SOLVER,
                                            "relax_broken"):
                plane.engine._relax_broken = False
        if farm is not None:
            farm.throttle_fault.clear()
        if plane.manager is not None:
            plane.manager.wal.fsync_fault = 0

    def _drive_farm(self, farm) -> None:
        """The federated tenants' per-cycle solver calls: a throttled
        call surfaces in-band backpressure (raising the FEDERATION
        conditions); a served one clears them."""
        for tenant in ("blue", "red"):
            farm.run(tenant, lambda: ({"ok": True}, b""))

    # -- the faulted plane --------------------------------------------

    def run(self) -> CampaignResult:
        spec, res = self.spec, self.result
        twin_dump = self._run_twin()
        self._vnow = 0.0
        ctl = resilience.DegradationController(clock=self._clock)
        with resilience.use(ctl):
            plane = _Plane(spec, self._clock,
                           persistence=spec.profile == KILL_STORM)
            farm = None
            if spec.profile == FED_PARTITION:
                from kueue_oss_tpu.federation.farm import FarmScheduler

                farm = FarmScheduler(clock=self._clock)
            mesh_inj = None
            if plane.engine is not None:
                from kueue_oss_tpu.chaos import MeshFaultInjector

                mesh_inj = MeshFaultInjector(plane.engine)
                if spec.profile == POD_LOSS:
                    # pod-loss storms the streaming fences; the flap
                    # trace is the fault — count the down-flaps
                    res.faults_injected += sum(
                        1 for evs in self.flaps.values()
                        for op, _ in evs if op == "down")

            def cycle_once(cycle: int, inject: bool) -> None:
                self._vnow += spec.clock_step_s
                self._apply_trace(plane, cycle)
                if inject:
                    self._inject(plane, farm, mesh_inj, cycle)
                if farm is not None:
                    self._drive_farm(farm)
                # pod-loss storm cycles stay on the streamed window
                # between periodic full solves (see _Plane.step)
                full = not (inject and spec.profile == POD_LOSS
                            and cycle % 3)
                eligible = plane.arrived > plane.admitted()
                t0 = time.perf_counter()
                delta = plane.step(spec.now, full_solve=full)
                wall_ms = (time.perf_counter() - t0) * 1000
                if eligible and delta == 0:
                    res.unavailable_cycles += 1
                    res.unavailable_wall_ms += wall_ms
                res.max_degradation_level = max(
                    res.max_degradation_level, ctl.max_level())

            for cycle in range(spec.storm_cycles):
                cycle_once(cycle, inject=True)
            self._heal(plane, farm, mesh_inj)

            # recovery: no new faults; cooldown probes + normal
            # admission must converge on the twin within the bound
            level_trace = [ctl.max_level()]
            for r in range(1, spec.convergence_bound + 1):
                cycle_once(spec.storm_cycles + r - 1, inject=False)
                level_trace.append(ctl.max_level())
                if (ctl.max_level() == 0
                        and canonical_dump(plane.store) == twin_dump):
                    res.converged = True
                    res.convergence_cycles = r
                    break
            res.recovered_identical = (
                canonical_dump(plane.store) == twin_dump)
            res.levels_zero = ctl.max_level() == 0
            res.monotone_recovery = all(
                b <= a for a, b in zip(level_trace, level_trace[1:]))
            res.invariant_violations = len(
                InvariantAuditor(plane.store).audit())
            res.transitions = {
                s: len(ctl.transitions_for(s))
                for s in resilience.SUBSYSTEMS
                if ctl.transitions_for(s)}
            cycles_total = spec.storm_cycles + (
                res.convergence_cycles if res.converged
                else spec.convergence_bound)
            eligible_cycles = max(1, cycles_total)
            res.availability = 1.0 - res.unavailable_cycles / eligible_cycles
            if plane.manager is not None:
                res.durable_identical = self._durable_check(plane)
        return res

    def _durable_check(self, plane: _Plane) -> bool:
        """kill-storm's extra oracle: close the durable plane, recover
        a fresh store from disk, and demand byte identity with the
        live one — the storm (failed fsyncs, a died checkpoint) must
        not have cost a single acknowledged record."""
        from kueue_oss_tpu.persist.manager import PersistenceManager

        live = canonical_dump(plane.store)
        plane.manager.close()
        m2 = PersistenceManager(self.spec.persistence_dir)
        try:
            recovered = m2.recover()
            return canonical_dump(recovered.store) == live
        finally:
            m2.close()


def run_campaign(profile: str, seed: int = 0, **kw) -> CampaignResult:
    """Convenience wrapper: build, run, return the result."""
    return ChaosCampaign(CampaignSpec(profile=profile, seed=seed,
                                      **kw)).run()
