"""LeaderWorkerSet integration.

Reference parity: pkg/controller/jobs/leaderworkerset/
leaderworkerset_reconciler.go (454 LoC) — unlike the GenericJob kinds,
an LWS is served by a CUSTOM reconciler that maintains ONE WORKLOAD PER
REPLICA GROUP: group i gets workload "<name>-<i>" with a leader podset
(count 1) and, when size > 1, a worker podset (count size-1)
(:leaderPodSetName/workerPodSetName). Scaling replicas up creates the
missing group workloads; scaling down deletes the orphaned ones
(filterWorkloads → toCreate/toUpdate/toDelete, :140-170). Each group's
pods are gated/ungated with its own workload's admission, so groups
admit independently.

The aggregated `LeaderWorkerSet` dataclass is the spec object; the
`LWSGroup` jobs it expands into are what flow through the generic
JobReconciler (the reference analog builds Workloads directly; routing
through the job framework keeps eviction/suspend semantics shared).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager

#: reference label set on every pod of a group (lwsNameLabel)
LWS_NAME_LABEL = "leaderworkerset.sigs.k8s.io/name"
GROUP_INDEX_LABEL = "leaderworkerset.sigs.k8s.io/group-index"


@integration_manager.register
@dataclass
class LeaderWorkerSet(BaseJob):
    """The LWS spec. `pod_sets()` gives the aggregate shape (used for
    quota summaries); admission flows through per-group LWSGroup jobs."""

    kind = "LeaderWorkerSet"

    replicas: int = 1
    size: int = 1  # pods per replica group (leader + workers)
    leader_requests: dict[str, int] = field(default_factory=dict)
    worker_requests: dict[str, int] = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None

    def validate(self) -> list[str]:
        """leaderworkerset_webhook.go: size and replicas must be
        positive (a zero-size group has no leader to admit)."""
        errs = []
        if self.size < 1:
            errs.append("size: must be >= 1")
        if self.replicas < 1:
            errs.append("replicas: must be >= 1")
        return errs

    def pod_sets(self) -> list[PodSet]:
        podsets = [PodSet(name="leader", count=self.replicas,
                          requests=dict(self.leader_requests))]
        workers_per_group = max(self.size - 1, 0)
        if workers_per_group:
            podsets.append(PodSet(
                name="workers", count=self.replicas * workers_per_group,
                requests=dict(self.worker_requests)))
        return podsets

    def group_pod_sets(self) -> list[PodSet]:
        """One group's shape (leaderworkerset_reconciler.go podsets)."""
        podsets = [PodSet(name="leader", count=1,
                          requests=dict(self.leader_requests),
                          topology_request=self.topology_request)]
        if self.size > 1:
            podsets.append(PodSet(
                name="workers", count=self.size - 1,
                requests=dict(self.worker_requests),
                topology_request=self.topology_request))
        return podsets


@integration_manager.register
@dataclass
class LWSGroup(BaseJob):
    """One replica group of a LeaderWorkerSet — the unit of admission."""

    kind = "LWSGroup"

    group_index: int = 0
    podsets: list[PodSet] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return list(self.podsets)


class LeaderWorkerSetReconciler:
    """Expands LWS specs into per-group jobs and keeps them in step with
    spec.replicas (leaderworkerset_reconciler.go Reconcile)."""

    def __init__(self, reconciler) -> None:
        self.reconciler = reconciler  # the generic JobReconciler
        self.sets: dict[str, LeaderWorkerSet] = {}

    def upsert(self, lws: LeaderWorkerSet) -> None:
        self.sets[lws.key] = lws

    def delete(self, key: str, now: float = 0.0) -> None:
        lws = self.sets.pop(key, None)
        if lws is None:
            return
        # delete the ACTUALLY managed groups, not the current spec's
        # replica range — a pre-delete scale-down must not leak groups
        for job in self.groups_of(lws):
            self.reconciler.delete_job(job, now=now)

    def _groups(self, lws: LeaderWorkerSet) -> list[LWSGroup]:
        return [LWSGroup(
            name=f"{lws.name}-{i}", namespace=lws.namespace,
            queue_name=lws.queue_name, priority=lws.priority,
            creation_time=lws.creation_time, group_index=i,
            labels={LWS_NAME_LABEL: lws.name, GROUP_INDEX_LABEL: str(i)},
            podsets=lws.group_pod_sets(),
        ) for i in range(lws.replicas)]

    def reconcile(self, now: float) -> None:
        for lws in self.sets.values():
            wanted = {j.key: j for j in self._groups(lws)}
            # existing groups of this LWS under management
            existing = {
                key: job for (kind, key), job in self.reconciler.jobs.items()
                if kind == "LWSGroup"
                and job.labels.get(LWS_NAME_LABEL) == lws.name
                and job.namespace == lws.namespace}
            # toDelete: scale-down removed the group (reconciler.go:168)
            for key, job in existing.items():
                if key not in wanted:
                    self.reconciler.delete_job(job, now=now)
            # toCreate/toUpdate (reconciler.go:151-166): new groups enter
            # management; existing ones refresh their podset shape so a
            # size/requests change rebuilds the group workload
            for key, job in wanted.items():
                cur = existing.get(key)
                if cur is None:
                    self.reconciler.upsert_job(job)
                else:
                    cur.podsets = job.podsets
                    cur.queue_name = lws.queue_name
        self.reconciler.reconcile_all(now)

    def groups_of(self, lws: LeaderWorkerSet) -> list[LWSGroup]:
        """Managed group jobs for an LWS, by group index."""
        out = [job for (kind, _), job in self.reconciler.jobs.items()
               if kind == "LWSGroup"
               and job.labels.get(LWS_NAME_LABEL) == lws.name
               and job.namespace == lws.namespace]
        return sorted(out, key=lambda j: j.group_index)
