"""LeaderWorkerSet integration.

Reference parity: pkg/controller/jobs/leaderworkerset — per replica group:
one leader pod + (size-1) workers; modeled as two podsets across replicas.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class LeaderWorkerSet(BaseJob):
    kind = "LeaderWorkerSet"

    replicas: int = 1
    size: int = 1  # pods per replica group (leader + workers)
    leader_requests: dict[str, int] = field(default_factory=dict)
    worker_requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        podsets = [PodSet(name="leader", count=self.replicas,
                          requests=dict(self.leader_requests))]
        workers_per_group = max(self.size - 1, 0)
        if workers_per_group:
            podsets.append(PodSet(
                name="workers", count=self.replicas * workers_per_group,
                requests=dict(self.worker_requests)))
        return podsets
