"""SparkApplication integration.

Reference parity: pkg/controller/jobs/sparkapplication/ — the controller
(322 LoC) + podset builder (498 LoC) + webhook (192 LoC):

- podsets are driver (count 1) + executor (count =
  spec.executor.instances, sparkapplication_podset.go:52-54 /
  sparkapplication_controller.go:140);
- per-role resources derive from the Spark resource model
  (mutateSparkPod: cores → cpu request, memory + memoryOverhead →
  memory request, GPU quantity onto the gpu resource name,
  sparkapplication_podset.go:343-500) — `effective_requests` mirrors
  that derivation when the spark-style fields are used, while raw
  `*_requests` dicts pass through untouched;
- dynamic allocation is REJECTED at the webhook: kueue cannot manage a
  fleet the spark operator resizes on its own
  (sparkapplication_webhook.go:129-134);
- partial admission writes the admitted count back to
  executor.instances (sparkapplication_controller.go:281).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager

MIB = 1024 * 1024

#: default memoryOverheadFactor when unset (spark-operator defaults)
DEFAULT_MEMORY_OVERHEAD_FACTOR = 0.1
MIN_MEMORY_OVERHEAD = 384 * MIB


@dataclass
class SparkRoleSpec:
    """Spark driver/executor resource model (sparkv1beta2 SparkPodSpec)."""

    cores: Optional[int] = None          # whole cores → cpu milli
    memory_mib: Optional[int] = None     # spark memory string, in MiB
    memory_overhead_mib: Optional[int] = None
    gpu_name: Optional[str] = None
    gpu_quantity: int = 0

    def requests(self, overhead_factor: float) -> dict[str, int]:
        out: dict[str, int] = {}
        if self.cores is not None:
            out["cpu"] = self.cores * 1000
        if self.memory_mib is not None:
            overhead = self.memory_overhead_mib
            if overhead is None:
                overhead = max(int(self.memory_mib * overhead_factor),
                               MIN_MEMORY_OVERHEAD // MIB)
            out["memory"] = (self.memory_mib + overhead) * MIB
        if self.gpu_name and self.gpu_quantity:
            out[self.gpu_name] = self.gpu_quantity
        return out


@integration_manager.register
@dataclass
class SparkApplication(BaseJob):
    kind = "SparkApplication"

    driver_requests: dict[str, int] = field(default_factory=dict)
    executor_instances: int = 1
    executor_requests: dict[str, int] = field(default_factory=dict)
    #: spark-style resource specs (used when the raw dicts are empty)
    driver_spec: Optional[SparkRoleSpec] = None
    executor_spec: Optional[SparkRoleSpec] = None
    memory_overhead_factor: float = DEFAULT_MEMORY_OVERHEAD_FACTOR
    #: spec.dynamicAllocation.enabled — invalid under kueue management
    dynamic_allocation: bool = False
    #: live status (sparkv1beta2 ApplicationStateType)
    application_state: str = ""

    def effective_requests(self, role: str) -> dict[str, int]:
        raw = self.driver_requests if role == "driver" \
            else self.executor_requests
        if raw:
            return dict(raw)
        spec = self.driver_spec if role == "driver" else self.executor_spec
        if spec is not None:
            return spec.requests(self.memory_overhead_factor)
        return {}

    def pod_sets(self) -> list[PodSet]:
        return [
            PodSet(name="driver", count=1,
                   requests=self.effective_requests("driver")),
            PodSet(name="executor", count=self.executor_instances,
                   requests=self.effective_requests("executor")),
        ]

    def validate(self) -> list[str]:
        """sparkapplication_webhook.go:129-134."""
        if self.dynamic_allocation:
            return ["spec.dynamicAllocation.enabled must be false: kueue "
                    "cannot manage dynamically allocated executors"]
        return []

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        super().run_with_podsets_info(infos)
        # partial admission shrinks executor.instances
        # (sparkapplication_controller.go:281); keep the spec value so
        # RestorePodSetsInfo can undo the shrink after eviction
        if getattr(self, "_spec_instances", None) is None:
            self._spec_instances = self.executor_instances
        for info in infos:
            if info.name == "executor" and info.count:
                self.executor_instances = info.count

    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        changed = super().restore_podsets_info(infos)
        saved = getattr(self, "_spec_instances", None)
        if saved is not None:
            changed = changed or saved != self.executor_instances
            self.executor_instances = saved
            self._spec_instances = None
        return changed

    def finished(self) -> tuple[str, bool, bool]:
        if self.application_state in ("COMPLETED", "FAILED"):
            return (self.finish_message,
                    self.application_state == "COMPLETED", True)
        return super().finished()

    def pods_ready(self) -> bool:
        return self.application_state == "RUNNING" or super().pods_ready()
