"""SparkApplication integration.

Reference parity: pkg/controller/jobs/sparkapplication — driver + executor
podsets.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class SparkApplication(BaseJob):
    kind = "SparkApplication"

    driver_requests: dict[str, int] = field(default_factory=dict)
    executor_instances: int = 1
    executor_requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [
            PodSet(name="driver", count=1,
                   requests=dict(self.driver_requests)),
            PodSet(name="executor", count=self.executor_instances,
                   requests=dict(self.executor_requests)),
        ]
