"""Deployment integration (serving workloads).

Reference parity: pkg/controller/jobs/deployment — webhook-only in the
reference: the Deployment webhook propagates the queue-name label onto
the pod template (deployment_webhook.go Default), and each replica pod
is then admitted INDIVIDUALLY as a plain single-pod workload through the
pod integration (serving semantics: replicas admit and preempt
independently; rolling updates surge pods just queue as new singletons).

The `Deployment` dataclass keeps a GenericJob form (one replicas-sized
podset) for aggregate quota views, and `expand_pods()` produces the
per-replica singleton pods matching the reference's actual admission
unit.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager
from kueue_oss_tpu.jobs.pod import Pod


@integration_manager.register
@dataclass
class Deployment(BaseJob):
    kind = "Deployment"

    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    #: live status
    ready_replicas: int = 0

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=self.replicas,
                       requests=dict(self.requests))]

    def pods_ready(self) -> bool:
        return self.ready_replicas >= self.replicas

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        self.ready_replicas = self.replicas if ready else 0

    def expand_pods(self) -> list[Pod]:
        """Per-replica singleton pods (deployment_webhook.go Default
        stamps the queue label; no pod-group labels — each pod is its
        own workload)."""
        return [Pod(
            name=f"{self.name}-{i}",
            namespace=self.namespace,
            queue_name=self.queue_name,
            requests=dict(self.requests),
            priority=self.priority,
            creation_time=self.creation_time,
        ) for i in range(self.replicas)]
