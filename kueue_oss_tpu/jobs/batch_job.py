"""batch/v1 Job integration.

Reference parity: pkg/controller/jobs/job/job_controller.go (381 LoC) —
one "main" podset sized by parallelism; partial admission maps to
min_parallelism (KEP-420, the reference's minimum parallelism
annotation) and RunWithPodSetsInfo shrinks parallelism to the admitted
count; ReclaimablePods releases the seats that completions math proves
will never be needed again (:213-227); PodsReady counts succeeded +
ready against parallelism (:322-329); Finished follows the
Complete/Failed job conditions (:312-320).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest, Toleration
from kueue_oss_tpu.jobframework.interface import BaseJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager

#: job_webhook.go JobCompletionsEqualParallelismAnnotation
SYNC_COMPLETIONS_ANNOTATION = (
    "kueue.x-k8s.io/job-completions-equal-parallelism")


@integration_manager.register
@dataclass
class BatchJob(BaseJob):
    kind = "Job"

    parallelism: int = 1
    completions: Optional[int] = None
    #: per-pod resource requests in canonical units
    requests: dict[str, int] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    #: minimum parallelism acceptable for partial admission (KEP-420)
    min_parallelism: Optional[int] = None
    topology_request: Optional[PodSetTopologyRequest] = None
    #: live status counters (job.Status)
    succeeded: int = 0
    #: batchv1 CompletionMode ("NonIndexed" | "Indexed")
    completion_mode: str = "NonIndexed"

    def validate(self) -> list[str]:
        """job_webhook.go validatePartialAdmissionCreate +
        validateSyncCompletionCreate."""
        errs = []
        if self.min_parallelism is not None and not (
                0 < self.min_parallelism < self.parallelism):
            errs.append(
                f"minParallelism {self.min_parallelism}: should be "
                f"between 0 and {self.parallelism - 1}")
        sync = self.annotations.get(SYNC_COMPLETIONS_ANNOTATION)
        if sync is not None:
            if sync.lower() not in ("true", "false"):
                errs.append(f"{SYNC_COMPLETIONS_ANNOTATION}: "
                            f"{sync!r} is not a boolean")
            elif sync.lower() == "true":
                if self.completion_mode != "Indexed":
                    errs.append(f"{SYNC_COMPLETIONS_ANNOTATION}: should "
                                "not be enabled for NonIndexed jobs")
                if (self.completions or 1) != self.parallelism:
                    errs.append(
                        "completions: should be equal to parallelism "
                        f"when {SYNC_COMPLETIONS_ANNOTATION} is true")
        return errs

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(
            name="main",
            count=self.parallelism,
            requests=dict(self.requests),
            min_count=self.min_parallelism,
            topology_request=self.topology_request,
            node_selector=dict(self.node_selector),
            tolerations=list(self.tolerations),
        )]

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        super().run_with_podsets_info(infos)
        # Partial admission shrinks parallelism to the admitted count
        # (job_controller.go RunWithPodSetsInfo).
        if infos and infos[0].count:
            self.parallelism = infos[0].count

    def reclaimable_pods(self) -> dict[str, int]:
        """job_controller.go:213-227: once remaining completions drop
        below parallelism, the surplus seats are reclaimable."""
        if self.parallelism == 1 or self.succeeded == 0:
            return {}
        remaining = (self.completions or self.parallelism) - self.succeeded
        if remaining >= self.parallelism:
            return {}
        return {"main": self.parallelism - max(remaining, 0)}

    def pods_ready(self) -> bool:
        """job_controller.go:322-329."""
        return self.succeeded + self.ready_pods >= self.parallelism

    def mark_succeeded(self, n: int = 1) -> None:
        """Simulator helper: n more pods completed successfully."""
        self.succeeded += n
        self.active_pods = max(self.active_pods - n, 0)
        self.ready_pods = max(self.ready_pods - n, 0)
        target = self.completions or self.parallelism
        if self.succeeded >= target:
            self.mark_finished(success=True, message="JobComplete")
