"""Kubeflow training-operator integrations.

Reference parity: pkg/controller/jobs/kubeflow/jobs/{tfjob,pytorchjob,
xgboostjob,paddlejob,jaxjob} — one podset per replica spec role, ordered
with the master/chief role first (kubeflowjob.go OrderedReplicaTypes).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@dataclass
class ReplicaSpec:
    role: str  # e.g. "Master", "Worker", "PS", "Chief"
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)


_ROLE_ORDER = {"Master": 0, "Chief": 0, "Launcher": 0}


@dataclass
class _KubeflowJob(BaseJob):
    replica_specs: list[ReplicaSpec] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        ordered = sorted(self.replica_specs,
                         key=lambda rs: (_ROLE_ORDER.get(rs.role, 1), rs.role))
        return [PodSet(name=rs.role.lower(), count=rs.replicas,
                       requests=dict(rs.requests)) for rs in ordered]


@integration_manager.register
@dataclass
class TFJob(_KubeflowJob):
    kind = "TFJob"


@integration_manager.register
@dataclass
class PyTorchJob(_KubeflowJob):
    kind = "PyTorchJob"


@integration_manager.register
@dataclass
class XGBoostJob(_KubeflowJob):
    kind = "XGBoostJob"


@integration_manager.register
@dataclass
class PaddleJob(_KubeflowJob):
    kind = "PaddleJob"


@integration_manager.register
@dataclass
class JAXJob(_KubeflowJob):
    kind = "JAXJob"
