"""Kubeflow training-operator integrations.

Reference parity: pkg/controller/jobs/kubeflow/kubeflowjob/
kubeflowjob_controller.go (shared KubeflowJob control, 240 LoC) plus the
per-framework glue in pkg/controller/jobs/kubeflow/jobs/{tfjob,pytorchjob,
xgboostjob,paddlejob,jaxjob}. Semantics carried over:

- podsets are built in the framework's canonical replica-type order
  (OrderedReplicaTypes, kubeflowjob_controller.go:174-181); replica types
  absent from the spec are dropped, the remainder keeps canonical order;
- workload priority class resolves runPolicy.schedulingPolicy first, then
  the first replica type that sets one
  (kubeflowjob_controller.go:153-171);
- RunWithPodSetsInfo merges the admission node selectors into each
  replica template in the same order and rejects a length mismatch
  (kubeflowjob_controller.go:57-75); RestorePodSetsInfo undoes it;
- PodsReady requires every replica type's ready count to reach its
  declared replicas (kubeflowjob_controller.go:133-151).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest, Toleration
from kueue_oss_tpu.jobframework.interface import BaseJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager


@dataclass
class ReplicaSpec:
    """One replica type's template (kftraining ReplicaSpec analog)."""

    role: str  # e.g. "Master", "Worker", "PS", "Chief", "Launcher"
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    priority_class: Optional[str] = None
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    topology_request: Optional[PodSetTopologyRequest] = None
    #: live status (maintained by the simulator / tests)
    ready_replicas: int = 0


#: fallback rank for roles outside a framework's canonical order
_ROLE_ORDER = {"Master": 0, "Chief": 0, "Launcher": 0}


@dataclass
class _KubeflowJob(BaseJob):
    """Shared control for the training-operator job kinds.

    Subclasses fix `kind` and `replica_order` (the framework's canonical
    replica-type sequence). `replica_specs` keeps list form for API
    stability; ordering always resolves through `ordered_replica_specs`.
    """

    #: canonical replica-type order; () = master-ish roles first, by name
    replica_order: tuple[str, ...] = ()

    replica_specs: list[ReplicaSpec] = field(default_factory=list)
    #: runPolicy.schedulingPolicy.priorityClass
    scheduling_priority_class: Optional[str] = None

    def ordered_replica_specs(self) -> list[ReplicaSpec]:
        if self.replica_order:
            rank = {t: i for i, t in enumerate(self.replica_order)}
            key = lambda rs: (rank.get(rs.role, len(rank)), rs.role)
        else:
            key = lambda rs: (_ROLE_ORDER.get(rs.role, 1), rs.role)
        return sorted(self.replica_specs, key=key)

    def effective_priority_class(self) -> Optional[str]:
        """kubeflowjob_controller.go:161-171 PriorityClass()."""
        if self.scheduling_priority_class:
            return self.scheduling_priority_class
        for rs in self.ordered_replica_specs():
            if rs.priority_class:
                return rs.priority_class
        return None

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(
            name=rs.role.lower(),
            count=rs.replicas,
            requests=dict(rs.requests),
            node_selector=dict(rs.node_selector),
            tolerations=list(rs.tolerations),
            topology_request=rs.topology_request,
        ) for rs in self.ordered_replica_specs()]

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        ordered = self.ordered_replica_specs()
        if len(infos) != len(ordered):
            raise ValueError(
                f"expected {len(ordered)} podset infos, got {len(infos)}")
        super().run_with_podsets_info(infos)
        # keep the FIRST (pristine) selectors across re-injections (the
        # elastic slice takeover calls this again while running)
        if getattr(self, "_saved_selectors", None) is None:
            self._saved_selectors = {
                rs.role: dict(rs.node_selector) for rs in ordered}
        for rs, info in zip(ordered, infos):
            rs.node_selector.update(info.node_selector)

    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        changed = super().restore_podsets_info(infos)
        saved = getattr(self, "_saved_selectors", None)
        if saved:
            for rs in self.replica_specs:
                if rs.role in saved:
                    rs.node_selector = dict(saved[rs.role])
            self._saved_selectors = None
        return changed

    def pods_ready(self) -> bool:
        return all(rs.ready_replicas >= rs.replicas
                   for rs in self.replica_specs)

    # -- simulator helpers --------------------------------------------------

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        for rs in self.replica_specs:
            rs.ready_replicas = rs.replicas if ready else 0

    def do_suspend(self) -> None:
        super().do_suspend()
        for rs in self.replica_specs:
            rs.ready_replicas = 0


@integration_manager.register
@dataclass
class TFJob(_KubeflowJob):
    """tfjob_controller.go OrderedReplicaTypes: Chief, Master, PS,
    Worker (then Evaluator)."""

    kind = "TFJob"
    replica_order: tuple[str, ...] = (
        "Chief", "Master", "PS", "Worker", "Evaluator")


@integration_manager.register
@dataclass
class PyTorchJob(_KubeflowJob):
    kind = "PyTorchJob"
    replica_order: tuple[str, ...] = ("Master", "Worker")


@integration_manager.register
@dataclass
class XGBoostJob(_KubeflowJob):
    kind = "XGBoostJob"
    replica_order: tuple[str, ...] = ("Master", "Worker")


@integration_manager.register
@dataclass
class PaddleJob(_KubeflowJob):
    kind = "PaddleJob"
    replica_order: tuple[str, ...] = ("Master", "Worker")


@integration_manager.register
@dataclass
class JAXJob(_KubeflowJob):
    kind = "JAXJob"
    replica_order: tuple[str, ...] = ("Worker",)
