"""AppWrapper integration.

Reference parity: pkg/controller/jobs/appwrapper — the wrapper's component
podsets are concatenated into one workload.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class AppWrapper(BaseJob):
    kind = "AppWrapper"

    #: (component name, count, per-pod requests)
    components: list[tuple[str, int, dict[str, int]]] = field(
        default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=name, count=count, requests=dict(requests))
                for name, count, requests in self.components]
