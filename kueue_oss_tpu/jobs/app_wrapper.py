"""AppWrapper integration.

Reference parity: pkg/controller/jobs/appwrapper/appwrapper_controller.go
(222 LoC) — an AppWrapper bundles heterogeneous component resources into
ONE workload: PodSets(): the components' declared podsets are
concatenated in component order (each component contributes the podsets
of the resource it wraps), and RunWithPodSetsInfo slices the injected
infos back to the owning component in the same order. Suspension drives
the wrapper's own suspend field; the wrapped components inherit it.

Components are either raw shapes `(name, count, requests)` or wrapped
GenericJob children (their pod_sets() are flattened in, names prefixed
with the child's name to stay unique across components).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Union

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob, GenericJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager

Component = Union[tuple, GenericJob]


@integration_manager.register
@dataclass
class AppWrapper(BaseJob):
    kind = "AppWrapper"

    #: (component name, count, per-pod requests) | wrapped GenericJob
    components: list[Component] = field(default_factory=list)

    def _component_podsets(self) -> list[tuple[Component, list[PodSet]]]:
        out: list[tuple[Component, list[PodSet]]] = []
        for comp in self.components:
            if isinstance(comp, tuple):
                name, count, requests = comp
                out.append((comp, [PodSet(
                    name=name, count=count, requests=dict(requests))]))
            else:
                prefixed = [PodSet(
                    name=f"{comp.name}-{ps.name}", count=ps.count,
                    requests=dict(ps.requests), min_count=ps.min_count,
                    topology_request=ps.topology_request,
                    node_selector=dict(ps.node_selector),
                    tolerations=list(ps.tolerations),
                ) for ps in comp.pod_sets()]
                out.append((comp, prefixed))
        return out

    def pod_sets(self) -> list[PodSet]:
        return [ps for _, podsets in self._component_podsets()
                for ps in podsets]

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        """Distribute infos back to wrapped children in component order
        (appwrapper_controller.go RunWithPodSetsInfo)."""
        super().run_with_podsets_info(infos)
        i = 0
        for comp, podsets in self._component_podsets():
            n = len(podsets)
            if isinstance(comp, GenericJob):
                # strip the component prefix so children that match infos
                # by their own podset names (e.g. Spark's "executor"
                # partial-admission hook) see the names they emitted
                prefix = f"{comp.name}-"
                child_infos = [PodSetInfo(
                    name=info.name.removeprefix(prefix), count=info.count,
                    node_selector=dict(info.node_selector),
                    tolerations=list(info.tolerations),
                    scheduling_gates=list(info.scheduling_gates),
                ) for info in infos[i:i + n]]
                comp.run_with_podsets_info(child_infos)
            i += n

    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        changed = super().restore_podsets_info(infos)
        for comp in self.components:
            if isinstance(comp, GenericJob):
                changed = comp.restore_podsets_info([]) or changed
        return changed

    def do_suspend(self) -> None:
        super().do_suspend()
        for comp in self.components:
            if isinstance(comp, GenericJob) and not comp.is_suspended():
                comp.do_suspend()

    def finished(self) -> tuple[str, bool, bool]:
        children = [c for c in self.components
                    if isinstance(c, GenericJob)]
        if children:
            results = [c.finished() for c in children]
            if all(done for _, _, done in results):
                success = all(ok for _, ok, _ in results)
                return ("all components finished", success, True)
            if any(done and not ok for _, ok, done in results):
                return ("component failed", False, True)
        return super().finished()

    def pods_ready(self) -> bool:
        children = [c for c in self.components
                    if isinstance(c, GenericJob)]
        if children:
            return all(c.pods_ready() for c in children)
        return super().pods_ready()
