"""StatefulSet integration.

Reference parity: pkg/controller/jobs/statefulset — replicas-sized single
podset; scale handled by workload-slice replacement (elastic jobs).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class StatefulSet(BaseJob):
    kind = "StatefulSet"

    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=self.replicas,
                       requests=dict(self.requests))]
