"""StatefulSet integration.

Reference parity: pkg/controller/jobs/statefulset/
statefulset_reconciler.go (447 LoC) + statefulset_webhook.go (222) +
statefulset_pod_reconciler.go (196). The reference does NOT reconcile the
StatefulSet as a GenericJob: its webhook stamps the pod template with the
queue label and the POD-GROUP labels (group name = sts name, total count
= replicas), so the sts's pods are admitted as one composable pod group
through the pod integration; the sts reconciler only tracks scale
changes (updating the group's total) and cleans up on deletion.

Both forms are provided here: the `StatefulSet` dataclass is a
GenericJob (one replicas-sized podset — used directly by the elastic
workload-slice path, which is how scaling admits without re-queueing the
whole group), and `expand_pods()` produces the gated member pods that
drive the PodGroupController exactly as the webhook-stamped pods do.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager
from kueue_oss_tpu.jobs.pod import (
    POD_GROUP_LABEL,
    POD_GROUP_TOTAL_ANNOTATION,
    Pod,
)


@integration_manager.register
@dataclass
class StatefulSet(BaseJob):
    kind = "StatefulSet"

    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    #: live status
    ready_replicas: int = 0

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=self.replicas,
                       requests=dict(self.requests))]

    def pods_ready(self) -> bool:
        return self.ready_replicas >= self.replicas

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        self.ready_replicas = self.replicas if ready else 0

    def expand_pods(self) -> list[Pod]:
        """The webhook-stamped member pods (statefulset_webhook.go
        Default): ordinal-named, gated, carrying the pod-group labels."""
        return [Pod(
            name=f"{self.name}-{i}",
            namespace=self.namespace,
            queue_name=self.queue_name,
            requests=dict(self.requests),
            labels={POD_GROUP_LABEL: self.name},
            annotations={POD_GROUP_TOTAL_ANNOTATION: str(self.replicas)},
            priority=self.priority,
            creation_time=self.creation_time,
        ) for i in range(self.replicas)]
