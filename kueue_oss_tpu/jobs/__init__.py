"""Built-in job integrations.

Reference parity: pkg/controller/jobs/jobs.go:20-35 — importing this
package registers every built-in integration with the process-wide
IntegrationManager, mirroring the reference's init() side-effect imports.
"""

from kueue_oss_tpu.jobs.batch_job import BatchJob
from kueue_oss_tpu.jobs.job_set import JobSet, ReplicatedJob
from kueue_oss_tpu.jobs.pod import PlainPod, PodGroup, PodGroupRole
from kueue_oss_tpu.jobs.deployment import Deployment
from kueue_oss_tpu.jobs.stateful_set import StatefulSet
from kueue_oss_tpu.jobs.leader_worker_set import (
    LeaderWorkerSet,
    LeaderWorkerSetReconciler,
    LWSGroup,
)
from kueue_oss_tpu.jobs.mpi_job import MPIJob
from kueue_oss_tpu.jobs.ray import RayCluster, RayJob, RayService, WorkerGroup
from kueue_oss_tpu.jobs.kubeflow import (
    JAXJob,
    PaddleJob,
    PyTorchJob,
    ReplicaSpec,
    TFJob,
    XGBoostJob,
)
from kueue_oss_tpu.jobs.train_job import (
    TrainingRuntime,
    TrainJob,
    runtime_registry,
)
from kueue_oss_tpu.jobs.app_wrapper import AppWrapper
from kueue_oss_tpu.jobs.spark import SparkApplication, SparkRoleSpec

__all__ = [
    "BatchJob", "JobSet", "ReplicatedJob", "PlainPod", "PodGroup",
    "PodGroupRole", "Deployment", "StatefulSet", "LeaderWorkerSet",
    "LeaderWorkerSetReconciler", "LWSGroup", "MPIJob",
    "RayCluster", "RayJob", "RayService", "WorkerGroup", "TFJob",
    "PyTorchJob", "XGBoostJob", "PaddleJob", "JAXJob", "ReplicaSpec",
    "TrainJob", "TrainingRuntime", "runtime_registry", "AppWrapper",
    "SparkApplication", "SparkRoleSpec",
]
