"""MPIJob integration.

Reference parity: pkg/controller/jobs/mpijob/mpijob_controller.go (238
LoC) — Launcher + Worker podsets in that order (:223-228), priority class
from runPolicy.schedulingPolicy, then the Launcher template, then the
Worker template (:178-190), and the kubeflow-style podset-info
merge/restore. `run_launcher_as_worker` mirrors the MPIJob v2
runLauncherAsWorker spec field: the launcher participates in the
computation, so its podset carries the worker resource shape when it has
no explicit requests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_oss_tpu.jobframework.interface import BaseJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class MPIJob(BaseJob):
    kind = "MPIJob"

    launcher_requests: dict[str, int] = field(default_factory=dict)
    worker_count: int = 1
    worker_requests: dict[str, int] = field(default_factory=dict)
    #: MPIJob v2 spec.runLauncherAsWorker
    run_launcher_as_worker: bool = False
    launcher_priority_class: Optional[str] = None
    worker_priority_class: Optional[str] = None
    scheduling_priority_class: Optional[str] = None
    worker_topology_request: Optional[PodSetTopologyRequest] = None
    #: live status
    ready_launchers: int = 0
    ready_workers: int = 0

    def effective_priority_class(self) -> Optional[str]:
        """mpijob_controller.go:178-190 PriorityClass()."""
        return (self.scheduling_priority_class
                or self.launcher_priority_class
                or self.worker_priority_class)

    def pod_sets(self) -> list[PodSet]:
        launcher_requests = dict(self.launcher_requests)
        if self.run_launcher_as_worker and not launcher_requests:
            launcher_requests = dict(self.worker_requests)
        sets = [PodSet(name="launcher", count=1,
                       requests=launcher_requests)]
        if self.worker_count > 0:
            sets.append(PodSet(
                name="worker", count=self.worker_count,
                requests=dict(self.worker_requests),
                topology_request=self.worker_topology_request))
        return sets

    def validate(self) -> list[str]:
        """mpijob_webhook.go validateCommon: worker count sanity and
        launcher-as-worker consistency (a launcher that counts as a
        worker needs the worker template to exist)."""
        errs = []
        if self.worker_count < 0:
            errs.append("mpiReplicaSpecs.Worker: replicas must be >= 0")
        if self.run_launcher_as_worker and self.worker_count <= 0:
            errs.append("runLauncherAsWorker: requires a Worker replica "
                        "spec to take the template from")
        return errs

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        expected = 1 + (1 if self.worker_count > 0 else 0)
        if len(infos) != expected:
            raise ValueError(
                f"expected {expected} podset infos, got {len(infos)}")
        super().run_with_podsets_info(infos)

    def pods_ready(self) -> bool:
        return (self.ready_launchers >= 1
                and self.ready_workers >= self.worker_count)

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        self.ready_launchers = 1 if ready else 0
        self.ready_workers = self.worker_count if ready else 0

    def do_suspend(self) -> None:
        super().do_suspend()
        self.ready_launchers = 0
        self.ready_workers = 0
