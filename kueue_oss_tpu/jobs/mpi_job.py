"""MPIJob integration.

Reference parity: pkg/controller/jobs/mpijob — launcher + worker podsets.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class MPIJob(BaseJob):
    kind = "MPIJob"

    launcher_requests: dict[str, int] = field(default_factory=dict)
    worker_count: int = 1
    worker_requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [
            PodSet(name="launcher", count=1,
                   requests=dict(self.launcher_requests)),
            PodSet(name="worker", count=self.worker_count,
                   requests=dict(self.worker_requests)),
        ]
