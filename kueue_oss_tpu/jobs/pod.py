"""Plain Pod and pod-group integrations.

Reference parity: pkg/controller/jobs/pod/pod_controller.go — a single
gated pod is a one-pod workload; pods sharing the pod-group label + total
count annotation form a composable group whose podsets are the distinct
pod template shapes (roles).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@integration_manager.register
@dataclass
class PlainPod(BaseJob):
    kind = "Pod"

    requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=1, requests=dict(self.requests))]


@dataclass
class PodGroupRole:
    """Pods of one template shape within a group."""

    name: str
    count: int = 1
    requests: dict[str, int] = field(default_factory=dict)


@integration_manager.register
@dataclass
class PodGroup(BaseJob):
    """An assembled pod group (kueue.x-k8s.io/pod-group-name label +
    pod-group-total-count annotation on the reference)."""

    kind = "PodGroup"

    roles: list[PodGroupRole] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=r.name, count=r.count,
                       requests=dict(r.requests)) for r in self.roles]
