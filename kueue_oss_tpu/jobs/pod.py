"""Plain Pod and pod-group integrations.

Reference parity: pkg/controller/jobs/pod/pod_controller.go (2191 LoC) —
the deepest integration in the reference:

- a single gated pod is a one-pod workload; the scheduling gate
  (kueue.x-k8s.io/admission) is removed when the workload admits;
- pods sharing the pod-group label (kueue.x-k8s.io/pod-group-name) with
  a total-count annotation form a COMPOSABLE group: the workload's
  podsets are the group's distinct pod shapes (roles), assembled once
  every expected pod has been observed (ConstructComposableWorkload);
- excess pods beyond the declared total are excluded from the workload
  (newest first, the reference's ExcessPods handling);
- a Failed pod can be REPLACED by a new pod of the same shape; the
  replacement inherits the group's admission and is ungated immediately
  (pod_controller.go replacement path);
- finished pods of a running group become RECLAIMABLE: their quota share
  is released through workload.status.reclaimablePods
  (JobWithReclaimablePods);
- the group finishes when enough pods have succeeded (total-count),
  or fails once every seat is terminal with no replacement pending.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager

#: reference label/annotation/gate names (pod_controller.go constants)
POD_GROUP_LABEL = "kueue.x-k8s.io/pod-group-name"
POD_GROUP_TOTAL_ANNOTATION = "kueue.x-k8s.io/pod-group-total-count"
ADMISSION_GATE = "kueue.x-k8s.io/admission"
#: finalizer kueue places on managed pods so quota accounting survives
#: deletion (pod_controller.go PodFinalizer)
KUEUE_FINALIZER = "kueue.x-k8s.io/managed"
#: opt-in annotation for FailureRecoveryPolicy force-deletion
#: (constants.go SafeToForcefullyDeleteAnnotationKey)
SAFE_TO_FORCE_DELETE_ANNOTATION = "kueue.x-k8s.io/safe-to-forcefully-delete"
MANAGED_LABEL = "kueue.x-k8s.io/managed"
RETRIABLE_IN_GROUP_ANNOTATION = "kueue.x-k8s.io/retriable-in-group"
#: TAS topology request annotations (pod_webhook.go validateTopologyRequest)
REQUIRED_TOPOLOGY_ANNOTATION = "kueue.x-k8s.io/podset-required-topology"
PREFERRED_TOPOLOGY_ANNOTATION = "kueue.x-k8s.io/podset-preferred-topology"

PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"


@integration_manager.register
@dataclass
class PlainPod(BaseJob):
    kind = "Pod"

    requests: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name="main", count=1, requests=dict(self.requests))]


@dataclass
class Pod:
    """An observed pod under kueue management (single or group member)."""

    name: str
    namespace: str = "default"
    queue_name: str = ""
    requests: dict[str, int] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    #: scheduling gates present on the pod; managed pods are created with
    #: the admission gate (the webhook injects it, pod webhook parity)
    scheduling_gates: list[str] = field(
        default_factory=lambda: [ADMISSION_GATE])
    phase: str = PENDING
    priority: int = 0
    creation_time: float = 0.0
    #: kueue's finalizer protocol: a managed pod keeps accounting alive
    #: across deletion until the controller releases it
    finalizers: list[str] = field(default_factory=list)
    #: set when deletion was requested; the pod is TERMINATING until its
    #: finalizers clear (pod_controller.go DeletionTimestamp handling)
    deletion_timestamp: Optional[float] = None
    deletion_grace_period_s: float = 30.0
    #: optimistic-concurrency token, bumped by every upsert; the strict
    #: finalizer patch (RemoveFinalizersWithStrictPatch) preconditions
    #: on it
    resource_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def terminating(self) -> bool:
        return self.deletion_timestamp is not None

    def active(self, now: float) -> bool:
        """IsActive (pod_controller.go:404-434): Running, not counted
        once stuck terminating past its grace period — and, under
        FastQuotaReleaseInPodIntegration, not counted the moment
        deletion begins."""
        from kueue_oss_tpu import features

        if self.phase != RUNNING:
            return False
        if self.deletion_timestamp is not None:
            if features.enabled("FastQuotaReleaseInPodIntegration"):
                return False
            if now - self.deletion_timestamp > self.deletion_grace_period_s:
                return False  # stuck terminating: free the quota
        return True

    @property
    def group_name(self) -> Optional[str]:
        return self.labels.get(POD_GROUP_LABEL)

    @property
    def group_total(self) -> Optional[int]:
        v = self.annotations.get(POD_GROUP_TOTAL_ANNOTATION)
        return int(v) if v is not None else None

    @property
    def gated(self) -> bool:
        return ADMISSION_GATE in self.scheduling_gates

    def ungate(self) -> None:
        if ADMISSION_GATE in self.scheduling_gates:
            self.scheduling_gates.remove(ADMISSION_GATE)
            # the pod starts running under kueue management: pin it so
            # quota accounting survives deletion (finalizer protocol)
            if KUEUE_FINALIZER not in self.finalizers:
                self.finalizers.append(KUEUE_FINALIZER)

    @property
    def terminal(self) -> bool:
        return self.phase in (SUCCEEDED, FAILED)

    def shape_key(self) -> tuple:
        """Role identity: pods with equal requests share a podset
        (the reference hashes the pod template)."""
        return tuple(sorted(self.requests.items()))


@dataclass
class PodGroupRole:
    """Pods of one template shape within a group."""

    name: str
    count: int = 1
    requests: dict[str, int] = field(default_factory=dict)


@integration_manager.register
@dataclass
class PodGroup(BaseJob):
    """An assembled pod group (composable workload).

    Built by the PodGroupController from observed member pods; implements
    the optional JobWithReclaimablePods interface via succeeded-pod
    counts per role.
    """

    kind = "PodGroup"

    roles: list[PodGroupRole] = field(default_factory=list)
    total_count: int = 0
    #: role name -> pods already succeeded (reclaimable)
    succeeded_by_role: dict[str, int] = field(default_factory=dict)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=r.name, count=r.count,
                       requests=dict(r.requests)) for r in self.roles]

    def reclaimable_pods(self) -> dict[str, int]:
        return dict(self.succeeded_by_role)


class PodGroupController:
    """Assembles observed pods into workloads and drives their lifecycle.

    The reconcile pass mirrors pod_controller.go Reconcile: singles get a
    one-pod workload; groups assemble once fully observed; admission
    ungates members; failures admit replacements; successes reclaim
    quota; total success finishes the group.
    """

    def __init__(self, store, scheduler, reconciler,
                 stuck_termination_timeout_s: float = 300.0) -> None:
        self.store = store
        self.scheduler = scheduler
        self.reconciler = reconciler
        self.pods: dict[str, Pod] = {}
        #: (namespace, group) -> PodGroup job driven through the reconciler
        self._groups: dict[tuple[str, str], PodGroup] = {}
        #: pods excluded as excess (observed beyond the declared total)
        self.excess_pods: set[str] = set()
        #: FailureRecoveryPolicy: terminating pods stuck past this are
        #: force-deleted when they opted in via the
        #: safe-to-forcefully-delete annotation
        self.stuck_termination_timeout_s = stuck_termination_timeout_s

    # -- pod lifecycle -----------------------------------------------------

    @staticmethod
    def validate_pod(pod: Pod) -> list[str]:
        """Pod admission webhook (pod_webhook.go validateCommon):
        managed-label value, group-name label / group-total annotation
        both-or-neither with a positive-int total and an RFC-1123 group
        name, and topology required/preferred mutual exclusion."""
        import re as _re

        errs: list[str] = []
        managed = pod.labels.get(MANAGED_LABEL)
        if managed is not None and managed != "true":
            errs.append(f"labels[{MANAGED_LABEL}]: managed label value "
                        "can only be 'true'")
        group = pod.group_name
        total_raw = pod.annotations.get(POD_GROUP_TOTAL_ANNOTATION)
        if group is None and total_raw is not None:
            errs.append(
                f"labels[{POD_GROUP_LABEL}]: both the "
                f"'{POD_GROUP_TOTAL_ANNOTATION}' annotation and the "
                f"'{POD_GROUP_LABEL}' label should be set")
        if group is not None:
            if not _re.match(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$", group):
                errs.append(f"labels[{POD_GROUP_LABEL}]: {group!r} is "
                            "not a valid RFC-1123 name")
            if total_raw is None:
                errs.append(
                    f"annotations[{POD_GROUP_TOTAL_ANNOTATION}]: both "
                    f"the '{POD_GROUP_TOTAL_ANNOTATION}' annotation and "
                    f"the '{POD_GROUP_LABEL}' label should be set")
        if total_raw is not None:
            try:
                if int(total_raw) <= 0:
                    errs.append(
                        f"annotations[{POD_GROUP_TOTAL_ANNOTATION}]: "
                        "must be a positive integer")
            except ValueError:
                errs.append(
                    f"annotations[{POD_GROUP_TOTAL_ANNOTATION}]: "
                    f"{total_raw!r} is not an integer")
        if (pod.annotations.get(REQUIRED_TOPOLOGY_ANNOTATION)
                and pod.annotations.get(PREFERRED_TOPOLOGY_ANNOTATION)):
            errs.append(
                f"annotations[{REQUIRED_TOPOLOGY_ANNOTATION}]: required "
                "and preferred topology are mutually exclusive")
        from kueue_oss_tpu.jobframework.webhook import is_qualified_name

        for ann in (REQUIRED_TOPOLOGY_ANNOTATION,
                    PREFERRED_TOPOLOGY_ANNOTATION):
            val = pod.annotations.get(ann)
            if val and not is_qualified_name(val):
                errs.append(f"annotations[{ann}]: {val!r} is not a "
                            "valid label name")
        return errs

    @staticmethod
    def validate_pod_update(old: Pod, new: Pod) -> list[str]:
        """pod_webhook.go ValidateUpdate: an unretriable pod group
        cannot be converted to retriable, and group membership is
        immutable."""
        errs = PodGroupController.validate_pod(new)
        old_r = old.annotations.get(RETRIABLE_IN_GROUP_ANNOTATION)
        new_r = new.annotations.get(RETRIABLE_IN_GROUP_ANNOTATION)
        if (new.group_name is not None and old_r == "false"
                and new_r != "false"):
            errs.append(
                f"annotations[{RETRIABLE_IN_GROUP_ANNOTATION}]: "
                "unretriable pod group can't be converted to retriable")
        if old.group_name != new.group_name:
            errs.append(f"labels[{POD_GROUP_LABEL}]: immutable")
        return errs

    def upsert_pod(self, pod: Pod) -> None:
        from kueue_oss_tpu import features

        pod.resource_version += 1
        self.pods[pod.key] = pod
        # finalizer protocol: kueue pins managed pods so quota accounting
        # survives deletion (pod_controller.go PodFinalizer). A pod still
        # gated by a suspended parent skips it — there is nothing to
        # account for yet (SkipFinalizersForPodsSuspendedByParent, GA).
        skip = (pod.gated
                and features.enabled(
                    "SkipFinalizersForPodsSuspendedByParent"))
        if not skip and KUEUE_FINALIZER not in pod.finalizers:
            pod.finalizers.append(KUEUE_FINALIZER)

    def delete_pod(self, key: str, now: float = 0.0) -> None:
        """Deletion request: a finalized pod only becomes TERMINATING —
        it stays tracked (and its seat accounted) until the controller
        releases the finalizer in reconcile; an unfinalized pod goes
        immediately."""
        pod = self.pods.get(key)
        if pod is None:
            return
        if pod.finalizers:
            if pod.deletion_timestamp is None:
                pod.deletion_timestamp = now
            if pod.group_name is not None and pod.phase != SUCCEEDED:
                pod.phase = FAILED  # seat vacated; replacement path
            return
        self._remove_pod(pod, now)

    def _remove_pod(self, pod: Pod, now: float) -> None:
        self.pods.pop(pod.key, None)
        self.excess_pods.discard(pod.key)
        if pod.group_name is None:
            job = self.reconciler.jobs.get(("Pod", pod.key))
            if job is not None:
                self.reconciler.delete_job(job, now=now)
            return
        # A deleted group member permanently vacates its seat: treat it
        # like a Failed pod so the group keeps its failure/replacement
        # semantics instead of waiting for a pod that will never return
        # (pod_controller.go handles deletion through the same
        # replacement path).
        if pod.phase not in (SUCCEEDED,):
            pod.phase = FAILED

    def _finalize_terminating(self, now: float) -> None:
        """Release finalizers of terminating pods whose accounting is
        settled (terminal phase, excess, or owning job finished), and
        force-delete stuck terminators that opted in under
        FailureRecoveryPolicy (pod_termination_controller.go:60-263)."""
        from kueue_oss_tpu import features

        frp = features.enabled("FailureRecoveryPolicy")
        for pod in list(self.pods.values()):
            if not pod.terminating:
                continue
            # the strict patch preconditions on the version observed at
            # the START of this pod's evaluation — edits landing while
            # settled/force are computed must fail the patch
            observed_rv = pod.resource_version
            settled = pod.terminal or pod.key in self.excess_pods
            if not settled and pod.group_name is not None:
                job = self._groups.get((pod.namespace, pod.group_name))
                settled = job is not None and job.is_finished
            stuck = (now - pod.deletion_timestamp
                     >= self.stuck_termination_timeout_s)
            force = (frp and stuck
                     and pod.annotations.get(
                         SAFE_TO_FORCE_DELETE_ANNOTATION) == "true")
            if settled or force:
                if self.remove_finalizer(pod, observed_rv):
                    if not pod.finalizers:
                        self._remove_pod(pod, now)

    def remove_finalizer(self, pod: Pod,
                         observed_rv: Optional[int] = None) -> bool:
        """Release kueue's finalizer.

        RemoveFinalizersWithStrictPatch (pod_controller.go:924): with the
        gate on, the removal is a resourceVersion-preconditioned patch —
        a pod modified since `observed_rv` fails the patch and the caller
        retries on the next reconcile (the blind merge patch the gate
        replaces could clobber a concurrent writer's finalizer edits).
        """
        from kueue_oss_tpu import features

        if (features.enabled("RemoveFinalizersWithStrictPatch")
                and observed_rv is not None
                and pod.resource_version != observed_rv):
            return False
        pod.finalizers = [f for f in pod.finalizers if f != KUEUE_FINALIZER]
        pod.resource_version += 1
        return True

    def mark_phase(self, key: str, phase: str) -> None:
        self.pods[key].phase = phase

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, now: float) -> None:
        from kueue_oss_tpu import features

        # a pod whose gate was removed is actually managed now: pin it
        # (the upsert-time skip only covers suspended-parent gating)
        for p in self.pods.values():
            if (not p.gated and not p.terminating
                    and KUEUE_FINALIZER not in p.finalizers):
                p.finalizers.append(KUEUE_FINALIZER)
        self._finalize_terminating(now)
        singles = [p for p in self.pods.values() if p.group_name is None]
        for pod in singles:
            self._reconcile_single(pod, now)

        groups: dict[tuple[str, str], list[Pod]] = {}
        for p in self.pods.values():
            if p.group_name is not None:
                groups.setdefault((p.namespace, p.group_name), []).append(p)
        for (ns, name), members in groups.items():
            self._reconcile_group(ns, name, members, now)
        self.reconciler.reconcile_all(now)
        # apply admission effects (ungating) after the workloads synced
        for pod in singles:
            job = self.reconciler.jobs.get(("Pod", pod.key))
            if job is not None and not job.is_suspended():
                pod.ungate()
        for (ns, name), members in groups.items():
            self._sync_group_gates(ns, name, members)

    # -- singles -----------------------------------------------------------

    def _reconcile_single(self, pod: Pod, now: float) -> None:
        key = ("Pod", pod.key)
        job = self.reconciler.jobs.get(key)
        if job is None:
            job = PlainPod(
                name=pod.name, namespace=pod.namespace,
                queue_name=pod.queue_name, requests=dict(pod.requests),
                priority=pod.priority, creation_time=pod.creation_time)
            self.reconciler.upsert_job(job)
        if pod.phase == SUCCEEDED:
            job.mark_finished(success=True)
        elif pod.phase == FAILED:
            job.mark_finished(success=False, message="pod failed")
        elif pod.phase == RUNNING:
            job.mark_running(ready=True)

    # -- groups ------------------------------------------------------------

    def _group_members(self, members: list[Pod]) -> tuple[list[Pod], int]:
        """Seated members (excess excluded, oldest first) + total count."""
        total = 0
        for p in members:
            if p.group_total:
                total = max(total, p.group_total)
        members = sorted(members,
                         key=lambda p: (p.creation_time, p.name))
        # a failed pod keeps its seat only until a replacement arrives:
        # seat live/succeeded pods first, failed ones fill what remains
        alive = [p for p in members if p.phase != FAILED]
        failed = [p for p in members if p.phase == FAILED]
        seated = (alive + failed)[:total] if total else alive + failed
        seated_keys = {p.key for p in seated}
        for p in members:
            if p.key in seated_keys:
                self.excess_pods.discard(p.key)
            else:
                self.excess_pods.add(p.key)
        return seated, total

    def _roles(self, seated: list[Pod]) -> list[PodGroupRole]:
        by_shape: dict[tuple, PodGroupRole] = {}
        for p in seated:
            k = p.shape_key()
            if k not in by_shape:
                by_shape[k] = PodGroupRole(
                    name=f"role-{len(by_shape)}", count=0,
                    requests=dict(p.requests))
            by_shape[k].count += 1
        return list(by_shape.values())

    def _role_of(self, roles: list[PodGroupRole],
                 pod: Pod) -> Optional[str]:
        for r in roles:
            if tuple(sorted(r.requests.items())) == pod.shape_key():
                return r.name
        return None

    def _reconcile_group(self, ns: str, name: str, members: list[Pod],
                         now: float) -> None:
        seated, total = self._group_members(members)
        if not total or len(seated) < total:
            # group not fully observed yet (the reference requeues until
            # assembly completes)
            return
        job = self._groups.get((ns, name))
        if job is None:
            oldest = min(p.creation_time for p in seated)
            job = PodGroup(
                name=name, namespace=ns,
                queue_name=next(p.queue_name for p in seated),
                priority=max(p.priority for p in seated),
                roles=self._roles(seated), total_count=total,
                creation_time=oldest)
            self._groups[(ns, name)] = job
            self.reconciler.upsert_job(job)

        # reclaimable + finish accounting — attribution uses the FROZEN
        # role set from assembly time (the admitted workload's podsets),
        # never a re-derived seating order
        succeeded: dict[str, int] = {}
        n_succeeded = 0
        for p in seated:
            if p.phase == SUCCEEDED:
                role = self._role_of(job.roles, p)
                if role:
                    succeeded[role] = succeeded.get(role, 0) + 1
                n_succeeded += 1
        job.succeeded_by_role = succeeded
        if n_succeeded >= total:
            job.mark_finished(success=True)
        elif all(p.terminal for p in seated):
            # every seat terminal without enough successes; the group
            # failed unless a replacement pod is still on its way
            pending_replacement = any(
                not p.terminal for p in members
                if p.key in self.excess_pods)
            if not pending_replacement:
                job.mark_finished(success=False,
                                  message="pod group failed")
        elif any(p.phase == RUNNING for p in seated):
            # activity honors termination state: a terminating pod stops
            # counting under FastQuotaReleaseInPodIntegration (or once
            # stuck past its grace period), releasing the workload's
            # active claim (pod_controller.go IsActive)
            job.active_pods = sum(1 for p in seated if p.active(now))
            job.ready_pods = sum(1 for p in seated
                                 if p.phase in (RUNNING, SUCCEEDED))

    def _sync_group_gates(self, ns: str, name: str,
                          members: list[Pod]) -> None:
        """Ungate member pods of admitted groups — including replacement
        pods that arrived after admission (pod_controller.go ungating +
        replacement path)."""
        job = self._groups.get((ns, name))
        if job is None or job.is_suspended():
            return
        for p in members:
            if p.key not in self.excess_pods and not p.terminal:
                p.ungate()
