"""Ray integrations: RayJob, RayCluster, RayService.

Reference parity:
- pkg/controller/jobs/raycluster/common.go BuildPodSets (:55-100): head
  podset (count 1) + one podset per worker group with
  count = replicas * numOfHosts (:78-83);
- common.go UpdatePodSets (:102-160): with in-tree autoscaling enabled,
  worker counts track the LIVE cluster's replicas (the autoscaler owns
  the replica count; kueue admits what is actually running), and
  autoscaling without workload slices is rejected at the webhook
  (:208-216, raycluster_webhook.go);
- pkg/controller/jobs/rayjob/rayjob_controller.go: a submitter podset is
  appended when submissionMode=K8sJobMode (:305-330; default submitter
  requests 500m CPU / 200Mi, :276-300); jobs with a clusterSelector are
  skipped — not managed by kueue (:155-159); Finished maps
  JobDeploymentStatus Complete/Failed (:246-251); PodsReady is the
  cluster reaching Ready state (:253-255);
- pkg/controller/jobs/rayservice/rayservice_controller.go: podsets from
  the service's cluster spec; ready when the service is Running.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager

#: default submitter-job requests (rayjob_controller.go:276-300, the
#: kuberay default submitter template) in canonical units
DEFAULT_SUBMITTER_REQUESTS = {"cpu": 500, "memory": 200 * 1024 * 1024}

#: RayJob submission modes (rayv1.JobSubmissionMode)
K8S_JOB_MODE = "K8sJobMode"
HTTP_MODE = "HTTPMode"


@dataclass
class WorkerGroup:
    name: str
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    #: TPU/multi-host groups run numOfHosts pods per replica
    num_of_hosts: int = 1
    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    #: autoscaler-owned live replica count (None = not yet scaled)
    live_replicas: Optional[int] = None
    topology_request: Optional[PodSetTopologyRequest] = None

    def count(self, autoscaling: bool) -> int:
        """common.go:78-83 + UpdatePodSets:141-149."""
        replicas = self.replicas
        if autoscaling and self.live_replicas is not None:
            # the autoscaler owns the count, but never beyond the
            # group's declared bounds
            replicas = self.live_replicas
            if self.max_replicas is not None:
                replicas = min(replicas, self.max_replicas)
            if self.min_replicas is not None:
                replicas = max(replicas, self.min_replicas)
        return replicas * max(self.num_of_hosts, 1)


@dataclass
class _RayBase(BaseJob):
    head_requests: dict[str, int] = field(default_factory=dict)
    worker_groups: list[WorkerGroup] = field(default_factory=list)
    #: rayClusterSpec.enableInTreeAutoscaling
    autoscaling: bool = False
    #: live RayCluster state ("", "Ready", ...)
    cluster_state: str = ""

    def cluster_pod_sets(self) -> list[PodSet]:
        podsets = [PodSet(name="head", count=1,
                          requests=dict(self.head_requests))]
        podsets.extend(PodSet(
            name=wg.name, count=wg.count(self.autoscaling),
            requests=dict(wg.requests),
            topology_request=wg.topology_request)
            for wg in self.worker_groups)
        return podsets

    def validate(self) -> list[str]:
        """raycluster_webhook.go:135-163: in-tree autoscaling only for
        elastic jobs (ElasticJobsViaWorkloadSlices + opted-in); at most
        7 worker groups (8 podsets minus the head); no group may take
        the reserved head name."""
        from kueue_oss_tpu import features, workloadslicing

        errs = []
        if self.autoscaling and not (
                features.enabled("ElasticJobsViaWorkloadSlices")
                and workloadslicing.enabled(self)):
            errs.append(
                "enableInTreeAutoscaling: a kueue managed job can use "
                "autoscaling only when the ElasticJobsViaWorkloadSlices "
                "feature gate is on and the job is an elastic job")
        if len(self.worker_groups) > 7:
            errs.append(f"workerGroupSpecs: too many worker groups "
                        f"({len(self.worker_groups)} > 7)")
        for wg in self.worker_groups:
            if wg.name == "head":
                errs.append('workerGroupSpecs: "head" is reserved for '
                            "the head group")
        return errs

    def pod_sets(self) -> list[PodSet]:
        return self.cluster_pod_sets()

    def pods_ready(self) -> bool:
        return self.cluster_state == "Ready"

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        self.cluster_state = "Ready" if ready else ""

    def do_suspend(self) -> None:
        super().do_suspend()
        self.cluster_state = ""


@integration_manager.register
@dataclass
class RayJob(_RayBase):
    kind = "RayJob"

    submission_mode: str = HTTP_MODE
    submitter_requests: dict[str, int] = field(default_factory=dict)
    #: non-empty = references an existing cluster; kueue skips the job
    #: (rayjob_controller.go:155-159 Skip())
    cluster_selector: dict[str, str] = field(default_factory=dict)
    #: live status (rayv1.JobDeploymentStatus)
    deployment_status: str = "New"
    job_status: str = ""
    #: rayjob spec.shutdownAfterJobFinishes
    shutdown_after_job_finishes: bool = True

    def skip(self) -> bool:
        return bool(self.cluster_selector)

    def validate(self) -> list[str]:
        """rayjob_webhook.go:110-140 on top of the cluster rules; both
        rules apply only to kueue-managed jobs (a cluster_selector job
        is skipped entirely, so its cluster lifecycle is not ours)."""
        errs = super().validate()
        if self.queue_name and self.cluster_selector:
            errs.append("clusterSelector: a kueue managed job should "
                        "not use an existing cluster")
        # independent of the clusterSelector rule: the reference rayjob
        # webhook reports both violations when both are present
        if self.queue_name and not self.shutdown_after_job_finishes:
            errs.append("shutdownAfterJobFinishes: a kueue managed job "
                        "should delete the cluster after finishing")
        return errs

    def pod_sets(self) -> list[PodSet]:
        podsets = self.cluster_pod_sets()
        if self.submission_mode == K8S_JOB_MODE:
            podsets.append(PodSet(
                name="submitter", count=1,
                requests=dict(self.submitter_requests
                              or DEFAULT_SUBMITTER_REQUESTS)))
        return podsets

    def finished(self) -> tuple[str, bool, bool]:
        if self.deployment_status in ("Complete", "Failed"):
            return (self.finish_message,
                    self.job_status == "SUCCEEDED", True)
        return super().finished()

    def is_active(self) -> bool:
        # rayjob_controller.go:146-149: no pods while Suspended or New
        return self.deployment_status not in ("Suspended", "New")


@integration_manager.register
@dataclass
class RayCluster(_RayBase):
    kind = "RayCluster"

    def finished(self) -> tuple[str, bool, bool]:
        # a bare cluster never self-terminates (raycluster_controller.go
        # Finished always false until deletion)
        return self.finish_message, self.finish_success, self.is_finished


@integration_manager.register
@dataclass
class RayService(_RayBase):
    kind = "RayService"

    #: live status (rayservice ServiceStatus)
    service_status: str = ""

    def pods_ready(self) -> bool:
        return self.service_status == "Running" or super().pods_ready()
