"""Ray integrations: RayJob, RayCluster, RayService.

Reference parity: pkg/controller/jobs/{rayjob,raycluster,rayservice} —
head podset + one podset per worker group.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@dataclass
class WorkerGroup:
    name: str
    replicas: int = 1
    requests: dict[str, int] = field(default_factory=dict)


@dataclass
class _RayBase(BaseJob):
    head_requests: dict[str, int] = field(default_factory=dict)
    worker_groups: list[WorkerGroup] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        podsets = [PodSet(name="head", count=1,
                          requests=dict(self.head_requests))]
        podsets.extend(PodSet(name=wg.name, count=wg.replicas,
                              requests=dict(wg.requests))
                       for wg in self.worker_groups)
        return podsets


@integration_manager.register
@dataclass
class RayJob(_RayBase):
    kind = "RayJob"


@integration_manager.register
@dataclass
class RayCluster(_RayBase):
    kind = "RayCluster"


@integration_manager.register
@dataclass
class RayService(_RayBase):
    kind = "RayService"
