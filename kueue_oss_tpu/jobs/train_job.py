"""TrainJob (kubeflow trainer v2) integration.

Reference parity: pkg/controller/jobs/trainjob — podsets derived from the
training runtime's pod-group shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager
from kueue_oss_tpu.jobs.kubeflow import ReplicaSpec


@integration_manager.register
@dataclass
class TrainJob(BaseJob):
    kind = "TrainJob"

    #: pod groups from the referenced TrainingRuntime
    replica_specs: list[ReplicaSpec] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=rs.role.lower(), count=rs.replicas,
                       requests=dict(rs.requests))
                for rs in self.replica_specs]
