"""TrainJob (kubeflow trainer v2) integration.

Reference parity: pkg/controller/jobs/trainjob/trainjob_controller.go
(430 LoC) — a TrainJob's podsets come from its RUNTIME, not its own
spec: the runtimeRef resolves against the (Cluster)TrainingRuntime
registry (:169-176), the runtime's template materializes a child JobSet,
and the TrainJob's overrides (trainer.numNodes → the trainer job's
parallelism, per-node resources) are patched in before podsets are
derived from the resulting replicated jobs.

Modeled here: `TrainingRuntime` templates register in a process-wide
registry (the Runtimes() analog); a `TrainJob` with a `runtime_ref`
derives its replica specs from the template with num_nodes /
resources-per-node overrides applied to the trainer step. Direct
`replica_specs` (no runtime) stay supported for ad-hoc jobs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager
from kueue_oss_tpu.jobs.kubeflow import ReplicaSpec

#: the runtime step that numNodes/resourcesPerNode overrides target
TRAINER_STEP = "Node"


@dataclass
class TrainingRuntime:
    """A (Cluster)TrainingRuntime template: ordered steps, each a
    replica shape (e.g. dataset-initializer, model-initializer, Node)."""

    name: str
    steps: list[ReplicaSpec] = field(default_factory=list)


class RuntimeRegistry:
    """kftrainerruntimecore.Runtimes() analog."""

    def __init__(self) -> None:
        self._runtimes: dict[str, TrainingRuntime] = {}

    def register(self, runtime: TrainingRuntime) -> TrainingRuntime:
        self._runtimes[runtime.name] = runtime
        return runtime

    def get(self, name: str) -> Optional[TrainingRuntime]:
        return self._runtimes.get(name)


runtime_registry = RuntimeRegistry()


@integration_manager.register
@dataclass
class TrainJob(BaseJob):
    kind = "TrainJob"

    #: direct pod groups (used when no runtime_ref)
    replica_specs: list[ReplicaSpec] = field(default_factory=list)
    #: name of a registered TrainingRuntime
    runtime_ref: Optional[str] = None
    #: spec.trainer.numNodes override onto the runtime's trainer step
    num_nodes: Optional[int] = None
    #: spec.trainer.resourcesPerNode override
    resources_per_node: Optional[dict[str, int]] = None

    def resolved_replica_specs(self) -> list[ReplicaSpec]:
        if self.runtime_ref is None:
            return list(self.replica_specs)
        runtime = runtime_registry.get(self.runtime_ref)
        if runtime is None:
            raise ValueError(
                f"TrainJob {self.key}: unknown runtime {self.runtime_ref!r}")
        out = []
        for step in runtime.steps:
            replicas = step.replicas
            requests = dict(step.requests)
            if step.role == TRAINER_STEP:
                if self.num_nodes is not None:
                    replicas = self.num_nodes
                if self.resources_per_node is not None:
                    requests = dict(self.resources_per_node)
            out.append(ReplicaSpec(
                role=step.role, replicas=replicas, requests=requests,
                priority_class=step.priority_class,
                node_selector=dict(step.node_selector),
                tolerations=list(step.tolerations),
                topology_request=step.topology_request))
        return out

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(name=rs.role.lower(), count=rs.replicas,
                       requests=dict(rs.requests),
                       node_selector=dict(rs.node_selector),
                       tolerations=list(rs.tolerations),
                       topology_request=rs.topology_request)
                for rs in self.resolved_replica_specs()]
