"""JobSet integration.

Reference parity: pkg/controller/jobs/jobset/jobset_controller.go — one
podset per replicated job, count = replicas * parallelism.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_oss_tpu.jobframework.interface import BaseJob
from kueue_oss_tpu.jobframework.registry import integration_manager


@dataclass
class ReplicatedJob:
    name: str
    replicas: int = 1
    parallelism: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None


@integration_manager.register
@dataclass
class JobSet(BaseJob):
    kind = "JobSet"

    replicated_jobs: list[ReplicatedJob] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(
            name=rj.name,
            count=rj.replicas * rj.parallelism,
            requests=dict(rj.requests),
            topology_request=rj.topology_request,
        ) for rj in self.replicated_jobs]
