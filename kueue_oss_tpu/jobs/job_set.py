"""JobSet integration.

Reference parity: pkg/controller/jobs/jobset/jobset_controller.go (245
LoC) — one podset per replicated job, count = replicas * parallelism;
PodsReady when every replicated job's ready+succeeded replicas reach its
declared replicas (:178-188); ReclaimablePods releases whole replicated
jobs as they succeed (:190-205); Finished follows the
JobSetCompleted/JobSetFailed conditions (:168-176); RunWithPodSetsInfo
merges admission node selectors per replicated job template.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, PodSetTopologyRequest
from kueue_oss_tpu.jobframework.interface import BaseJob, PodSetInfo
from kueue_oss_tpu.jobframework.registry import integration_manager


@dataclass
class ReplicatedJob:
    name: str
    replicas: int = 1
    parallelism: int = 1
    requests: dict[str, int] = field(default_factory=dict)
    topology_request: Optional[PodSetTopologyRequest] = None
    node_selector: dict[str, str] = field(default_factory=dict)
    #: live status (jobset ReplicatedJobStatus)
    ready_replicas: int = 0
    succeeded_replicas: int = 0


@integration_manager.register
@dataclass
class JobSet(BaseJob):
    kind = "JobSet"

    replicated_jobs: list[ReplicatedJob] = field(default_factory=list)

    def pod_sets(self) -> list[PodSet]:
        return [PodSet(
            name=rj.name,
            count=rj.replicas * rj.parallelism,
            requests=dict(rj.requests),
            topology_request=rj.topology_request,
            node_selector=dict(rj.node_selector),
        ) for rj in self.replicated_jobs]

    def validate(self) -> list[str]:
        """jobset_webhook.go: replicated-job names must be unique and
        replicas positive (duplicate names would collapse podsets)."""
        errs = []
        seen: set[str] = set()
        for rj in self.replicated_jobs:
            if rj.name in seen:
                errs.append(f"replicatedJobs: duplicate name {rj.name!r}")
            seen.add(rj.name)
            if rj.replicas < 1:
                errs.append(f"replicatedJobs {rj.name}: replicas must "
                            "be >= 1")
        return errs

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        if len(infos) != len(self.replicated_jobs):
            raise ValueError(
                f"expected {len(self.replicated_jobs)} podset infos, "
                f"got {len(infos)}")
        super().run_with_podsets_info(infos)
        # keep the FIRST (pristine) selectors across re-injections (the
        # elastic slice takeover calls this again while running)
        if getattr(self, "_saved_selectors", None) is None:
            self._saved_selectors = [dict(rj.node_selector)
                                     for rj in self.replicated_jobs]
        for rj, info in zip(self.replicated_jobs, infos):
            rj.node_selector.update(info.node_selector)

    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        changed = super().restore_podsets_info(infos)
        saved = getattr(self, "_saved_selectors", None)
        if saved:
            for rj, sel in zip(self.replicated_jobs, saved):
                rj.node_selector = dict(sel)
            self._saved_selectors = None
        return changed

    def pods_ready(self) -> bool:
        """jobset_controller.go:178-188."""
        return all(rj.ready_replicas + rj.succeeded_replicas >= rj.replicas
                   for rj in self.replicated_jobs)

    def reclaimable_pods(self) -> dict[str, int]:
        """jobset_controller.go:190-205: succeeded replicas of a
        replicated job free their parallelism-sized share."""
        out = {}
        for rj in self.replicated_jobs:
            if 0 < rj.succeeded_replicas <= rj.replicas:
                out[rj.name] = rj.succeeded_replicas * rj.parallelism
        return out

    def mark_running(self, ready: bool = True) -> None:
        super().mark_running(ready=ready)
        for rj in self.replicated_jobs:
            rj.ready_replicas = rj.replicas if ready else 0

    def do_suspend(self) -> None:
        super().do_suspend()
        for rj in self.replicated_jobs:
            rj.ready_replicas = 0
