"""CRD defaulting and validation webhooks.

Reference parity: pkg/webhooks (webhooks.go:28-50 registers ClusterQueue,
Cohort, ResourceFlavor, LocalQueue and Workload webhooks). Each validator
returns a list of error strings (empty = valid), mirroring field.ErrorList;
`admit_*` helpers raise ValidationError on non-empty results so callers can
use them as an enforcing gate in front of the store.
"""

from __future__ import annotations

import re
from typing import Optional

from kueue_oss_tpu.api.types import (
    ClusterQueue,
    Cohort,
    FlavorFungibilityPolicy,
    LocalQueue,
    PreemptionPolicyValue,
    ResourceFlavor,
    Workload,
    iter_quotas,
)
from kueue_oss_tpu.core.store import Store

#: RFC-1123 label, same constraint the apiserver puts on CRD names
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_MAX_NAME_LEN = 253


class ValidationError(ValueError):
    def __init__(self, errors: list[str]) -> None:
        super().__init__("; ".join(errors))
        self.errors = errors


def _check_name(name: str, what: str) -> list[str]:
    if not name:
        return [f"{what}: name is required"]
    if len(name) > _MAX_NAME_LEN:
        return [f"{what} {name!r}: name exceeds {_MAX_NAME_LEN} chars"]
    if not _NAME_RE.match(name):
        return [f"{what} {name!r}: not a valid RFC-1123 name"]
    return []


# ---------------------------------------------------------------------------
# ClusterQueue (reference: pkg/webhooks/clusterqueue_webhook.go)
# ---------------------------------------------------------------------------

_WITHIN_CQ = {PreemptionPolicyValue.NEVER,
              PreemptionPolicyValue.LOWER_PRIORITY,
              PreemptionPolicyValue.LOWER_OR_NEWER_EQUAL_PRIORITY}
_RECLAIM = {PreemptionPolicyValue.NEVER,
            PreemptionPolicyValue.LOWER_PRIORITY,
            PreemptionPolicyValue.ANY}
_BORROW_WITHIN = {PreemptionPolicyValue.NEVER,
                  PreemptionPolicyValue.LOWER_PRIORITY}
_FUNGIBILITY = {FlavorFungibilityPolicy.BORROW,
                FlavorFungibilityPolicy.PREEMPT,
                FlavorFungibilityPolicy.TRY_NEXT_FLAVOR}


def validate_cluster_queue(cq: ClusterQueue) -> list[str]:
    return (_validate_cluster_queue_core(cq)
            + _validate_ac_on_flavors(cq))


def _validate_cluster_queue_core(cq: ClusterQueue) -> list[str]:
    """Everything except the admissionChecksStrategy.onFlavors check,
    whose update path has gate-dependent legacy exemptions."""
    errs = _check_name(cq.name, "clusterQueue")
    for i, rg in enumerate(cq.resource_groups):
        covered = set(rg.covered_resources)
        if not covered:
            errs.append(f"resourceGroups[{i}]: coveredResources is required")
        if not rg.flavors:
            errs.append(f"resourceGroups[{i}]: at least one flavor required")
        for fq in rg.flavors:
            have = {rq.name for rq in fq.resources}
            if have != covered:
                errs.append(
                    f"resourceGroups[{i}] flavor {fq.name}: resources "
                    f"{sorted(have)} must match coveredResources "
                    f"{sorted(covered)}")
            for rq in fq.resources:
                if rq.nominal < 0:
                    errs.append(f"flavor {fq.name}/{rq.name}: "
                                "nominalQuota must be >= 0")
                if rq.borrowing_limit is not None and rq.borrowing_limit < 0:
                    errs.append(f"flavor {fq.name}/{rq.name}: "
                                "borrowingLimit must be >= 0")
                if rq.lending_limit is not None:
                    if rq.lending_limit < 0:
                        errs.append(f"flavor {fq.name}/{rq.name}: "
                                    "lendingLimit must be >= 0")
                    elif rq.lending_limit > rq.nominal:
                        errs.append(f"flavor {fq.name}/{rq.name}: "
                                    "lendingLimit must be <= nominalQuota")
    # a resource may appear in only one resource group
    seen: dict[str, int] = {}
    for i, rg in enumerate(cq.resource_groups):
        for r in rg.covered_resources:
            if r in seen:
                errs.append(f"resource {r}: covered by resourceGroups "
                            f"[{seen[r]}] and [{i}]")
            seen[r] = i
    p = cq.preemption
    if p.within_cluster_queue not in _WITHIN_CQ:
        errs.append(f"preemption.withinClusterQueue: invalid value "
                    f"{p.within_cluster_queue!r}")
    if p.reclaim_within_cohort not in _RECLAIM:
        errs.append(f"preemption.reclaimWithinCohort: invalid value "
                    f"{p.reclaim_within_cohort!r}")
    if p.borrow_within_cohort.policy not in _BORROW_WITHIN:
        errs.append(f"preemption.borrowWithinCohort.policy: invalid value "
                    f"{p.borrow_within_cohort.policy!r}")
    if (p.borrow_within_cohort.policy == PreemptionPolicyValue.NEVER
            and p.borrow_within_cohort.max_priority_threshold is not None):
        errs.append("preemption.borrowWithinCohort.maxPriorityThreshold: "
                    "only allowed with policy LowerPriority")
    ff = cq.flavor_fungibility
    if ff.when_can_borrow not in _FUNGIBILITY:
        errs.append(f"flavorFungibility.whenCanBorrow: invalid value "
                    f"{ff.when_can_borrow!r}")
    if ff.when_can_preempt not in _FUNGIBILITY:
        errs.append(f"flavorFungibility.whenCanPreempt: invalid value "
                    f"{ff.when_can_preempt!r}")
    if cq.fair_sharing.weight < 0:
        errs.append("fairSharing.weight must be >= 0")
    if cq.cohort:
        errs.extend(_check_name(cq.cohort, "cohort"))
    return errs


def _cq_flavor_names(cq: ClusterQueue) -> set[str]:
    return {fq.name for rg in cq.resource_groups for fq in rg.flavors}


def _validate_ac_on_flavors(cq: ClusterQueue,
                            old: Optional[ClusterQueue] = None) -> list[str]:
    """admissionChecksStrategy onFlavors must name flavors of this CQ.

    On update with the RejectUpdatesToCQWithInvalidOnFlavors gate
    DISABLED, rules carried over unchanged from the old spec are
    exempt (legacy CQs persisted with invalid onFlavors must remain
    updatable) as long as the CQ's flavor set did not change; with the
    gate enabled every rule is validated. Reference:
    clusterqueue_webhook.go validateAdmissionCheckOnFlavorsUpdate."""
    from kueue_oss_tpu import features

    strategy = cq.admission_checks_strategy
    if strategy is None:
        return []
    valid = _cq_flavor_names(cq)
    old_rules: dict[str, frozenset] = {}
    if (old is not None
            and not features.enabled("RejectUpdatesToCQWithInvalidOnFlavors")
            and old.admission_checks_strategy is not None
            and _cq_flavor_names(old) == valid):
        old_rules = {r.name: frozenset(r.on_flavors)
                     for r in old.admission_checks_strategy.admission_checks}
    errs = []
    for i, rule in enumerate(strategy.admission_checks):
        if old_rules.get(rule.name) == frozenset(rule.on_flavors):
            continue
        for fl in rule.on_flavors:
            if fl not in valid:
                errs.append(
                    f"admissionChecksStrategy.admissionChecks[{i}]"
                    f".onFlavors: {fl!r} is not a flavor of this "
                    f"ClusterQueue (allowed: {sorted(valid)})")
    return errs


def validate_cluster_queue_update(old: ClusterQueue,
                                  new: ClusterQueue) -> list[str]:
    return (_validate_cluster_queue_core(new)
            + _validate_ac_on_flavors(new, old=old))


# ---------------------------------------------------------------------------
# Cohort (reference: pkg/webhooks/cohort_webhook.go + hierarchy cycle check)
# ---------------------------------------------------------------------------


def validate_cohort(cohort: Cohort,
                    store: Optional[Store] = None) -> list[str]:
    errs = _check_name(cohort.name, "cohort")
    if cohort.parent:
        errs.extend(_check_name(cohort.parent, "parent"))
        if cohort.parent == cohort.name:
            errs.append(f"cohort {cohort.name}: cannot be its own parent")
        elif store is not None and _would_cycle(cohort, store):
            errs.append(f"cohort {cohort.name}: parent chain forms a cycle")
    for (flavor, resource), rq in iter_quotas(cohort.resource_groups):
        if rq.nominal < 0:
            errs.append(f"cohort {cohort.name} {flavor}/{resource}: "
                        "nominalQuota must be >= 0")
    if cohort.fair_sharing.weight < 0:
        errs.append("fairSharing.weight must be >= 0")
    return errs


def _would_cycle(cohort: Cohort, store: Store) -> bool:
    """Walk the would-be parent chain (reference: hierarchy/cycle.go
    HasCycle, evaluated against the store instead of the live forest)."""
    seen = {cohort.name}
    cur = cohort.parent
    while cur:
        if cur in seen:
            return True
        seen.add(cur)
        parent = store.cohorts.get(cur)
        cur = parent.parent if parent is not None else None
    return False


# ---------------------------------------------------------------------------
# ResourceFlavor / LocalQueue
# ---------------------------------------------------------------------------


def validate_resource_flavor(rf: ResourceFlavor) -> list[str]:
    errs = _check_name(rf.name, "resourceFlavor")
    for k in rf.node_labels:
        if not k:
            errs.append("nodeLabels: empty key")
    for t in rf.node_taints:
        if not t.key:
            errs.append("nodeTaints: taint key is required")
        if t.effect not in ("NoSchedule", "PreferNoSchedule", "NoExecute"):
            errs.append(f"nodeTaints {t.key}: invalid effect {t.effect!r}")
    return errs


def validate_local_queue(lq: LocalQueue) -> list[str]:
    errs = _check_name(lq.name, "localQueue")
    errs.extend(_check_name(lq.cluster_queue, "clusterQueue"))
    return errs


def validate_local_queue_update(old: LocalQueue, new: LocalQueue) -> list[str]:
    """clusterQueue is immutable (localqueue_webhook.go ValidateUpdate)."""
    errs = validate_local_queue(new)
    if old.cluster_queue != new.cluster_queue:
        errs.append("clusterQueue is immutable")
    return errs


# ---------------------------------------------------------------------------
# Workload (reference: pkg/webhooks/workload_webhook.go)
# ---------------------------------------------------------------------------


def sanitize_podsets(wl: Workload) -> bool:
    """Deduplicate env-var entries in podset templates, keeping only the
    LAST occurrence of each name, so workload creation succeeds even
    when the spec carries duplicates (SanitizePodSets gate,
    kube_features.go:207-212). Returns True if anything changed."""
    from kueue_oss_tpu import features

    if not features.enabled("SanitizePodSets"):
        return False
    changed = False
    for ps in wl.podsets:
        if not ps.env:
            continue
        seen: set[str] = set()
        deduped: list[tuple[str, str]] = []
        for name, value in reversed(ps.env):
            if name in seen:
                continue
            seen.add(name)
            deduped.append((name, value))
        deduped.reverse()
        if deduped != ps.env:
            ps.env = deduped
            changed = True
    return changed


def default_workload(wl: Workload, store: Optional[Store] = None) -> None:
    """Defaulting: podset names, priority from WorkloadPriorityClass,
    podset-template sanitization (SanitizePodSets)."""
    for i, ps in enumerate(wl.podsets):
        if not ps.name:
            ps.name = "main" if i == 0 else f"ps{i}"
    sanitize_podsets(wl)
    if store is not None and wl.priority_class and wl.priority == 0:
        pc = store.priority_classes.get(wl.priority_class)
        if pc is not None:
            wl.priority = pc.value


def validate_workload(wl: Workload) -> list[str]:
    errs = _check_name(wl.name, "workload")
    if not wl.podsets:
        errs.append("podSets: at least one required")
    if len(wl.podsets) > 8:
        errs.append("podSets: at most 8 podsets allowed")
    names = set()
    for ps in wl.podsets:
        if ps.name in names:
            errs.append(f"podSets: duplicate name {ps.name!r}")
        names.add(ps.name)
        if ps.count < 0:
            errs.append(f"podSet {ps.name}: count must be >= 0")
        if ps.min_count is not None and not 0 < ps.min_count <= ps.count:
            errs.append(f"podSet {ps.name}: minCount must be in (0, count]")
        for r, q in ps.requests.items():
            if q < 0:
                errs.append(f"podSet {ps.name}: negative request for {r}")
        # one shared TAS topology-request validator (tas_validation.go):
        # workloads created directly get the same rules as job webhooks
        from kueue_oss_tpu import features
        from kueue_oss_tpu.jobframework.webhook import (
            validate_tas_podset_request,
        )

        if features.enabled("TopologyAwareScheduling"):
            errs.extend(validate_tas_podset_request(ps))
    return errs


def validate_workload_update(old: Workload, new: Workload) -> list[str]:
    """Podsets immutable while quota is reserved; queueName immutable
    while admitted (workload_webhook.go ValidateWorkloadUpdate)."""
    errs = validate_workload(new)
    if old.is_quota_reserved:
        old_shape = [(ps.name, ps.count, sorted(ps.requests.items()))
                     for ps in old.podsets]
        new_shape = [(ps.name, ps.count, sorted(ps.requests.items()))
                     for ps in new.podsets]
        if old_shape != new_shape:
            errs.append("podSets are immutable while quota is reserved")
        if old.queue_name != new.queue_name:
            errs.append("queueName is immutable while quota is reserved")
        if old.priority != new.priority:
            errs.append("priority is immutable while quota is reserved")
    return errs


# ---------------------------------------------------------------------------
# Enforcing helpers
# ---------------------------------------------------------------------------


def _admit(errs: list[str]) -> None:
    if errs:
        raise ValidationError(errs)


def admit_cluster_queue(cq: ClusterQueue) -> None:
    _admit(validate_cluster_queue(cq))


def admit_cohort(cohort: Cohort, store: Optional[Store] = None) -> None:
    _admit(validate_cohort(cohort, store))


def admit_resource_flavor(rf: ResourceFlavor) -> None:
    _admit(validate_resource_flavor(rf))


def admit_local_queue(lq: LocalQueue) -> None:
    _admit(validate_local_queue(lq))


def admit_workload(wl: Workload, store: Optional[Store] = None) -> None:
    default_workload(wl, store)
    _admit(validate_workload(wl))
