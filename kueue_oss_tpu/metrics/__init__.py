"""Prometheus-style metrics registry.

Reference parity: pkg/metrics/metrics.go:316-857 — the same series names and
label sets, backed by a small in-process registry instead of the Prometheus
client. `render()` emits text exposition format for scraping/inspection, and
the perf runner scrapes counters the same way the reference's runner scrapes
minimalkueue's metrics endpoint.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Iterable, Optional

# Default histogram buckets mirroring prometheus.DefBuckets plus the
# exponential range the reference uses for wait-time series.
DEF_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
WAIT_BUCKETS = tuple(1 * 2 ** i for i in range(15))  # 1s .. ~4.5h

LabelValues = tuple[str, ...]

#: process-wide exemplar switch (obs.configure / bench twins): with it
#: off, Histogram.observe drops exemplar payloads before taking the
#: lock, so the disabled cost is one module-attribute read
exemplars_enabled = True


class _Series:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...]) -> None:
        self.name = name
        self.help = help_
        self.labels = labels
        self._lock = threading.Lock()

    def _key(self, label_values: Iterable[str]) -> LabelValues:
        key = tuple(str(v) for v in label_values)
        if len(key) != len(self.labels):
            raise ValueError(
                f"{self.name}: expected labels {self.labels}, got {key}")
        return key


class Counter(_Series):
    kind = "counter"

    def __init__(self, name: str, help_: str,
                 labels: tuple[str, ...] = ()) -> None:
        super().__init__(name, help_, labels)
        self._values: dict[LabelValues, float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = self._key(label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        return self._values.get(self._key(label_values), 0.0)

    def total(self) -> float:
        return sum(self._values.values())

    def delete_matching(self, **by_label: str) -> None:
        idx = {self.labels.index(k): v for k, v in by_label.items()}
        with self._lock:
            for key in [k for k in self._values
                        if all(k[i] == v for i, v in idx.items())]:
                del self._values[key]

    def collect(self) -> dict[LabelValues, float]:
        # a concurrent inc()/set() during a scrape would otherwise raise
        # "dictionary changed size during iteration" inside dict()
        with self._lock:
            return dict(self._values)


class Gauge(Counter):
    kind = "gauge"

    def set(self, *label_values: str, value: float) -> None:
        key = self._key(label_values)
        with self._lock:
            self._values[key] = float(value)

    def replace_prefix(self, prefix: tuple[str, ...],
                       updates: dict[tuple, float]) -> None:
        """Set every (prefix + suffix) sample from `updates`; stale
        samples sharing the prefix first report one scrape of 0, then
        drop off entirely — a drained gauge must not keep its last
        value, and churned label sets must not accumulate forever
        (reference metrics.go zero-fill + DeleteLabelValues)."""
        n = len(prefix)
        with self._lock:
            for key in list(self._values):
                if key[:n] == prefix and key[n:] not in updates:
                    if self._values[key] == 0.0:
                        del self._values[key]
                    else:
                        self._values[key] = 0.0
        for suffix, v in updates.items():
            self.set(*(prefix + tuple(suffix)), value=v)


class Histogram(_Series):
    kind = "histogram"

    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEF_BUCKETS) -> None:
        super().__init__(name, help_, labels)
        self.buckets = tuple(sorted(buckets))
        #: key -> (bucket counts, sum, count)
        self._values: dict[LabelValues, tuple[list[int], float, int]] = {}
        #: key -> bucket index -> (exemplar labels, value, optional ts);
        #: index len(buckets) is the +Inf bucket. One exemplar per
        #: bucket (the newest), the OpenMetrics convention — it links a
        #: latency bucket back to the exact decision (cycle/workload)
        #: that landed there.
        self._exemplars: dict[
            LabelValues, dict[int, tuple[dict, float, float]]] = {}

    def observe(self, *label_values: str, value: float,
                exemplar: Optional[dict] = None,
                exemplar_ts: Optional[float] = None) -> None:
        """``exemplar`` is a small {label: value} dict (e.g.
        {"cycle": 17, "workload": "ns/w"}) attached to the bucket this
        observation falls in and emitted in the OpenMetrics
        exposition; ignored while ``exemplars_enabled`` is False.
        Stored as given — values stringify at render/accessor time, so
        the admission hot path pays one tuple store, not a dict
        rebuild (the bench.py slo scenario's exemplar_overhead_pct
        twin measures exactly this path)."""
        key = self._key(label_values)
        if exemplar is not None and not exemplars_enabled:
            exemplar = None
        with self._lock:
            counts, total, n = self._values.get(
                key, ([0] * len(self.buckets), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._values[key] = (counts, total + value, n + 1)
            if exemplar is not None:
                # first bucket with edge >= value == the le bucket the
                # observation lands in (len(buckets) == +Inf); the
                # timestamp is optional in the OpenMetrics grammar, so
                # the hot path never calls time.time() itself
                idx = bisect_left(self.buckets, value)
                self._exemplars.setdefault(key, {})[idx] = (
                    exemplar, float(value), exemplar_ts)

    def count(self, *label_values: str) -> int:
        # reads hold the lock too: observe() replaces the value tuple,
        # and a torn (counts, sum, n) read would hand the caller a sum
        # from one generation and a count from another
        key = self._key(label_values)
        with self._lock:
            v = self._values.get(key)
            return v[2] if v else 0

    def sum(self, *label_values: str) -> float:
        key = self._key(label_values)
        with self._lock:
            v = self._values.get(key)
            return v[1] if v else 0.0

    def total_count(self) -> int:
        with self._lock:
            return sum(v[2] for v in self._values.values())

    def exemplars(self, *label_values: str
                  ) -> dict[int, tuple[dict, float, Optional[float]]]:
        """Bucket index -> (labels, value, ts) snapshot for one key,
        label values stringified (the exposition's view)."""
        key = self._key(label_values)
        with self._lock:
            raw = dict(self._exemplars.get(key, {}))
        return {i: ({str(k): str(v) for k, v in labels.items()},
                    value, ts)
                for i, (labels, value, ts) in raw.items()}

    def delete_matching(self, **by_label: str) -> None:
        idx = {self.labels.index(k): v for k, v in by_label.items()}
        with self._lock:
            for key in [k for k in self._values
                        if all(k[i] == v for i, v in idx.items())]:
                del self._values[key]
                self._exemplars.pop(key, None)

    def collect(self):
        # copy the per-key bucket lists too: observe() mutates them in
        # place, so a shallow dict copy would still hand the renderer a
        # list another thread is updating mid-iteration
        with self._lock:
            return {k: (list(counts), total, n)
                    for k, (counts, total, n) in self._values.items()}

    def collect_exemplars(self):
        with self._lock:
            return {k: dict(v) for k, v in self._exemplars.items()}


class Registry:
    def __init__(self) -> None:
        self._series: dict[str, _Series] = {}
        # register()/get() race the exposition path (a scrape iterating
        # the series dict while a late import registers a new one);
        # all three now share this lock
        self._lock = threading.Lock()

    def register(self, s: _Series) -> _Series:
        with self._lock:
            self._series[s.name] = s
        return s

    def get(self, name: str) -> Optional[_Series]:
        with self._lock:
            return self._series.get(name)

    def _series_snapshot(self) -> list[_Series]:
        with self._lock:
            return list(self._series.values())

    def render(self, openmetrics: bool = False) -> str:
        """Text exposition: Prometheus 0.0.4 by default, OpenMetrics
        with ``openmetrics=True`` — same series, plus per-bucket
        exemplars (``# {labels} value ts``) and the ``# EOF``
        terminator. Exemplars only exist in the OpenMetrics form; the
        classic format has no grammar for them."""
        out: list[str] = []
        for s in self._series_snapshot():
            family = s.name
            if (openmetrics and s.kind == "counter"
                    and family.endswith("_total")):
                # the OpenMetrics grammar names a counter FAMILY
                # suffix-free and requires its sample to be
                # <family>_total; emitting both with the suffix makes
                # a real Prometheus scrape fail to parse
                family = family[:-len("_total")]
            out.append(f"# HELP {family} {_escape_help(s.help)}")
            out.append(f"# TYPE {family} {s.kind}")
            if isinstance(s, Histogram):
                ex_of = s.collect_exemplars() if openmetrics else {}
                for key, (counts, total, n) in sorted(s.collect().items()):
                    base = _fmt_labels(s.labels, key)
                    exemplars = ex_of.get(key, {})
                    for i, (b, c) in enumerate(zip(s.buckets, counts)):
                        le = _merge_labels(base, f'le="{b}"')
                        out.append(f"{s.name}_bucket{le} {c}"
                                   + _fmt_exemplar(exemplars.get(i)))
                    inf = _merge_labels(base, 'le="+Inf"')
                    out.append(f"{s.name}_bucket{inf} {n}"
                               + _fmt_exemplar(
                                   exemplars.get(len(s.buckets))))
                    out.append(f"{s.name}_sum{base} {total}")
                    out.append(f"{s.name}_count{base} {n}")
            else:
                for key, v in sorted(s.collect().items()):  # type: ignore[attr-defined]
                    out.append(f"{s.name}{_fmt_labels(s.labels, key)} {v}")
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


def _escape_label_value(v: str) -> str:
    """Prometheus/OpenMetrics label-value escaping: backslash, double
    quote, newline. Recorder reason strings and CQ names flow into
    labels verbatim — an unescaped quote or newline would corrupt the
    whole exposition for every scraper."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline only (the exposition
    # grammar; quotes are legal there)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: tuple[str, ...], values: LabelValues) -> str:
    if not names:
        return ""
    pairs = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in zip(names, values))
    return "{" + pairs + "}"


def _fmt_exemplar(ex: Optional[tuple[dict, float, Optional[float]]]) -> str:
    if ex is None:
        return ""
    labels, value, ts = ex
    pairs = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    tail = f" {round(ts, 3)}" if ts is not None else ""
    return " # {" + pairs + "} " + f"{value}" + tail


def _merge_labels(base: str, extra: str) -> str:
    if not base:
        return "{" + extra + "}"
    return base[:-1] + "," + extra + "}"


registry = Registry()

# -- scheduler cycle (metrics.go:316-347) -----------------------------------

admission_attempts_total = registry.register(Counter(
    "kueue_admission_attempts_total",
    "Total number of admission cycle attempts by result", ("result",)))
admission_attempt_duration_seconds = registry.register(Histogram(
    "kueue_admission_attempt_duration_seconds",
    "Latency of an admission cycle attempt", ("result",)))
admission_cycle_preemption_skips = registry.register(Gauge(
    "kueue_admission_cycle_preemption_skips",
    "Workloads skipped by preemption in the last cycle", ("cluster_queue",)))

# -- pending / status gauges (metrics.go:360-382, 677-732) -------------------

pending_workloads = registry.register(Gauge(
    "kueue_pending_workloads", "Pending workloads per CQ and status",
    ("cluster_queue", "status")))
local_queue_pending_workloads = registry.register(Gauge(
    "kueue_local_queue_pending_workloads",
    "Pending workloads per LocalQueue and status",
    ("local_queue", "namespace", "status")))
reserving_active_workloads = registry.register(Gauge(
    "kueue_reserving_active_workloads",
    "Workloads with reserved quota per CQ", ("cluster_queue",)))
admitted_active_workloads = registry.register(Gauge(
    "kueue_admitted_active_workloads",
    "Admitted not-finished workloads per CQ", ("cluster_queue",)))
cluster_queue_status = registry.register(Gauge(
    "kueue_cluster_queue_status", "CQ status by condition",
    ("cluster_queue", "status")))

# -- workload flow counters (metrics.go:402-673) -----------------------------

quota_reserved_workloads_total = registry.register(Counter(
    "kueue_quota_reserved_workloads_total",
    "Total workloads that got quota reserved", ("cluster_queue",)))
admitted_workloads_total = registry.register(Counter(
    "kueue_admitted_workloads_total",
    "Total admitted workloads", ("cluster_queue",)))
finished_workloads_total = registry.register(Counter(
    "kueue_finished_workloads_total",
    "Total finished workloads", ("cluster_queue",)))
evicted_workloads_total = registry.register(Counter(
    "kueue_evicted_workloads_total",
    "Total evicted workloads by reason", ("cluster_queue", "reason")))
preempted_workloads_total = registry.register(Counter(
    "kueue_preempted_workloads_total",
    "Total preempted workloads by reason", ("preempting_cluster_queue", "reason")))
replaced_workload_slices_total = registry.register(Counter(
    "kueue_replaced_workload_slices_total",
    "Total workload slices replaced by a scaled-up slice", ("cluster_queue",)))

quota_reserved_wait_time_seconds = registry.register(Histogram(
    "kueue_quota_reserved_wait_time_seconds",
    "Time from creation to quota reservation", ("cluster_queue",),
    buckets=WAIT_BUCKETS))
admission_wait_time_seconds = registry.register(Histogram(
    "kueue_admission_wait_time_seconds",
    "Time from creation to admission", ("cluster_queue",),
    buckets=WAIT_BUCKETS))
admission_checks_wait_time_seconds = registry.register(Histogram(
    "kueue_admission_checks_wait_time_seconds",
    "Time from quota reservation to admission", ("cluster_queue",),
    buckets=WAIT_BUCKETS))

# -- quota gauges (metrics.go:733-804) ---------------------------------------

cluster_queue_resource_usage = registry.register(Gauge(
    "kueue_cluster_queue_resource_usage", "Current usage per CQ/flavor/resource",
    ("cluster_queue", "flavor", "resource")))
cluster_queue_resource_reservation = registry.register(Gauge(
    "kueue_cluster_queue_resource_reservation",
    "Currently reserved quantity per CQ/flavor/resource",
    ("cluster_queue", "flavor", "resource")))
cluster_queue_nominal_quota = registry.register(Gauge(
    "kueue_cluster_queue_nominal_quota", "Nominal quota per CQ/flavor/resource",
    ("cluster_queue", "flavor", "resource")))
cluster_queue_borrowing_limit = registry.register(Gauge(
    "kueue_cluster_queue_borrowing_limit",
    "Borrowing limit per CQ/flavor/resource",
    ("cluster_queue", "flavor", "resource")))
cluster_queue_lending_limit = registry.register(Gauge(
    "kueue_cluster_queue_lending_limit",
    "Lending limit per CQ/flavor/resource",
    ("cluster_queue", "flavor", "resource")))

# -- fair sharing (metrics.go:805-830) ---------------------------------------

cluster_queue_weighted_share = registry.register(Gauge(
    "kueue_cluster_queue_weighted_share",
    "DominantResourceShare of the CQ (x1000, weighted)", ("cluster_queue",)))
cohort_weighted_share = registry.register(Gauge(
    "kueue_cohort_weighted_share",
    "DominantResourceShare of the cohort (x1000, weighted)", ("cohort",)))

# -- LocalQueue family (metrics.go local_queue_* series; gate
# LocalQueueMetrics) ----------------------------------------------------------

local_queue_quota_reserved_workloads_total = registry.register(Counter(
    "kueue_local_queue_quota_reserved_workloads_total",
    "Total workloads with quota reserved per LocalQueue",
    ("local_queue", "namespace")))
local_queue_admitted_workloads_total = registry.register(Counter(
    "kueue_local_queue_admitted_workloads_total",
    "Total admitted workloads per LocalQueue", ("local_queue", "namespace")))
local_queue_evicted_workloads_total = registry.register(Counter(
    "kueue_local_queue_evicted_workloads_total",
    "Total evicted workloads per LocalQueue by reason",
    ("local_queue", "namespace", "reason")))
local_queue_finished_workloads_total = registry.register(Counter(
    "kueue_local_queue_finished_workloads_total",
    "Total finished workloads per LocalQueue", ("local_queue", "namespace")))
local_queue_reserving_active_workloads = registry.register(Gauge(
    "kueue_local_queue_reserving_active_workloads",
    "Workloads with reserved quota per LocalQueue",
    ("local_queue", "namespace")))
local_queue_admitted_active_workloads = registry.register(Gauge(
    "kueue_local_queue_admitted_active_workloads",
    "Admitted not-finished workloads per LocalQueue",
    ("local_queue", "namespace")))
local_queue_status = registry.register(Gauge(
    "kueue_local_queue_status", "LocalQueue status by condition",
    ("local_queue", "namespace", "status")))
local_queue_resource_usage = registry.register(Gauge(
    "kueue_local_queue_resource_usage",
    "Current usage per LocalQueue/flavor/resource",
    ("local_queue", "namespace", "flavor", "resource")))
local_queue_resource_reservation = registry.register(Gauge(
    "kueue_local_queue_resource_reservation",
    "Currently reserved quantity per LocalQueue/flavor/resource",
    ("local_queue", "namespace", "flavor", "resource")))
local_queue_quota_reserved_wait_time_seconds = registry.register(Histogram(
    "kueue_local_queue_quota_reserved_wait_time_seconds",
    "Time from creation to quota reservation per LocalQueue",
    ("local_queue", "namespace"), buckets=WAIT_BUCKETS))
local_queue_admission_wait_time_seconds = registry.register(Histogram(
    "kueue_local_queue_admission_wait_time_seconds",
    "Time from creation to admission per LocalQueue",
    ("local_queue", "namespace"), buckets=WAIT_BUCKETS))

# -- cohort subtree family (metrics.go cohort_subtree_*) ----------------------

cohort_subtree_quota = registry.register(Gauge(
    "kueue_cohort_subtree_quota",
    "Subtree quota per cohort/flavor/resource",
    ("cohort", "flavor", "resource")))
cohort_subtree_resource_reservations = registry.register(Gauge(
    "kueue_cohort_subtree_resource_reservations",
    "Reserved quantity in the cohort subtree per flavor/resource",
    ("cohort", "flavor", "resource")))
cohort_subtree_admitted_active_workloads = registry.register(Gauge(
    "kueue_cohort_subtree_admitted_active_workloads",
    "Admitted not-finished workloads in the cohort subtree", ("cohort",)))
cohort_subtree_admitted_workloads_total = registry.register(Counter(
    "kueue_cohort_subtree_admitted_workloads_total",
    "Total workloads admitted in the cohort subtree", ("cohort",)))

# -- eviction / readiness detail (metrics.go) ---------------------------------

evicted_workloads_once_total = registry.register(Counter(
    "kueue_evicted_workloads_once_total",
    "Workloads evicted at least once, by reason (first eviction only)",
    ("cluster_queue", "reason")))
finished_workloads_gauge = registry.register(Gauge(
    "kueue_finished_workloads",
    "Finished workloads currently retained per CQ", ("cluster_queue",)))
admitted_until_ready_wait_time_seconds = registry.register(Histogram(
    "kueue_admitted_until_ready_wait_time_seconds",
    "Time from admission until all pods ready", ("cluster_queue",),
    buckets=WAIT_BUCKETS))
ready_wait_time_seconds = registry.register(Histogram(
    "kueue_ready_wait_time_seconds",
    "Time from creation until all pods ready", ("cluster_queue",),
    buckets=WAIT_BUCKETS))
pods_ready_to_evicted_time_seconds = registry.register(Histogram(
    "kueue_pods_ready_to_evicted_time_seconds",
    "Time between pods becoming ready and the workload's eviction",
    ("cluster_queue", "reason"), buckets=WAIT_BUCKETS))
workload_creation_latency_seconds = registry.register(Histogram(
    "kueue_workload_creation_latency_seconds",
    "Time from job creation to its Workload object creation",
    ("job_kind",), buckets=WAIT_BUCKETS))
workload_eviction_latency_seconds = registry.register(Histogram(
    "kueue_workload_eviction_latency_seconds",
    "Time from the Evicted condition turning True until quota released "
    "(metrics.go:654-666; ~0 for synchronous in-process evictions, >0 "
    "when a deferred flow set the condition earlier)",
    ("cluster_queue", "reason"), buckets=WAIT_BUCKETS))
local_queue_admission_checks_wait_time_seconds = registry.register(
    Histogram("kueue_local_queue_admission_checks_wait_time_seconds",
              "Per-LQ time waiting on admission checks",
              ("local_queue", "namespace"), buckets=WAIT_BUCKETS))
local_queue_admitted_until_ready_wait_time_seconds = registry.register(
    Histogram("kueue_local_queue_admitted_until_ready_wait_time_seconds",
              "Per-LQ time from admission until all pods ready",
              ("local_queue", "namespace"), buckets=WAIT_BUCKETS))
local_queue_ready_wait_time_seconds = registry.register(
    Histogram("kueue_local_queue_ready_wait_time_seconds",
              "Per-LQ time from creation until all pods ready",
              ("local_queue", "namespace"), buckets=WAIT_BUCKETS))
local_queue_finished_workloads_gauge = registry.register(Gauge(
    "kueue_local_queue_finished_workloads",
    "Finished workloads currently retained per LQ",
    ("local_queue", "namespace")))
cluster_queue_resource_pending = registry.register(Gauge(
    "kueue_cluster_queue_resource_pending",
    "Pending requested quantity per CQ/resource",
    ("cluster_queue", "resource")))
build_info = registry.register(Gauge(
    "kueue_build_info", "Build metadata", ("version",)))
build_info.set("kueue-oss-tpu-r3", value=1)

# -- solver-specific (new; no reference analog) ------------------------------

solver_cycle_duration_seconds = registry.register(Histogram(
    "kueue_tpu_solver_cycle_duration_seconds",
    "Wall time of one batched TPU solve", ("phase",)))
solver_plan_fallbacks_total = registry.register(Counter(
    "kueue_tpu_solver_plan_fallbacks_total",
    "Solver plans rejected by the host oracle re-check", ()))

# -- solver backend resilience (sidecar transport + circuit breaker) ---------

solver_remote_retries_total = registry.register(Counter(
    "kueue_tpu_solver_remote_retries_total",
    "Remote solve attempts retried after a transport fault", ()))
solver_remote_failures_total = registry.register(Counter(
    "kueue_tpu_solver_remote_failures_total",
    "Remote solve attempt failures by kind "
    "(timeout/protocol/connection/server)", ("kind",)))
solver_deadline_exceeded_total = registry.register(Counter(
    "kueue_tpu_solver_deadline_exceeded_total",
    "Remote solves abandoned at the per-call deadline", ()))
solver_fallback_total = registry.register(Counter(
    "kueue_tpu_solver_fallback_total",
    "Backlog drains degraded to the host cycle path by reason",
    ("reason",)))
solver_breaker_trips_total = registry.register(Counter(
    "kueue_tpu_solver_breaker_trips_total",
    "Solver circuit breaker transitions into the open state", ()))
solver_breaker_state = registry.register(Gauge(
    "kueue_tpu_solver_breaker_state",
    "Solver breaker state (0 closed, 1 half-open, 2 open)", ()))
solver_plan_rejected_total = registry.register(Counter(
    "kueue_tpu_solver_plan_rejected_total",
    "Imported plans rejected wholesale by the sanity guard", ()))
degradation_level = registry.register(Gauge(
    "kueue_degradation_level",
    "Current degradation ladder level per subsystem (0 = healthy; "
    "see docs/ROBUSTNESS.md 'Degradation ladder')", ("subsystem",)))
degradation_transitions_total = registry.register(Counter(
    "kueue_degradation_transitions_total",
    "Degradation condition transitions (direction: degrade/recover)",
    ("subsystem", "direction")))

# -- delta-sync solver sessions (docs/SOLVER_PROTOCOL.md) --------------------

solver_resync_total = registry.register(Counter(
    "kueue_tpu_solver_resync_total",
    "Session full-resyncs forced by a sidecar state divergence, by "
    "reason (session_missing/epoch_mismatch/checksum_mismatch/...)",
    ("reason",)))
solver_session_frames_total = registry.register(Counter(
    "kueue_tpu_solver_session_frames_total",
    "Solver request frames shipped by kind (sync/delta/resync/legacy)",
    ("kind",)))
solver_session_bytes_total = registry.register(Counter(
    "kueue_tpu_solver_session_bytes_total",
    "Solver request payload bytes shipped by frame kind", ("kind",)))
solver_session_evictions_total = registry.register(Counter(
    "kueue_tpu_solver_session_evictions_total",
    "Sidecar session-table evictions by reason (lru = capacity "
    "pressure past max_sessions; tenant_evicted = a whole tenant "
    "namespace dropped by the farm/chaos layer)", ("reason",)))

# -- federation / multi-tenant solver farm (docs/FEDERATION.md) --------------

solver_farm_requests_total = registry.register(Counter(
    "kueue_tpu_solver_farm_requests_total",
    "Solver farm requests admitted to the executor, by tenant", ("tenant",)))
solver_farm_wall_seconds_total = registry.register(Counter(
    "kueue_tpu_solver_farm_wall_seconds_total",
    "Solver wall-time consumed on the shared farm, by tenant (the "
    "quantity the deficit-round-robin scheduler arbitrates)",
    ("tenant",)))
solver_farm_throttled_total = registry.register(Counter(
    "kueue_tpu_solver_farm_throttled_total",
    "Farm requests rejected with backpressure (per-tenant queue "
    "overflow; the client degrades to host cycles via "
    "SolverUnavailable)", ("tenant",)))
solver_farm_tenants = registry.register(Gauge(
    "kueue_tpu_solver_farm_tenants",
    "Distinct tenants with live state on the shared solver farm", ()))
solver_farm_grant_wait_seconds = registry.register(Histogram(
    "kueue_tpu_solver_farm_grant_wait_seconds",
    "Seconds between a solve request's arrival at the farm and its "
    "DRR grant (the queue-wait the deficit scheduler imposes), by "
    "tenant", ("tenant",),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)))

# -- device telemetry (obs/devtel.py, docs/OBSERVABILITY.md) -----------------

solver_compiles_total = registry.register(Counter(
    "kueue_tpu_solver_compiles_total",
    "First-call XLA compilations detected per (kernel, arm, pow2 "
    "shape bucket) by the devtel compile detector",
    ("kernel", "arm", "bucket")))
solver_compile_seconds = registry.register(Histogram(
    "kueue_tpu_solver_compile_seconds",
    "Wall seconds of solves flagged as compile-bearing (first call "
    "for a (kernel, arm, shape-bucket); upper-bounds compile time — "
    "the wall includes the traced execution)", (),
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0)))
solver_transfer_bytes_total = registry.register(Counter(
    "kueue_tpu_solver_transfer_bytes_total",
    "Host<->device and wire transfer bytes by direction (h2d = "
    "uploads incl. donated deltas; avoided = copies elided by "
    "donation/aliasing; tx = request frames on the sidecar wire), "
    "arm, and tenant", ("direction", "arm", "tenant")))
solver_hbm_resident_bytes = registry.register(Gauge(
    "kueue_tpu_solver_hbm_resident_bytes",
    "Bytes of solver problem state resident on device after the last "
    "drain (portable bookkeeping over the delta-session buffers)", ()))
solver_hbm_bytes_in_use = registry.register(Gauge(
    "kueue_tpu_solver_hbm_bytes_in_use",
    "Device-reported bytes_in_use per device (memory_stats(); absent "
    "on backends that do not expose allocator stats)", ("device",)))
solver_deep_captures_total = registry.register(Counter(
    "kueue_tpu_solver_deep_captures_total",
    "Tail-based deep-capture sessions by trigger "
    "(slo_burn/phase_regression/manual) and outcome "
    "(started/suppressed_cooldown/suppressed_busy/disarmed)",
    ("trigger", "outcome")))

# -- federated dispatch (multikueue/dispatcher.py WhatIf strategy) -----------

multikueue_whatif_dispatch_total = registry.register(Counter(
    "kueue_multikueue_whatif_dispatch_total",
    "What-if-scored dispatch decisions by outcome (scored = batched "
    "pricer nominated a cluster; fallback = farm/pricer unavailable, "
    "degraded to Incremental; deferred = outstanding nomination still "
    "within its round timeout)", ("outcome",)))
multikueue_dispatch_score_ms = registry.register(Histogram(
    "kueue_multikueue_dispatch_score_ms",
    "Wall milliseconds spent pricing one dispatch across candidate "
    "clusters with the batched what-if solve", (),
    buckets=(1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
             1000.0, 2500.0)))

# -- columnar export-path health (solver/columnar.py) ------------------------

columnar_bailouts_total = registry.register(Counter(
    "kueue_tpu_columnar_bailouts_total",
    "Columnar exports that bailed out to the classic dict walk, by "
    "reason (afs_active = AdmissionFairSharing consulted, column "
    "store cannot price usage-ordering; retry_exhausted = concurrent "
    "mutation raced the lock-free snapshot three times)", ("reason",)))

# -- mesh-sharded drains (solver/sharded.py, docs/SOLVER_PROTOCOL.md) --------

solver_mesh_devices = registry.register(Gauge(
    "kueue_tpu_solver_mesh_devices",
    "Devices in the solver mesh used by the most recent drain "
    "(0 = single-chip / host path)", ()))
solver_shard_imbalance = registry.register(Histogram(
    "kueue_tpu_solver_shard_imbalance",
    "Real-row imbalance across mesh shards per drain "
    "((max - min) / mean occupied rows; 0 = perfectly even)", (),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0)))
solver_multihost_processes = registry.register(Gauge(
    "kueue_tpu_solver_multihost_processes",
    "jax processes in the pod-scale solver bootstrap "
    "(1 = single-host; set by service.serve_multihost)", ()))

# -- convex-relaxation fast-path arm (solver/relax.py) -----------------------

solver_relax_drains_total = registry.register(Counter(
    "kueue_tpu_solver_relax_drains_total",
    "Relaxed-arm solves by outcome (served = relax plan emitted; "
    "audit_match / audit_diverged = exact-kernel disagreement audits; "
    "error = arm fault, drain fell back to an exact arm)",
    ("outcome",)))
solver_relax_support_fraction = registry.register(Histogram(
    "kueue_tpu_solver_relax_support_fraction",
    "Rounded support size as a fraction of live backlog rows per "
    "relaxed solve", (),
    buckets=(0.01, 0.02, 0.05, 0.1, 0.2, 0.35, 0.5, 0.75, 1.0)))

# -- streaming control plane (scheduler/streaming.py) ------------------------

stream_microdrains_total = registry.register(Counter(
    "kueue_stream_microdrains_total",
    "Micro-batched sub-cycle admission drains by outcome (admitted / "
    "parked = only no-fit parkings / deferred = every pending CQ "
    "fenced to the next full solve / idle)", ("outcome",)))
stream_admitted_total = registry.register(Counter(
    "kueue_stream_admitted_total",
    "Workloads admitted sub-cycle by the streaming fast path", ()))
stream_demotions_total = registry.register(Counter(
    "kueue_stream_demotions_total",
    "Fast-path demotions by fence reason (cohort_event / spec_change "
    "/ borrow_capable / out_of_order / unsupported / "
    "flavor_witness_invalid = a capacity event could flip the "
    "full-solve flavor pick / headroom_exhausted = the admission "
    "needed borrowed capacity or overran the reserved nominal-"
    "headroom budget / watch_coalesced = arrival signals absorbed "
    "into an already-running watch-driven micro-drain under burst "
    "backpressure, not a fence) — fence reasons defer the subtree "
    "to the next full solve",
    ("reason",)))
stream_eligible_fraction = registry.register(Gauge(
    "kueue_stream_eligible_fraction",
    "Fraction of pending ClusterQueues the last micro-drain walked "
    "on the streaming fast path (1 - deferred/considered; the "
    "coverage the wide fences buy over the structural PR-11 fences)",
    ()))
stream_spec_solves_total = registry.register(Counter(
    "kueue_stream_spec_solves_total",
    "Full solves pulled forward because a spec edit (quota/flavor "
    "change, node flap) was observed mid-window by the streaming "
    "fast path", ()))

# -- decision flight recorder (obs/) -----------------------------------------

decision_events_total = registry.register(Counter(
    "kueue_decision_events_total",
    "Flight-recorder decision events by kind", ("kind",)))
decision_skips_total = registry.register(Counter(
    "kueue_decision_skips_total",
    "Workload skip/fallback decisions by bounded reason slug",
    ("reason",)))

# -- what-if engine (kueue_oss_tpu/sim/, docs/SIMULATOR.md) ------------------

whatif_scenarios_total = registry.register(Counter(
    "kueue_tpu_whatif_scenarios_total",
    "Counterfactual scenarios solved by the what-if engine, by mode "
    "(batched/sequential/trace)", ("mode",)))
whatif_batches_total = registry.register(Counter(
    "kueue_tpu_whatif_batches_total",
    "Vmapped what-if batch dispatches", ()))
whatif_batch_width = registry.register(Histogram(
    "kueue_tpu_whatif_batch_width",
    "Scenario-axis width of what-if batch dispatches (pow2-padded)", (),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)))
whatif_duration_seconds = registry.register(Histogram(
    "kueue_tpu_whatif_duration_seconds",
    "What-if engine wall time by phase (build/solve/parity/report)",
    ("phase",)))
whatif_round_buckets_total = registry.register(Counter(
    "kueue_tpu_whatif_round_buckets_total",
    "What-if scenarios dispatched per predicted-round-count bucket "
    "(round-skew bucketing keeps short lanes out of long batches)",
    ("bucket",)))
whatif_parity_failures_total = registry.register(Counter(
    "kueue_tpu_whatif_parity_failures_total",
    "What-if batches whose vmapped plans diverged from the sequential "
    "oracle (must stay 0; a nonzero count is a kernel bug)", ()))
whatif_retier_total = registry.register(Counter(
    "kueue_tpu_whatif_retier_total",
    "What-if scenarios re-tiered from the FULL kernel to the relax-LP "
    "approximate tier by the lane-budget planner, by reason — every "
    "re-tier is reported per scenario row; none may happen silently",
    ("reason",)))
whatif_full_chunks_total = registry.register(Counter(
    "kueue_tpu_whatif_full_chunks_total",
    "Lane-budgeted FULL-kernel sweep chunk dispatches", ()))
whatif_resident_syncs_total = registry.register(Counter(
    "kueue_tpu_whatif_resident_syncs_total",
    "ResidentSweep device-state refreshes by kind (full upload on "
    "spec-gen change / row scatter on workload churn / reuse when "
    "nothing moved)", ("kind",)))

# -- cluster health layer (obs/health.py + obs/ledger.py,
# docs/OBSERVABILITY.md "Cluster health & SLOs") -----------------------------

slo_burn_rate = registry.register(Gauge(
    "kueue_slo_burn_rate",
    "Queue-wait SLO burn rate per scope/key/window (1.0 = exactly "
    "consuming the error budget; alerting thresholds sit well above)",
    ("scope", "key", "window")))
slo_alerts_firing = registry.register(Gauge(
    "kueue_slo_alerts_firing",
    "Burn-rate alerts currently firing per scope/key (0 or 1)",
    ("scope", "key")))
slo_alert_transitions_total = registry.register(Counter(
    "kueue_slo_alert_transitions_total",
    "Burn-rate alert state transitions by direction (fired/cleared)",
    ("scope", "key", "state")))
starvation_oldest_pending_seconds = registry.register(Gauge(
    "kueue_starvation_oldest_pending_seconds",
    "Age of the oldest pending workload per CQ at the last SLO "
    "evaluation (the starvation watchdog's primary signal)",
    ("cluster_queue",)))
ledger_records_total = registry.register(Counter(
    "kueue_ledger_records_total",
    "Cycle-ledger rows recorded, by kind (host/solver/stream)",
    ("kind",)))
slo_alert_deliveries_total = registry.register(Counter(
    "kueue_slo_alert_deliveries_total",
    "Alert-sink notifications on burn-rate fire/clear transitions, "
    "by outcome (ok/error)", ("outcome",)))
cycle_phase_regression = registry.register(Gauge(
    "kueue_cycle_phase_regression",
    "1 while the fast EWMA of a cycle phase's wall exceeds the "
    "regression ratio over its slow baseline (ledger-driven "
    "regression detection), else 0", ("kind", "phase")))
cycle_phase_regression_ratio = registry.register(Gauge(
    "kueue_cycle_phase_regression_ratio",
    "Fast-EWMA / slow-baseline ratio per cycle phase (1.0 = at "
    "baseline)", ("kind", "phase")))

# -- durable control plane (persist/, docs/DURABILITY.md) --------------------

wal_records_total = registry.register(Counter(
    "kueue_wal_records_total",
    "Write-ahead-log records appended, by kind (event/intent)",
    ("kind",)))
wal_bytes_total = registry.register(Counter(
    "kueue_wal_bytes_total",
    "Write-ahead-log bytes appended (frame headers included)", ()))
wal_fsyncs_total = registry.register(Counter(
    "kueue_wal_fsyncs_total",
    "fsync barriers issued by the write-ahead log", ()))
wal_fsync_faults_total = registry.register(Counter(
    "kueue_wal_fsync_faults_total",
    "fsync failures absorbed by the WAL durability ladder "
    "(always -> batch -> off; docs/ROBUSTNESS.md)", ()))
checkpoints_total = registry.register(Counter(
    "kueue_checkpoints_total",
    "Store checkpoints by outcome (written = full / incremental / "
    "failed)", ("outcome",)))
checkpoint_duration_seconds = registry.register(Histogram(
    "kueue_checkpoint_duration_seconds",
    "Wall time of one atomic checkpoint (serialize + fsync + rotate)",
    ()))
checkpoint_bytes = registry.register(Gauge(
    "kueue_checkpoint_bytes",
    "Payload bytes of the most recent checkpoint, by kind "
    "(full/incremental)", ("kind",)))
wal_shipped_bytes_total = registry.register(Counter(
    "kueue_wal_shipped_bytes_total",
    "Bytes shipped to the warm standby, by stream (tail = synced "
    "active-segment suffix / sealed = rotated segments / checkpoint)",
    ("stream",)))
wal_compaction_dropped_total = registry.register(Counter(
    "kueue_wal_compaction_dropped_total",
    "Records dropped by per-key log compaction during sealed-segment "
    "shipping (superseded events + satisfied intents)", ()))
wal_standby_rebootstraps_total = registry.register(Counter(
    "kueue_wal_standby_rebootstraps_total",
    "Warm-standby re-bootstraps from a newer shipped checkpoint that "
    "superseded the replay frontier", ()))
wal_standby_pruned_total = registry.register(Counter(
    "kueue_wal_standby_pruned_total",
    "Superseded shipped files (retired segments, out-of-chain "
    "checkpoints) deleted by the warm standby's GC", ()))
recovery_total = registry.register(Counter(
    "kueue_recovery_total",
    "Recoveries by source (checkpoint/wal_only/empty)", ("source",)))
recovery_replayed_records = registry.register(Gauge(
    "kueue_recovery_replayed_records",
    "WAL records replayed by the most recent recovery", ()))
invariant_audits_total = registry.register(Counter(
    "kueue_invariant_audits_total",
    "Invariant auditor passes completed", ()))
invariant_violations_total = registry.register(Counter(
    "kueue_invariant_violations_total",
    "Accounting invariant violations detected, by check "
    "(must stay 0; a nonzero count means derived state drifted from "
    "the admission records)", ("check",)))
invariant_heals_total = registry.register(Counter(
    "kueue_invariant_heals_total",
    "Auto-heal index rebuilds performed by the invariant auditor", ()))
invariant_audit_errors_total = registry.register(Counter(
    "kueue_invariant_audit_errors_total",
    "Background audit passes that crashed internally (an auditor "
    "defect, NOT state drift — the violations counter stays clean)",
    ()))
invariant_last_violations = registry.register(Gauge(
    "kueue_invariant_last_violations",
    "Violations found by the most recent audit pass", ()))


# -- recording helpers (reference: pkg/metrics exported funcs) ---------------

class CycleResult:
    SUCCESS = "success"
    INADMISSIBLE = "inadmissible"


def observe_admission_attempt(result: str, duration_s: float) -> None:
    admission_attempts_total.inc(result)
    admission_attempt_duration_seconds.observe(result, value=duration_s)


def report_pending_workloads(cq: str, active: int, inadmissible: int) -> None:
    pending_workloads.set(cq, "active", value=active)
    pending_workloads.set(cq, "inadmissible", value=inadmissible)


def _lq_metrics_enabled() -> bool:
    from kueue_oss_tpu import features

    return features.enabled("LocalQueueMetrics")


# ---------------------------------------------------------------------------
# custom metric labels (gate CustomMetricLabels; pkg/metrics/custom_labels.go)
# ---------------------------------------------------------------------------

#: configured ClusterQueue label keys appended to per-CQ series
_custom_cq_keys: list[str] = []
#: cq name -> resolved label values (parallel to _custom_cq_keys)
_custom_cq_values: dict[str, tuple[str, ...]] = {}


def configure_custom_labels(cq_label_keys: list[str]) -> None:
    """Extend the per-CQ admission series with values taken from each
    ClusterQueue's object labels (reference custom_labels.go: the metric
    vecs are rebuilt with the extended label set at config time). The
    gate is consulted HERE, at configure time, so the series label
    tuples and the emit-time value tuples can never disagree."""
    from kueue_oss_tpu import features

    global _custom_cq_keys
    if not features.enabled("CustomMetricLabels"):
        cq_label_keys = []
    _custom_cq_keys = list(cq_label_keys)
    _custom_cq_values.clear()
    extra = tuple("label_" + k.replace("/", "_").replace(".", "_").
                  replace("-", "_") for k in cq_label_keys)
    for series in (admitted_workloads_total, admission_wait_time_seconds,
                   quota_reserved_workloads_total,
                   quota_reserved_wait_time_seconds):
        base = series.labels[:1]          # ("cluster_queue",)
        series.labels = base + extra


def record_cq_labels(cq_name: str, labels: dict) -> None:
    """Resolve + store a CQ's custom label values; a change clears the
    CQ's stale series (CustomLabelStore.StoreAndClear)."""
    if not _custom_cq_keys:
        return
    vals = tuple(labels.get(k, "") for k in _custom_cq_keys)
    old = _custom_cq_values.get(cq_name)
    if old is not None and old != vals:
        for series in (admitted_workloads_total,
                       admission_wait_time_seconds,
                       quota_reserved_workloads_total,
                       quota_reserved_wait_time_seconds):
            series.delete_matching(cluster_queue=cq_name)
    _custom_cq_values[cq_name] = vals


def _cq_labels(cq: str) -> tuple:
    if not _custom_cq_keys:
        return (cq,)
    return (cq,) + _custom_cq_values.get(
        cq, ("",) * len(_custom_cq_keys))


def admitted_workload(cq: str, wait_s: float, lq: str = "",
                      namespace: str = "default",
                      exemplar: Optional[dict] = None) -> None:
    """``exemplar`` (e.g. {"cycle": "17", "workload": "ns/w"}) rides
    the wait-time histogram so a latency bucket links back to the
    exact ledger row and decision chain (docs/OBSERVABILITY.md)."""
    admitted_workloads_total.inc(*_cq_labels(cq))
    admission_wait_time_seconds.observe(*_cq_labels(cq),
                                        value=max(wait_s, 0.0),
                                        exemplar=exemplar)
    if lq and _lq_metrics_enabled():
        local_queue_admitted_workloads_total.inc(lq, namespace)
        local_queue_admission_wait_time_seconds.observe(
            lq, namespace, value=max(wait_s, 0.0))


def quota_reserved_workload(cq: str, wait_s: float, lq: str = "",
                            namespace: str = "default",
                            exemplar: Optional[dict] = None) -> None:
    quota_reserved_workloads_total.inc(*_cq_labels(cq))
    quota_reserved_wait_time_seconds.observe(*_cq_labels(cq),
                                             value=max(wait_s, 0.0),
                                             exemplar=exemplar)
    if lq and _lq_metrics_enabled():
        local_queue_quota_reserved_workloads_total.inc(lq, namespace)
        local_queue_quota_reserved_wait_time_seconds.observe(
            lq, namespace, value=max(wait_s, 0.0))


def report_cluster_queue_quotas(cq: str, quotas) -> None:
    """quotas: iterable of ((flavor, resource), ResourceQuota)."""
    for (flavor, resource), rq in quotas:
        cluster_queue_nominal_quota.set(cq, flavor, resource, value=rq.nominal)
        if rq.borrowing_limit is not None:
            cluster_queue_borrowing_limit.set(
                cq, flavor, resource, value=rq.borrowing_limit)
        if rq.lending_limit is not None:
            cluster_queue_lending_limit.set(
                cq, flavor, resource, value=rq.lending_limit)


def report_cluster_queue_usage(cq: str, usage: dict, spec_frs=None) -> None:
    """spec_frs: every (flavor, resource) pair in the CQ's spec. Pairs whose
    usage dropped to zero are absent from the snapshot usage dict but must
    still report 0 — the reference emits a sample for every configured pair
    (metrics.go ReportClusterQueueQuotas/usage, :733+)."""
    if spec_frs is not None:
        for fr in spec_frs:
            if fr not in usage:
                flavor, resource = fr
                cluster_queue_resource_usage.set(
                    cq, flavor, resource, value=0)
                cluster_queue_resource_reservation.set(
                    cq, flavor, resource, value=0)
    for (flavor, resource), q in usage.items():
        cluster_queue_resource_usage.set(cq, flavor, resource, value=q)
        cluster_queue_resource_reservation.set(cq, flavor, resource, value=q)


def clear_cluster_queue_metrics(cq: str) -> None:
    """Reference parity: metrics.ClearClusterQueueResourceMetrics on CQ delete."""
    for series in (cluster_queue_resource_usage,
                   cluster_queue_resource_reservation,
                   cluster_queue_nominal_quota,
                   cluster_queue_borrowing_limit,
                   cluster_queue_lending_limit):
        series.delete_matching(cluster_queue=cq)
    for series in (pending_workloads, admission_cycle_preemption_skips,
                   reserving_active_workloads, admitted_active_workloads,
                   cluster_queue_status, cluster_queue_weighted_share):
        series.delete_matching(cluster_queue=cq)


def reset_all() -> None:
    """Test helper: drop every recorded sample (registry keeps its series)."""
    for s in registry._series_snapshot():
        s._values = {}  # type: ignore[attr-defined]
        if isinstance(s, Histogram):
            s._exemplars = {}
