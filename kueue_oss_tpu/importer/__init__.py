"""Batch importer for pre-existing pods.

Reference parity: cmd/importer — two phases over running cluster pods
that predate kueue: **check** validates each pod maps to a LocalQueue
(by the queue label) whose ClusterQueue exists and covers the pod's
requests; **import** creates an already-admitted Workload per pod so the
quota books reflect reality (cmd/importer/README:1-25, pod/import.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import (
    Admission,
    PodSet,
    PodSetAssignment,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.store import Store

QUEUE_LABEL = "kueue.x-k8s.io/queue-name"


@dataclass
class ExistingPod:
    """A running, un-managed pod found in the cluster."""

    name: str
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    requests: dict[str, int] = field(default_factory=dict)
    priority: int = 0


@dataclass
class ImportResult:
    checked: int = 0
    importable: int = 0
    imported: int = 0
    errors: list[str] = field(default_factory=list)


class Importer:
    def __init__(self, store: Store) -> None:
        self.store = store

    def _check_pod(self, pod: ExistingPod) -> tuple[Optional[str], Optional[str]]:
        """Returns (cq_name, error)."""
        queue = pod.labels.get(QUEUE_LABEL)
        if not queue:
            return None, f"pod {pod.namespace}/{pod.name}: no queue label"
        lq = self.store.local_queues.get(f"{pod.namespace}/{queue}")
        if lq is None:
            return None, (f"pod {pod.namespace}/{pod.name}: "
                          f"LocalQueue {queue!r} not found")
        cq = self.store.cluster_queues.get(lq.cluster_queue)
        if cq is None:
            return None, (f"pod {pod.namespace}/{pod.name}: ClusterQueue "
                          f"{lq.cluster_queue!r} not found")
        covered = {r for rg in cq.resource_groups
                   for r in rg.covered_resources}
        missing = set(pod.requests) - covered
        if missing:
            return None, (f"pod {pod.namespace}/{pod.name}: resources "
                          f"{sorted(missing)} not covered by "
                          f"ClusterQueue {cq.name!r}")
        return cq.name, None

    def check(self, pods: list[ExistingPod]) -> ImportResult:
        res = ImportResult()
        for pod in pods:
            res.checked += 1
            _, err = self._check_pod(pod)
            if err:
                res.errors.append(err)
            else:
                res.importable += 1
        return res

    def run(self, pods: list[ExistingPod], now: float = 0.0) -> ImportResult:
        """Check then import: each valid pod becomes an admitted Workload
        charged against the first flavor that defines its resources."""
        res = self.check(pods)
        if res.errors:
            return res  # all-or-nothing like the importer's check phase
        for pod in pods:
            cq_name, _ = self._check_pod(pod)
            cq = self.store.cluster_queues[cq_name]
            flavors: dict[str, str] = {}
            for r in pod.requests:
                for rg in cq.resource_groups:
                    if r in rg.covered_resources and rg.flavors:
                        flavors[r] = rg.flavors[0].name
                        break
            wl = Workload(
                name=f"pod-{pod.name}",
                namespace=pod.namespace,
                queue_name=pod.labels[QUEUE_LABEL],
                priority=pod.priority,
                podsets=[PodSet(name="main", count=1,
                                requests=dict(pod.requests))],
                creation_time=now,
            )
            wl.status.admission = Admission(
                cluster_queue=cq_name,
                podset_assignments=[PodSetAssignment(
                    name="main", flavors=flavors,
                    resource_usage=dict(pod.requests), count=1)])
            wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                             reason="Imported", now=now)
            wl.set_condition(WorkloadConditionType.ADMITTED, True,
                             reason="Imported", now=now)
            self.store.add_workload(wl)
            res.imported += 1
        return res
