"""Static dashboard frontend (kueueviz's React app analog, build-free).

One self-contained HTML page served at `/`: fetches the JSON APIs
(/api/overview, /api/clusterqueues, /api/cohorts, /api/workloads) and
renders live-refreshing tables. Reference: cmd/kueueviz/frontend —
the same read-only views (queues, cohorts, workloads, status counts)
without the React/Vite toolchain.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>kueue-oss-tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 14px/1.5 system-ui, sans-serif; margin: 2rem; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 2rem; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: .3rem .7rem;
           border-bottom: 1px solid color-mix(in srgb, currentColor 18%, transparent); }
  th { font-weight: 600; }
  .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
          border: 1px solid currentColor; font-size: .85em; }
  #overview span { margin-right: 1.5rem; }
  footer { margin-top: 2rem; opacity: .6; font-size: .85em; }
</style>
</head>
<body>
<h1>kueue-oss-tpu</h1>
<div id="overview">loading…</div>
<h2>ClusterQueues</h2>
<table id="cqs"><thead><tr>
  <th>Name</th><th>Cohort</th><th>Pending</th><th>Inadmissible</th>
  <th>Reserving</th><th>Usage</th></tr></thead><tbody></tbody></table>
<h2>Cohorts</h2>
<table id="cohorts"><thead><tr>
  <th>Name</th><th>Parent</th><th>ClusterQueues</th></tr></thead>
  <tbody></tbody></table>
<h2>Workloads</h2>
<table id="wls"><thead><tr>
  <th>Namespace</th><th>Name</th><th>LocalQueue</th><th>Priority</th>
  <th>Status</th></tr></thead><tbody></tbody></table>
<footer>auto-refreshes every 2s · JSON at /api/*</footer>
<script>
const fmt = (o) => Object.entries(o || {}).map(
    ([k, v]) => `${k}=${v}`).join(" ");
async function refresh() {
  try {
    const [cqs, cohorts, wls] = await Promise.all([
      fetch('/api/clusterqueues').then(r => r.json()),
      fetch('/api/cohorts').then(r => r.json()),
      fetch('/api/workloads').then(r => r.json()),
    ]);
    const counts = {};
    for (const w of wls) counts[w.status] = (counts[w.status] || 0) + 1;
    document.getElementById('overview').innerHTML =
      `<span><b>${cqs.length}</b> ClusterQueues</span>` +
      `<span><b>${wls.length}</b> Workloads</span>` +
      Object.entries(counts)
        .map(([k, v]) => `<span><b>${v}</b> ${k}</span>`).join('');
    const fill = (id, rows) => {
      document.querySelector(`#${id} tbody`).innerHTML =
        rows.map(r => `<tr>${r.map(c => `<td>${c}</td>`).join('')}</tr>`)
            .join('');
    };
    fill('cqs', cqs.map(q => [q.name, q.cohort || '—', q.pending,
                              q.inadmissible, q.reserved,
                              fmt(q.usage)]));
    fill('cohorts', cohorts.map(c => [c.name, c.parent || '—',
                                      (c.clusterQueues || []).join(', ')]));
    fill('wls', wls.map(w => [w.namespace, w.name, w.localQueue,
                              w.priority,
                              `<span class="pill">${w.status}</span>`]));
  } catch (e) { /* server restarting; retry on next tick */ }
}
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
