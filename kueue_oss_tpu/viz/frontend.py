"""Static dashboard frontend (kueueviz's React app analog, build-free).

One self-contained HTML page served at `/`: fetches the JSON APIs
(/api/overview) and renders the cohort hierarchy as a nested tree with
per-ClusterQueue usage bars, plus live-refreshing queue/workload tables,
hash-routed per-resource DETAIL views (#/workload/ns/name, #/cq/name,
#/cohort/name — WorkloadDetail.jsx et al analogs) and live refresh over
SSE (/api/stream; useWebSocket.js analog) with polling fallback.
Reference: cmd/kueueviz/frontend without the React/Vite toolchain.
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>kueue-oss-tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 14px/1.5 system-ui, sans-serif; margin: 2rem;
         max-width: 72rem; }
  h1 { font-size: 1.3rem; }
  h2 { font-size: 1.05rem; margin-top: 2rem; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: .3rem .7rem; border-bottom:
    1px solid color-mix(in srgb, currentColor 18%, transparent); }
  th { font-weight: 600; }
  .pill { display: inline-block; padding: 0 .5rem; border-radius: 999px;
          border: 1px solid currentColor; font-size: .85em; }
  #overview span { margin-right: 1.5rem; }
  ul.tree { list-style: none; padding-left: 1.2rem; }
  ul.tree > li { padding-left: .4rem; }
  ul.tree > li::before { content: "├ "; opacity: .5; }
  ul.tree > li:last-child::before { content: "└ "; }
  .cohort { font-weight: 600; }
  .cq-line { display: inline-flex; align-items: center; gap: .6rem;
             width: calc(100% - 2rem); }
  .cq-name { min-width: 11rem; }
  .bar { flex: 1; max-width: 22rem; height: .8rem; border-radius: 4px;
         border: 1px solid color-mix(in srgb, currentColor 35%,
                                     transparent);
         overflow: hidden; }
  .bar > div { height: 100%; background:
    color-mix(in srgb, currentColor 45%, transparent); }
  .bar > div.over { background: #c0392b; }
  .frac { opacity: .7; font-size: .85em; min-width: 8rem; }
  footer { margin-top: 2rem; opacity: .6; font-size: .85em; }
</style>
</head>
<body>
<h1><a href="#" style="color:inherit;text-decoration:none"
  onclick="location.hash=''">kueue-oss-tpu</a></h1>
<div id="detail" style="display:none"></div>
<div id="main">
<div id="overview">loading…</div>
<h2>Cluster health</h2>
<div id="health">loading…</div>
<table id="slos" style="display:none"><thead><tr>
  <th>Scope</th><th>Key</th><th>Burn 5m</th><th>Burn 1h</th>
  <th>Fast window</th><th>Alert</th><th>Exemplar</th></tr></thead>
  <tbody></tbody></table>
<table id="starve" style="display:none"><thead><tr>
  <th>ClusterQueue</th><th>Oldest pending</th><th>Workload</th>
  <th>Starved</th></tr></thead><tbody></tbody></table>
<h2>Cohort tree</h2>
<div id="tree"></div>
<h2>ClusterQueues</h2>
<table id="cqs"><thead><tr>
  <th>Name</th><th>Cohort</th><th>Pending</th><th>Inadmissible</th>
  <th>Reserving</th><th>Usage</th></tr></thead><tbody></tbody></table>
<h2>LocalQueues</h2>
<table id="lqs"><thead><tr>
  <th>Namespace</th><th>Name</th><th>ClusterQueue</th><th>Pending</th>
  <th>Reserving</th><th>Admitted</th><th>Stop</th></tr></thead>
  <tbody></tbody></table>
<h2>Workloads</h2>
<table id="wls"><thead><tr>
  <th>Namespace</th><th>Name</th><th>LocalQueue</th><th>Priority</th>
  <th>Status</th><th>ClusterQueue</th></tr></thead><tbody></tbody></table>
<h2>ResourceFlavors</h2>
<table id="rfs"><thead><tr>
  <th>Name</th><th>Node labels</th><th>Taints</th><th>Topology</th>
  <th>Used by</th></tr></thead><tbody></tbody></table>
<h2>Topologies</h2>
<table id="tps"><thead><tr>
  <th>Name</th><th>Levels</th><th>Domains per level</th><th>Flavors</th>
  </tr></thead><tbody></tbody></table>
<h2>AdmissionChecks</h2>
<table id="acs"><thead><tr>
  <th>Name</th><th>Controller</th><th>Active</th><th>Waiting workloads</th>
  </tr></thead><tbody></tbody></table>
<h2>What-if planner</h2>
<div id="whatif-form">
  quota factors <input id="wi-factors" value="0.5,1.5,2" size="12">
  on <input id="wi-target" value="*" size="10"
           title="CQ or cohort name glob; a cohort scales its subtree">
  arrival <input id="wi-arrival" value="" size="10"
                 placeholder="e.g. 0.5,2">
  <label title="FULL preemption kernel: real preemption counts,
lane-budgeted batching; overflow rows fall back to the relax LP
(tier column)"><input type="checkbox" id="wi-full"> preemption</label>
  <button onclick="runWhatIf()">simulate</button>
  <button onclick="runLadder()"
          title="double the arrival load until something breaks:
admission SLO, starvation age, or a borrowing ceiling">load
ladder</button>
  <span id="wi-status" class="frac"></span>
</div>
<table id="wis" style="display:none"><thead><tr>
  <th>Scenario</th><th>Tier</th><th>Workloads</th><th>Admitted</th>
  <th>Parked</th><th>Preempt</th><th>Utilization</th>
  <th>Fairness drift</th><th>Rounds</th>
  </tr></thead><tbody></tbody></table>
<div id="wi-ladder" style="display:none">
  <h3>Breaking points</h3>
  <p id="wi-breaks" class="frac"></p>
  <table id="wil"><thead><tr>
    <th>Load</th><th>Tier</th><th>Admission rate</th>
    <th>Starvation p95</th><th>CQs at borrow ceiling</th>
    <th>Preempt</th><th>Breaches</th>
    </tr></thead><tbody></tbody></table>
</div>
</div>
<footer>live over SSE (/api/stream), 2s polling fallback ·
JSON at /api/overview · decision traces at /api/decisions ·
what-if planning at /api/whatif · Prometheus at /metrics</footer>
<script>
const fmt = (o) => Object.entries(o || {}).map(
    ([k, v]) => `${k}=${v}`).join(" ") || "—";
function usageBar(cq) {
  // dominant utilisation across flavor/resource quota columns
  let frac = 0, label = "";
  for (const [k, n] of Object.entries(cq.nominalQuota || {})) {
    const used = (cq.usage || {})[k] || 0;
    if (n > 0 && used / n > frac) { frac = used / n; label = k; }
  }
  const over = frac > 1;
  const pct = Math.min(frac, 1) * 100;
  return `<span class="cq-line"><span class="cq-name">${cq.name}</span>` +
    `<span class="bar"><div class="${over ? "over" : ""}"` +
    ` style="width:${pct}%"></div></span>` +
    `<span class="frac">${(frac * 100).toFixed(0)}%` +
    (label ? ` ${label}` : "") + (over ? " ⚠ borrowing" : "") +
    `</span><span class="frac">${cq.pending || 0} pending</span></span>`;
}
function renderTree(cohorts, cqs) {
  const byName = Object.fromEntries(cohorts.map(c => [c.name, c]));
  const cqByName = Object.fromEntries(cqs.map(q => [q.name, q]));
  const children = {};
  const roots = [];
  for (const c of cohorts) {
    if (c.parent && byName[c.parent]) {
      (children[c.parent] ||= []).push(c.name);
    } else roots.push(c.name);
  }
  function node(name) {
    const c = byName[name];
    const kids = (children[name] || []).map(node).join("");
    const queues = (c.clusterQueues || [])
      .map(q => `<li>${usageBar(cqByName[q] || {name: q})}</li>`)
      .join("");
    return `<li><span class="cohort">${name}</span>` +
      `<ul class="tree">${kids}${queues}</ul></li>`;
  }
  // parentless ClusterQueues render as their own roots
  const solo = cqs.filter(q => !q.cohort)
    .map(q => `<li>${usageBar(q)}</li>`).join("");
  return `<ul class="tree">${roots.map(node).join("")}${solo}</ul>`;
}
async function refresh() {
  try {
    const o = await fetch("/api/overview").then(r => r.json());
    const cqs = o.clusterQueues, wls = o.workloads;
    const counts = {};
    for (const w of wls) counts[w.status] = (counts[w.status] || 0) + 1;
    const sv = o.solver || {};
    const fallbacks = Object.values(sv.fallbacks || {})
      .reduce((a, b) => a + b, 0);
    document.getElementById("overview").innerHTML =
      `<span><b>${cqs.length}</b> ClusterQueues</span>` +
      `<span><b>${o.cohorts.length}</b> Cohorts</span>` +
      `<span><b>${wls.length}</b> Workloads</span>` +
      Object.entries(counts)
        .map(([k, v]) => `<span><b>${v}</b> ${k}</span>`).join("") +
      `<span>solver breaker <b>${sv.breakerState || "closed"}</b>` +
      (sv.breakerTrips ? ` (${sv.breakerTrips} trips)` : "") +
      `</span>` +
      (fallbacks ? `<span><b>${fallbacks}</b> host fallbacks</span>` : "");
    document.getElementById("tree").innerHTML =
      renderTree(o.cohorts, cqs);
    const fill = (id, rows) => {
      document.querySelector(`#${id} tbody`).innerHTML =
        rows.map(r => `<tr>${r.map(c => `<td>${c}</td>`).join("")}</tr>`)
            .join("");
    };
    fill("cqs", cqs.map(q => [
        `<a href="#/cq/${q.name}">${q.name}</a>`,
        q.cohort ? `<a href="#/cohort/${q.cohort}">${q.cohort}</a>` : "—",
        q.pending, q.inadmissible, q.reserved, fmt(q.usage)]));
    fill("wls", wls.slice(0, 300).map(w => [
        w.namespace,
        `<a href="#/workload/${w.namespace}/${w.name}">${w.name}</a>`,
        w.localQueue, w.priority,
        `<span class="pill">${w.status}</span>`,
        w.clusterQueue || "—"]));
    fill("lqs", (o.localQueues || []).map(q => [
        q.namespace, q.name,
        `<a href="#/cq/${q.clusterQueue}">${q.clusterQueue}</a>`,
        q.pending, q.reserving, q.admitted, q.stopPolicy]));
    fill("rfs", (o.resourceFlavors || []).map(f => [
        f.name, fmt(f.nodeLabels), (f.taints || []).join(", ") || "—",
        f.topology || "—", (f.usedBy || []).join(", ") || "—"]));
    fill("tps", (o.topologies || []).map(t => [
        t.name, (t.levels || []).join(" › "),
        (t.domainsPerLevel || []).join("/"),
        (t.flavors || []).join(", ") || "—"]));
    fill("acs", (o.admissionChecks || []).map(a => [
        a.name, a.controller || "—", a.active ? "yes" : "no",
        a.waitingWorkloads]));
  } catch (e) { /* server restarting; retry on next tick */ }
  refreshHealth();
}
async function refreshHealth() {
  // cluster health + SLO section (/api/health, /api/slo)
  try {
    const h = await fetch("/api/health").then(r => r.json());
    const badge = h.status === "ok" ? "✅" :
      (h.status === "degraded" ? "⚠️" : "🔥");
    document.getElementById("health").innerHTML =
      `<span>${badge} <b>${h.status}</b></span> ` +
      `<span>${(h.alertsFiring || []).length} alert(s) firing</span> ` +
      `<span>${(h.starved || []).length} starved CQ(s)</span> ` +
      `<span>breaker ${h.breakerState}</span> ` +
      `<span>${h.invariantViolations} invariant violation(s)</span> ` +
      `<span class="frac">ledger: ${h.ledger.rows} rows, last cycle ` +
      `${h.ledger.lastCycle} (${h.ledger.lastKind})</span>` +
      (h.degradation ? `<br/><span class="frac">degradation: ` +
        Object.entries(h.degradation.subsystems || {}).map(([k, v]) =>
          `${k} L${v.level} (${v.rung})`).join(" · ") + `</span>` : "");
    const s = await fetch("/api/slo").then(r => r.json());
    const slis = s.slis || [];
    const tbl = document.getElementById("slos");
    tbl.style.display = slis.length ? "" : "none";
    tbl.querySelector("tbody").innerHTML = slis.map(x =>
      `<tr><td>${x.scope}</td><td>${x.key}</td>` +
      `<td>${x.burnFast}</td><td>${x.burnSlow}</td>` +
      `<td>${x.fast.bad}/${x.fast.total} bad</td>` +
      `<td><span class="pill">${x.alert.state}</span></td>` +
      `<td>${x.alert.exemplar ? `cycle ${x.alert.exemplar.cycle} · ` +
        `<a href="#/workload/${x.alert.exemplar.workload}">` +
        `${x.alert.exemplar.workload}</a>` : "—"}</td></tr>`).join("");
    const st = s.starvation || [];
    const stb = document.getElementById("starve");
    stb.style.display = st.length ? "" : "none";
    stb.querySelector("tbody").innerHTML = st.map(x =>
      `<tr><td>${x.clusterQueue}</td>` +
      `<td>${Math.round(x.oldestAgeSeconds)}s</td>` +
      `<td>${x.workload}</td>` +
      `<td>${x.starved ? "⚠️ yes" : "no"}</td></tr>`).join("");
  } catch (e) { /* health layer unavailable */ }
}
async function runWhatIf() {
  const status = document.getElementById("wi-status");
  const table = document.getElementById("wis");
  document.getElementById("wi-ladder").style.display = "none";
  status.textContent = "solving…";
  const params = new URLSearchParams();
  params.set("factors", document.getElementById("wi-factors").value);
  params.set("target", document.getElementById("wi-target").value);
  const arr = document.getElementById("wi-arrival").value.trim();
  if (arr) params.set("arrival", arr);
  if (document.getElementById("wi-full").checked)
    params.set("full", "1");
  try {
    const r = await fetch("/api/whatif?" + params.toString());
    const rep = await r.json();
    if (rep.error) { status.textContent = rep.error; return; }
    const t = rep.timing || {};
    const retier = (rep.base || {}).retier;
    status.textContent = `${(rep.scenarios || []).length} scenarios in ` +
      `one dispatch (${t.scenarios_per_sec || "?"}/s, parity ` +
      `${rep.parity && rep.parity.identical ? "ok" : "FAILED"}` +
      (retier ? `, ${retier.indices.length} re-tiered to relax: ` +
                `${retier.reason}` : "") + `)`;
    table.style.display = "";
    document.querySelector("#wis tbody").innerHTML =
      (rep.scenarios || []).map(s => `<tr><td>${s.name}</td>` +
        `<td>${s.tier || "lean"}</td>` +
        `<td>${s.workloads}</td><td>${s.admitted}</td>` +
        `<td>${s.parked}</td><td>${s.preemptions}</td>` +
        `<td>${(s.utilization * 100).toFixed(0)}%</td>` +
        `<td>${s.fairness_drift}</td><td>${s.rounds}</td></tr>`)
      .join("");
  } catch (e) { status.textContent = "what-if unavailable"; }
}
async function runLadder() {
  const status = document.getElementById("wi-status");
  document.getElementById("wis").style.display = "none";
  const box = document.getElementById("wi-ladder");
  status.textContent = "climbing the load ladder…";
  const params = new URLSearchParams();
  params.set("ladder", "1,2,4,8");
  if (document.getElementById("wi-full").checked)
    params.set("full", "1");
  try {
    const r = await fetch("/api/whatif?" + params.toString());
    const res = await r.json();
    if (res.error) { status.textContent = res.error; return; }
    status.textContent = `${(res.ladder || []).length} rungs`;
    const firsts = [
      ["SLO burn", res.first_slo_burn],
      ["starvation breach", res.first_starvation_breach],
      ["borrow ceiling", res.first_borrow_ceiling]];
    document.getElementById("wi-breaks").textContent =
      (res.what_breaks_first
        ? `first to break: ${res.what_breaks_first.replace(/_/g, " ")} — `
        : "nothing breaks on this ladder — ") +
      firsts.map(([n, f]) =>
        `${n}: ${f == null ? "never" : "x" + f}`).join(", ");
    box.style.display = "";
    document.querySelector("#wil tbody").innerHTML =
      (res.ladder || []).map(s => `<tr><td>x${s.factor}</td>` +
        `<td>${s.tier || "lean"}</td>` +
        `<td>${(s.admission_rate * 100).toFixed(0)}%</td>` +
        `<td>${Math.round(s.starvation_age_p95)}s</td>` +
        `<td>${s.cqs_at_borrow_ceiling}</td>` +
        `<td>${s.preemptions}</td>` +
        `<td>${Object.entries(s.breaches || {}).filter(([, v]) => v)
                .map(([k]) => k.replace(/_/g, " ")).join(", ") || "—"}` +
        `</td></tr>`).join("");
  } catch (e) { status.textContent = "what-if unavailable"; }
}
const obj = (o) => `<table><tbody>` + Object.entries(o || {}).map(
  ([k, v]) => `<tr><th>${k}</th><td><pre style="margin:0">` +
    `${typeof v === "object" ? JSON.stringify(v, null, 1) : v}` +
    `</pre></td></tr>`).join("") + `</tbody></table>`;
async function renderDetail() {
  const h = location.hash.replace(/^#\\/?/, "");
  const main = document.getElementById("main");
  const det = document.getElementById("detail");
  if (!h) { main.style.display = ""; det.style.display = "none"; return; }
  const parts = h.split("/");
  let url = null, title = "";
  if (parts[0] === "workload" && parts.length === 3) {
    url = `/api/workloads/${parts[1]}/${parts[2]}`;
    title = `Workload ${parts[1]}/${parts[2]}`;
  } else if (parts[0] === "cq" && parts.length === 2) {
    url = `/api/clusterqueues/${parts[1]}`;
    title = `ClusterQueue ${parts[1]}`;
  } else if (parts[0] === "cohort" && parts.length === 2) {
    url = `/api/cohorts/${parts[1]}`;
    title = `Cohort ${parts[1]}`;
  }
  if (!url) { location.hash = ""; return; }
  main.style.display = "none"; det.style.display = "";
  try {
    const r = await fetch(url);
    let body = r.ok ? obj(await r.json()) : `<p>not found</p>`;
    if (parts[0] === "workload" && r.ok) {
      // the flight recorder's answer to "why is my job still pending?"
      const ex = await fetch(url + "/explain");
      if (ex.ok) {
        const events = (await ex.json()).events || [];
        body += `<h3>Decision trace (newest first)</h3>` +
          `<table><thead><tr><th>cycle</th><th>path</th><th>kind</th>` +
          `<th>reason</th></tr></thead><tbody>` +
          events.map(e => `<tr><td>${e.cycle}</td><td>${e.path}</td>` +
            `<td><span class="pill">${e.kind}</span></td>` +
            `<td>${e.reason || e.reasonSlug || ""}</td></tr>`).join("") +
          `</tbody></table>`;
      }
    }
    det.innerHTML = `<h2>${title}</h2>` + body +
      `<p><a href="#" onclick="location.hash=''">← back</a></p>`;
  } catch (e) { det.innerHTML = `<p>unavailable</p>`; }
}
function onChange() { refresh(); renderDetail(); }
window.addEventListener("hashchange", renderDetail);
onChange();
let sse = null;
try {
  sse = new EventSource("/api/stream");
  sse.onmessage = onChange;
  sse.onerror = () => { /* fall back to polling below */ };
} catch (e) {}
setInterval(() => { if (!sse || sse.readyState === 2) onChange(); }, 2000);
</script>
</body>
</html>
"""
