"""kueueviz-style dashboard backend.

Reference parity: cmd/kueueviz (Go/gin backend streaming cluster state to
a React frontend over websockets). The dashboard surface here is a JSON
snapshot API — the same aggregate views the kueueviz frontend renders
(cluster queues with usage/pending, cohort tree, workload listing) served
from the store, pollable over HTTP or consumed directly by tooling.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kueue_oss_tpu.api.types import iter_quotas
from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store


class Dashboard:
    def __init__(self, store: Store, queues: QueueManager) -> None:
        self.store = store
        self.queues = queues

    # -- views (kueueviz backend endpoints) ---------------------------------

    def cluster_queues_view(self) -> list[dict]:
        usage: dict[str, dict[str, int]] = {}
        counts: dict[str, dict[str, int]] = {}
        for wl in self.store.workloads.values():
            adm = wl.status.admission
            if adm is None or wl.is_finished:
                continue
            cq = adm.cluster_queue
            c = counts.setdefault(cq, {"admitted": 0, "reserved": 0})
            if wl.is_admitted:
                c["admitted"] += 1
            if wl.is_quota_reserved:
                c["reserved"] += 1
                u = usage.setdefault(cq, {})
                for psa in adm.podset_assignments:
                    for r, q in psa.resource_usage.items():
                        key = f"{psa.flavors.get(r, '?')}/{r}"
                        u[key] = u.get(key, 0) + q
        out = []
        for name, cq in sorted(self.store.cluster_queues.items()):
            q = self.queues.queues.get(name)
            nominal = {f"{fl}/{r}": rq.nominal
                       for (fl, r), rq in iter_quotas(cq.resource_groups)}
            out.append({
                "name": name,
                "cohort": cq.cohort,
                "strategy": cq.queueing_strategy,
                "stopPolicy": cq.stop_policy,
                "nominalQuota": nominal,
                "usage": usage.get(name, {}),
                "pending": (q.pending_active if q else 0),
                "inadmissible": (q.pending_inadmissible if q else 0),
                **counts.get(name, {"admitted": 0, "reserved": 0}),
            })
        return out

    def cohorts_view(self) -> list[dict]:
        out = []
        for name, cohort in sorted(self.store.cohorts.items()):
            members = sorted(
                cq.name for cq in self.store.cluster_queues.values()
                if cq.cohort == name)
            out.append({"name": name, "parent": cohort.parent,
                        "clusterQueues": members})
        return out

    def workloads_view(self, namespace: Optional[str] = None) -> list[dict]:
        from kueue_oss_tpu.core.workload_info import workload_status

        out = []
        for wl in sorted(self.store.workloads.values(), key=lambda w: w.key):
            if namespace is not None and wl.namespace != namespace:
                continue
            out.append({
                "namespace": wl.namespace,
                "name": wl.name,
                "localQueue": wl.queue_name,
                "priority": wl.priority,
                "status": workload_status(wl),
                "clusterQueue": (wl.status.admission.cluster_queue
                                 if wl.status.admission else None),
            })
        return out

    def overview(self) -> dict:
        return {
            "clusterQueues": self.cluster_queues_view(),
            "cohorts": self.cohorts_view(),
            "workloads": self.workloads_view(),
        }


class DashboardServer:
    """GET / (HTML dashboard) + /api/clusterqueues | /api/cohorts |
    /api/workloads | /api/overview"""

    def __init__(self, dashboard: Dashboard, port: int = 0) -> None:
        dash = dashboard

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self) -> None:
                if self.path in ("", "/", "/index.html"):
                    from kueue_oss_tpu.viz.frontend import INDEX_HTML

                    body = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                routes = {
                    "/api/clusterqueues": dash.cluster_queues_view,
                    "/api/cohorts": dash.cohorts_view,
                    "/api/workloads": dash.workloads_view,
                    "/api/overview": dash.overview,
                }
                fn = routes.get(self.path.rstrip("/"))
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(fn()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]

    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
