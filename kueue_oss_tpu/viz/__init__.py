"""kueueviz-style dashboard backend.

Reference parity: cmd/kueueviz (Go/gin backend streaming cluster state to
a React frontend over websockets; per-resource detail views like
WorkloadDetail.jsx / ClusterQueueDetail.jsx / CohortDetail.jsx). The
dashboard surface here is a JSON snapshot API plus per-resource DETAIL
endpoints and an SSE live stream (/api/stream) — store watch events push
fresh snapshots to connected clients the way the reference's
useWebSocket.js hook refreshes its views.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kueue_oss_tpu.api.types import iter_quotas
from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store


class Dashboard:
    def __init__(self, store: Store, queues: QueueManager,
                 recorder=None, sim_config=None) -> None:
        from kueue_oss_tpu import obs

        self.store = store
        self.queues = queues
        #: SimulatorConfig governing /api/whatif sweeps (an operator's
        #: Configuration.simulator block plugs in here); None = the
        #: defaults (mesh off, 256-scenario cap, 2 parity checks)
        self.sim_config = sim_config
        #: decision flight recorder backing /api/decisions and the
        #: per-workload explain endpoint (defaults to the process-wide
        #: journal the scheduler/solver emit into)
        self.recorder = recorder if recorder is not None else obs.recorder
        #: the solver-farm scheduler behind /api/farm/weights (attach
        #: via ``dash.farm = scheduler`` when federation is on)
        self.farm = None
        #: the Tracer behind /api/trace (attach via ``dash.tracer =
        #: engine.debugger.tracer`` — or any Tracer merging fabric
        #: spans); None = 404-equivalent empty export
        self.tracer = None
        #: bumped on every store event; SSE clients wake on it
        self._gen = 0
        #: (monotonic wall, report) memo shared by slo_view and
        #: health_view — the frontend fetches both endpoints on one
        #: refresh tick, and each full evaluation walks every SLI key
        #: plus every pending workload under the QueueManager mutex
        self._slo_memo: tuple[float, dict] | None = None
        self._cond = threading.Condition()
        store.watch(self._on_event)

    def _on_event(self, event) -> None:
        with self._cond:
            self._gen += 1
            self._cond.notify_all()

    def wait_for_change(self, gen: int, timeout: float = 15.0) -> int:
        """Block until the store generation passes ``gen`` (or timeout);
        returns the current generation."""
        with self._cond:
            if self._gen == gen:
                self._cond.wait(timeout)
            return self._gen

    # -- views (kueueviz backend endpoints) ---------------------------------

    def cluster_queues_view(self) -> list[dict]:
        usage: dict[str, dict[str, int]] = {}
        counts: dict[str, dict[str, int]] = {}
        for wl in self.store.workloads.values():
            adm = wl.status.admission
            if adm is None or wl.is_finished:
                continue
            cq = adm.cluster_queue
            c = counts.setdefault(cq, {"admitted": 0, "reserved": 0})
            if wl.is_admitted:
                c["admitted"] += 1
            if wl.is_quota_reserved:
                c["reserved"] += 1
                u = usage.setdefault(cq, {})
                for psa in adm.podset_assignments:
                    for r, q in psa.resource_usage.items():
                        key = f"{psa.flavors.get(r, '?')}/{r}"
                        u[key] = u.get(key, 0) + q
        out = []
        for name, cq in sorted(self.store.cluster_queues.items()):
            q = self.queues.queues.get(name)
            nominal = {f"{fl}/{r}": rq.nominal
                       for (fl, r), rq in iter_quotas(cq.resource_groups)}
            out.append({
                "name": name,
                "cohort": cq.cohort,
                "strategy": cq.queueing_strategy,
                "stopPolicy": cq.stop_policy,
                "nominalQuota": nominal,
                "usage": usage.get(name, {}),
                "pending": (q.pending_active if q else 0),
                "inadmissible": (q.pending_inadmissible if q else 0),
                **counts.get(name, {"admitted": 0, "reserved": 0}),
            })
        return out

    def cohorts_view(self) -> list[dict]:
        out = []
        for name, cohort in sorted(self.store.cohorts.items()):
            members = sorted(
                cq.name for cq in self.store.cluster_queues.values()
                if cq.cohort == name)
            out.append({"name": name, "parent": cohort.parent,
                        "clusterQueues": members})
        return out

    def workloads_view(self, namespace: Optional[str] = None) -> list[dict]:
        from kueue_oss_tpu.core.workload_info import workload_status

        out = []
        for wl in sorted(self.store.workloads.values(), key=lambda w: w.key):
            if namespace is not None and wl.namespace != namespace:
                continue
            out.append({
                "namespace": wl.namespace,
                "name": wl.name,
                "localQueue": wl.queue_name,
                "priority": wl.priority,
                "status": workload_status(wl),
                "clusterQueue": (wl.status.admission.cluster_queue
                                 if wl.status.admission else None),
            })
        return out

    def local_queues_view(self) -> list[dict]:
        from kueue_oss_tpu.controllers.core_controllers import (
            LocalQueueReconciler,
        )

        counts = LocalQueueReconciler(self.store)._counts_by_lq()
        out = []
        for lq in sorted(self.store.local_queues.values(),
                         key=lambda l: l.key):
            pending, reserving, admitted = counts.get(
                (lq.namespace, lq.name), (0, 0, 0))
            out.append({"namespace": lq.namespace, "name": lq.name,
                        "clusterQueue": lq.cluster_queue,
                        "stopPolicy": lq.stop_policy,
                        "pending": pending, "reserving": reserving,
                        "admitted": admitted})
        return out

    def resource_flavors_view(self) -> list[dict]:
        from kueue_oss_tpu.api.types import format_taint

        out = []
        for rf in sorted(self.store.resource_flavors.values(),
                         key=lambda r: r.name):
            out.append({
                "name": rf.name,
                "nodeLabels": dict(rf.node_labels),
                "taints": [format_taint(t) for t in rf.node_taints],
                "topology": rf.topology_name,
                "usedBy": self.store.cluster_queues_using_flavor(rf.name),
            })
        return out

    def topologies_view(self) -> list[dict]:
        # distinct label-prefix tuples per level in ONE node pass — the
        # SSE loop serializes overview() per store change per client, so
        # this must not build Domain trees (snapshot construction is
        # O(topologies x nodes x levels) with allocation-heavy rollups)
        out = []
        nodes = list(self.store.nodes.values())
        for t in sorted(self.store.topologies.values(),
                        key=lambda t: t.name):
            per_level: list[set] = [set() for _ in t.levels]
            for n in nodes:
                values = []
                for li, key in enumerate(t.levels):
                    v = (n.name if key == "kubernetes.io/hostname"
                         else n.labels.get(key))
                    if v is None:
                        break
                    values.append(v)
                    per_level[li].add(tuple(values))
            out.append({
                "name": t.name,
                "levels": list(t.levels),
                "domainsPerLevel": [len(s) for s in per_level],
                "flavors": sorted(
                    rf.name for rf in self.store.resource_flavors.values()
                    if rf.topology_name == t.name),
            })
        return out

    def admission_checks_view(self) -> list[dict]:
        # workloads currently gated per check (AdmissionChecks.jsx)
        waiting: dict[str, int] = {}
        for wl in self.store.workloads.values():
            for name, st in wl.status.admission_checks.items():
                if st.state in ("Pending", "Retry"):
                    waiting[name] = waiting.get(name, 0) + 1
        out = []
        for ac in sorted(self.store.admission_checks.values(),
                         key=lambda a: a.name):
            out.append({
                "name": ac.name,
                "controller": ac.controller_name,
                "active": ac.status.active,
                "waitingWorkloads": waiting.get(ac.name, 0),
            })
        return out

    def solver_view(self) -> dict:
        """Solver-backend resilience at a glance: breaker state and the
        PR-3 degradation counters, so a tripped breaker is visible on
        the overview without scraping /metrics."""
        from kueue_oss_tpu import metrics, obs

        return {
            "breakerState": obs.breaker_state_name(),
            "breakerTrips": int(
                metrics.solver_breaker_trips_total.total()),
            "fallbacks": {k[0]: int(v) for k, v in
                          metrics.solver_fallback_total.collect().items()},
            "remoteFailures": {
                k[0]: int(v) for k, v in
                metrics.solver_remote_failures_total.collect().items()},
            "planRejected": int(
                metrics.solver_plan_rejected_total.total()),
        }

    def overview(self) -> dict:
        return {
            "clusterQueues": self.cluster_queues_view(),
            "cohorts": self.cohorts_view(),
            "workloads": self.workloads_view(),
            "localQueues": self.local_queues_view(),
            "resourceFlavors": self.resource_flavors_view(),
            "topologies": self.topologies_view(),
            "admissionChecks": self.admission_checks_view(),
            "solver": self.solver_view(),
        }

    # -- what-if planning (sim/, docs/SIMULATOR.md) ------------------------

    def whatif_view(self, factors=None, target: str = "*",
                    arrival=None, max_scenarios: int = 64,
                    full=None, ladder=None) -> dict:
        """Counterfactual sweep over the LIVE store's current backlog:
        quota factors (x arrival factors when given) on the matched CQ
        or cohort, solved in one vmapped dispatch. The capacity-planning
        answer straight from the dashboard.

        ``full`` routes through the FULL preemption kernel
        (lane-budgeted; relax-LP re-tiers reported per row); ``ladder``
        switches to the breaking-point load ladder over the given
        arrival factors ("what breaks first as load doubles",
        sim/traces.py)."""
        from kueue_oss_tpu.config.configuration import SimulatorConfig
        from kueue_oss_tpu.sim import (
            WhatIfEngine,
            arrival_sweep,
            cross,
            load_ladder,
            quota_sweep,
        )
        from kueue_oss_tpu.solver.tensors import UnsupportedProblem

        cfg = (self.sim_config if self.sim_config is not None
               else SimulatorConfig())
        if ladder:
            try:
                res = load_ladder(self.store, factors=list(ladder),
                                  queues=self.queues, config=cfg,
                                  full=full)
            except (UnsupportedProblem, ValueError) as e:
                return {"error": str(e)}
            res["report"] = res["report"].to_dict()
            return res
        factors = list(factors or (0.5, 1.5, 2.0))
        specs = quota_sweep(factors, target=target)
        if arrival:
            specs = cross(specs, arrival_sweep(list(arrival)))
        specs = specs[:max(1, min(max_scenarios, cfg.max_scenarios))]
        engine = WhatIfEngine(self.store, self.queues, config=cfg)
        try:
            report = engine.run(specs, full=full)
        except (UnsupportedProblem, ValueError) as e:
            return {"error": str(e)}
        return report.to_dict()

    # -- flight-recorder views (obs/) ---------------------------------------

    def workload_explain(self, namespace: str, name: str) -> Optional[dict]:
        """The workload's decision history, newest-first — the answer to
        'why is my job still pending?'. None only when the workload is
        unknown AND the journal has nothing for it."""
        key = f"{namespace}/{name}"
        events = self.recorder.explain(key)
        if not events and key not in self.store.workloads:
            return None
        return {"workload": key,
                "events": [ev.to_dict() for ev in events]}

    def decisions_view(self, last_cycles: int = 10) -> dict:
        """Per-cycle decision groups, each carrying its ledger rows
        (the host cycle row and, when a drain served the cycle, the
        solver row) — the decision chain and the cycle's timing/
        routing record join on the cycle id."""
        from kueue_oss_tpu import obs

        cycles = self.recorder.decisions(last_cycles)
        wanted = {group["cycle"] for group in cycles}
        by_cycle: dict[int, list] = {}
        for row in obs.cycle_ledger.rows():
            # serialize only the cycles this response returns — the
            # ring holds up to max_cycles (4096) rows and this view is
            # polled on the frontend's refresh tick
            if row.cycle in wanted:
                by_cycle.setdefault(row.cycle, []).append(row.to_dict())
        for group in cycles:
            rows = by_cycle.get(group["cycle"])
            if rows:
                group["ledger"] = rows
        return {"cycles": cycles}

    # -- cluster health & SLOs (obs/health.py, obs/ledger.py) ---------------

    def _slo_report(self) -> dict:
        """One evaluation shared across the endpoints hit in a single
        frontend refresh tick (coalesced for ~1s of wall time)."""
        import time as _time

        from kueue_oss_tpu import obs

        now = _time.monotonic()
        memo = self._slo_memo
        if memo is not None and now - memo[0] < 1.0:
            return memo[1]
        report = obs.slo_engine.evaluate(queues=self.queues)
        self._slo_memo = (now, report)
        return report

    def slo_view(self) -> dict:
        """The SLO engine's full report: per-CQ/per-priority SLIs with
        burn rates + alert states, and the starvation watchdog fed
        from the live queues (GET /api/slo)."""
        return self._slo_report()

    def health_view(self) -> dict:
        """One-look cluster health (GET /api/health): worst-signal
        status rollup over the burn-rate alerts, the starvation
        watchdog, the solver breaker, the invariant auditor, and the
        ledger-driven phase-regression detector."""
        from kueue_oss_tpu import metrics, obs

        from kueue_oss_tpu import resilience

        report = self._slo_report()
        firing = report["alerts"]
        starved = [s for s in report["starvation"] if s["starved"]]
        breaker = obs.breaker_state_name()
        violations = int(metrics.invariant_last_violations.value())
        regressions = obs.phase_regression.regressing()
        degradation = resilience.controller.snapshot()
        if firing or violations:
            status = "critical"
        elif (starved or breaker != "closed" or regressions
                or degradation["degraded"]):
            status = "degraded"
        else:
            status = "ok"
        last = obs.cycle_ledger.last_row()
        return {
            "status": status,
            "alertsFiring": firing,
            "starved": starved,
            "breakerState": breaker,
            "invariantViolations": violations,
            "phaseRegressions": regressions,
            "degradation": degradation,
            "ledger": {
                "rows": len(obs.cycle_ledger.rows()),
                "lastCycle": last.cycle if last is not None else 0,
                "lastKind": last.kind if last is not None else "",
            },
            "objective": report["objective"],
        }

    def degradation_view(self) -> dict:
        """The degradation-ladder rollup + recent transitions (GET
        /api/degradation): per-subsystem level/rung/conditions from the
        process-wide DegradationController."""
        from kueue_oss_tpu import resilience

        ctl = resilience.controller
        snap = ctl.snapshot()
        snap["recentTransitions"] = list(ctl.history[-50:])
        return snap

    def farm_weights_view(self) -> dict:
        """The solver farm's live DRR weights (GET /api/farm/weights)."""
        if self.farm is None:
            return {"attached": False}
        return {"attached": True,
                "weights": dict(self.farm.weights),
                "defaultWeight": self.farm.default_weight,
                "stats": self.farm.stats()}

    def set_farm_weights(self, payload: dict) -> dict:
        """Runtime re-weighting (POST /api/farm/weights): body
        ``{"weights": {tenant: w}, "defaultWeight": w}``; either key
        optional. Takes effect within one ring walk."""
        if self.farm is None:
            return {"ok": False, "error": "no farm attached"}
        weights = payload.get("weights")
        if weights is not None and not isinstance(weights, dict):
            return {"ok": False, "error": "weights must be an object"}
        try:
            effective = self.farm.set_weights(
                weights, payload.get("defaultWeight"))
        except (TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad weights: {e}"}
        return {"ok": True, "weights": effective,
                "defaultWeight": self.farm.default_weight}

    def trace_view(self, last_cycles: int = 0) -> dict:
        """Chrome-trace export of the merged fabric timeline (GET
        /api/trace[?cycles=N]): host drain spans, farm grant-waits and
        sidecar/mesh solves on their own synthetic tracks. ``cycles``
        windows to the newest N distinct cycle ids (0 = the whole
        bounded ring — the Tracer ring already caps the export)."""
        if self.tracer is None:
            return {"attached": False, "traceEvents": []}
        spans = self.tracer.spans()
        if last_cycles > 0:
            cycles = sorted({(a or {}).get("cycle")
                             for (_, _, _, _, a) in spans}
                            - {None})
            keep = set(cycles[-last_cycles:])
            spans = [s for s in spans
                     if (s[4] or {}).get("cycle") is None
                     or s[4]["cycle"] in keep]
        return json.loads(self.tracer.chrome_trace(spans=spans))

    def telemetry_view(self) -> dict:
        """Device-telemetry status (GET /api/telemetry): collector
        flags, compile-detector summary, deep-capture arm/active/
        cooldown state and recent capture artifacts."""
        from kueue_oss_tpu.obs import devtel

        return devtel.collector.status()

    def telemetry_post(self, payload: dict) -> dict:
        """Capture control (POST /api/telemetry): body ``{"action":
        "arm"|"disarm"|"trigger"|"stop"}``; ``trigger`` takes an
        optional ``reason`` detail string. Arm/disarm gate the
        tail-capture trigger path; trigger starts a manual capture
        (subject to the single-slot and cooldown gates); stop
        force-finishes the in-flight capture."""
        from kueue_oss_tpu.obs import devtel

        cap = devtel.collector.capture
        action = payload.get("action")
        if action == "arm":
            cap.armed = True
        elif action == "disarm":
            cap.armed = False
        elif action == "trigger":
            started = cap.trigger(
                "manual", {"reason": str(payload.get("reason", "api"))})
            if not started:
                return {"ok": False,
                        "error": "capture suppressed (disarmed, busy, "
                                 "or cooling down)",
                        "status": devtel.collector.status()}
        elif action == "stop":
            cap.stop()
        else:
            return {"ok": False,
                    "error": "action must be one of arm, disarm, "
                             "trigger, stop"}
        return {"ok": True, "status": devtel.collector.status()}

    # -- per-resource detail views (WorkloadDetail.jsx et al) ---------------

    def workload_detail(self, namespace: str, name: str) -> Optional[dict]:
        from kueue_oss_tpu.core.workload_info import workload_status

        wl = self.store.workloads.get(f"{namespace}/{name}")
        if wl is None:
            return None
        adm = wl.status.admission
        return {
            "namespace": wl.namespace,
            "name": wl.name,
            "localQueue": wl.queue_name,
            "priority": wl.priority,
            "priorityClass": wl.priority_class,
            "status": workload_status(wl),
            "active": wl.active,
            "podSets": [{
                "name": ps.name, "count": ps.count,
                "requests": dict(ps.requests),
                "minCount": ps.min_count,
            } for ps in wl.podsets],
            "conditions": [{
                "type": t, "status": c.status, "reason": c.reason,
                "message": c.message,
                "lastTransitionTime": c.last_transition_time,
            } for t, c in sorted(wl.status.conditions.items())],
            "admission": None if adm is None else {
                "clusterQueue": adm.cluster_queue,
                "podSetAssignments": [{
                    "name": psa.name, "count": psa.count,
                    "flavors": dict(psa.flavors),
                    "resourceUsage": dict(psa.resource_usage),
                    "topologyAssignment": None
                    if psa.topology_assignment is None else {
                        "levels": list(psa.topology_assignment.levels),
                        "domains": [{
                            "values": list(d.values), "count": d.count}
                            for d in psa.topology_assignment.domains],
                    },
                } for psa in adm.podset_assignments],
            },
            "admissionChecks": [{
                "name": n, "state": s.state, "message": s.message,
            } for n, s in sorted(wl.status.admission_checks.items())],
        }

    def cluster_queue_detail(self, name: str) -> Optional[dict]:
        cq = self.store.cluster_queues.get(name)
        if cq is None:
            return None
        base = next((v for v in self.cluster_queues_view()
                     if v["name"] == name), {})
        q = self.queues.queues.get(name)
        pending = []
        if q is not None:
            from kueue_oss_tpu.core.workload_info import effective_priority

            for pos, info in enumerate(q.snapshot_order()):
                pending.append({
                    "namespace": info.obj.namespace,
                    "name": info.obj.name,
                    "position": pos,
                    "priority": effective_priority(info.obj),
                })
            for key in q.inadmissible:
                wl = self.store.workloads.get(key)
                if wl is not None:
                    pending.append({
                        "namespace": wl.namespace, "name": wl.name,
                        "position": "inadmissible",
                        "priority": wl.priority,
                    })
        admitted = [
            {"namespace": wl.namespace, "name": wl.name}
            for wl in sorted(self.store.workloads.values(),
                             key=lambda w: w.key)
            if wl.is_quota_reserved and not wl.is_finished
            and wl.status.admission is not None
            and wl.status.admission.cluster_queue == name]
        return {
            **base,
            "preemption": {
                "withinClusterQueue": cq.preemption.within_cluster_queue,
                "reclaimWithinCohort": cq.preemption.reclaim_within_cohort,
            },
            "fairWeight": cq.fair_sharing.weight,
            "flavors": [fq.name for rg in cq.resource_groups
                        for fq in rg.flavors],
            "admissionChecks": list(cq.admission_checks),
            "pendingWorkloads": pending,
            "admittedWorkloads": admitted,
        }

    def cohort_detail(self, name: str) -> Optional[dict]:
        cohort = self.store.cohorts.get(name)
        members = sorted(cq.name for cq in self.store.cluster_queues.values()
                         if cq.cohort == name)
        if cohort is None and not members:
            return None
        from kueue_oss_tpu.core.snapshot import build_snapshot

        snap = build_snapshot(self.store)
        cq_views = {v["name"]: v for v in self.cluster_queues_view()}
        subtree_quota: dict[str, int] = {}
        subtree_usage: dict[str, int] = {}
        node = snap.forest.nodes.get(name)
        if node is not None:
            for (fl, r), v in node.subtree_quota.items():
                subtree_quota[f"{fl}/{r}"] = v
            for (fl, r), v in node.usage.items():
                subtree_usage[f"{fl}/{r}"] = v
        return {
            "name": name,
            "parent": cohort.parent if cohort is not None else None,
            "subtreeQuota": subtree_quota,
            "subtreeUsage": subtree_usage,
            "clusterQueues": [cq_views.get(m, {"name": m})
                              for m in members],
        }


class DashboardServer:
    """GET / (HTML dashboard) + /api/clusterqueues | /api/cohorts |
    /api/workloads | /api/overview"""

    def __init__(self, dashboard: Dashboard, port: int = 0) -> None:
        dash = dashboard

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self) -> None:
                if self.path in ("", "/", "/index.html"):
                    from kueue_oss_tpu.viz.frontend import INDEX_HTML

                    body = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics":
                    # Prometheus text exposition (registry render);
                    # OpenMetrics (with exemplars + # EOF) under
                    # standard content negotiation or ?format=
                    from urllib.parse import parse_qs, urlparse

                    from kueue_oss_tpu import metrics as kmetrics

                    qs = parse_qs(urlparse(self.path).query)
                    accept = self.headers.get("Accept", "")
                    om = ("openmetrics" in accept
                          or "openmetrics" in qs.get("format", [""]))
                    body = kmetrics.registry.render(
                        openmetrics=om).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8" if om else
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/api/decisions":
                    from urllib.parse import parse_qs, urlparse

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        n = int(qs.get("cycles", ["10"])[0])
                    except ValueError:
                        n = 10
                    body = json.dumps(dash.decisions_view(n)).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/api/trace":
                    from urllib.parse import parse_qs, urlparse

                    qs = parse_qs(urlparse(self.path).query)
                    try:
                        n = int(qs.get("cycles", ["0"])[0])
                    except ValueError:
                        n = 0
                    body = json.dumps(dash.trace_view(n)).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/api/whatif":
                    from urllib.parse import parse_qs, urlparse

                    qs = parse_qs(urlparse(self.path).query)

                    def floats(key):
                        # malformed numbers are a caller error: answer
                        # 400, never a silently different sweep
                        raw = ",".join(qs.get(key, []))
                        try:
                            return [float(x) for x in raw.split(",")
                                    if x.strip()]
                        except ValueError:
                            raise ValueError(
                                f"{key} must be comma-separated "
                                f"numbers, got {raw!r}")

                    raw_full = qs.get("full", [None])[0]
                    full = (None if raw_full is None
                            else raw_full.lower() in ("1", "true",
                                                      "yes", "on"))
                    try:
                        view = dash.whatif_view(
                            factors=floats("factors") or None,
                            target=qs.get("target", ["*"])[0],
                            arrival=floats("arrival") or None,
                            full=full,
                            ladder=floats("ladder") or None)
                    except ValueError as e:
                        view = {"error": str(e)}
                    body = json.dumps(view).encode()
                    self.send_response(400 if "error" in view else 200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/api/stream":
                    # SSE live refresh (useWebSocket.js analog): push an
                    # overview snapshot on every store change, with a
                    # keepalive comment on idle timeouts
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    gen = -1
                    try:
                        while True:
                            new_gen = dash.wait_for_change(gen, timeout=5.0)
                            if new_gen == gen:
                                self.wfile.write(b": keepalive\n\n")
                            else:
                                gen = new_gen
                                body = json.dumps(dash.overview())
                                self.wfile.write(
                                    f"data: {body}\n\n".encode())
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        return
                # per-resource detail endpoints
                detail = None
                parts = path.strip("/").split("/")
                if (len(parts) == 5 and parts[:2] == ["api", "workloads"]
                        and parts[4] == "explain"):
                    detail = dash.workload_explain(parts[2], parts[3])
                    if detail is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                elif len(parts) == 4 and parts[:2] == ["api", "workloads"]:
                    detail = dash.workload_detail(parts[2], parts[3])
                elif len(parts) == 3 and parts[1] == "clusterqueues":
                    detail = dash.cluster_queue_detail(parts[2])
                elif len(parts) == 3 and parts[1] == "cohorts":
                    detail = dash.cohort_detail(parts[2])
                if detail is not None:
                    body = json.dumps(detail).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                routes = {
                    "/api/clusterqueues": dash.cluster_queues_view,
                    "/api/cohorts": dash.cohorts_view,
                    "/api/workloads": dash.workloads_view,
                    "/api/localqueues": dash.local_queues_view,
                    "/api/resourceflavors": dash.resource_flavors_view,
                    "/api/topologies": dash.topologies_view,
                    "/api/admissionchecks": dash.admission_checks_view,
                    "/api/overview": dash.overview,
                    "/api/slo": dash.slo_view,
                    "/api/health": dash.health_view,
                    "/api/degradation": dash.degradation_view,
                    "/api/farm/weights": dash.farm_weights_view,
                    "/api/telemetry": dash.telemetry_view,
                }
                fn = routes.get(path)
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(fn()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                path = self.path.split("?", 1)[0]
                posts = {"/api/farm/weights": dash.set_farm_weights,
                         "/api/telemetry": dash.telemetry_post}
                fn = posts.get(path)
                if fn is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, TypeError) as e:
                    out = {"ok": False, "error": f"bad request: {e}"}
                    code = 400
                else:
                    out = fn(payload)
                    code = 200 if out.get("ok") else 409
                body = json.dumps(out).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]

    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
