"""Federated control-plane fleet helpers (docs/FEDERATION.md).

A *member* is one complete control plane — Store, QueueManager,
Scheduler, SolverEngine — whose engine talks to a SHARED solver farm
through a tenant-tagged ``SolverClient``. N members against one
sidecar is the "many clusters, one brain" topology of ROADMAP item 4;
these helpers build that wiring for tests, the bench federation
scenario, and the chaos member-loss harness.

The per-member plan contract: because the farm namespaces sessions by
tenant and the DRR only reorders WHO solves next (never what a solve
returns), a member's admitted/parked plans must be bit-identical to
the same control plane running against a dedicated sidecar —
``plan_fingerprint`` is the equality the tests assert.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.scheduler.scheduler import Scheduler
from kueue_oss_tpu.solver.engine import SolverEngine
from kueue_oss_tpu.solver.service import SolverClient


@dataclasses.dataclass
class FederationMember:
    """One tenant control plane bound to the shared farm."""

    name: str
    store: Store
    queues: QueueManager
    scheduler: Scheduler
    engine: SolverEngine

    def drain(self, now: float = 0.0):
        return self.engine.drain(now=now)


def build_member(name: str,
                 socket_path: Optional[str] = None,
                 store: Optional[Store] = None,
                 seed: Optional[Callable[[Store], None]] = None,
                 pad_to: Optional[int] = None,
                 **client_kwargs) -> FederationMember:
    """Build one control plane. With ``socket_path`` the engine solves
    remotely as tenant ``name``; without it the member runs host-side
    (the dedicated-baseline twin in parity tests). ``seed`` populates
    the (fresh or supplied) store before the queue manager attaches."""
    store = store if store is not None else Store()
    if seed is not None:
        seed(store)
    queues = QueueManager(store)
    scheduler = Scheduler(store, queues)
    remote = (SolverClient(socket_path, tenant=name, **client_kwargs)
              if socket_path is not None else None)
    engine = SolverEngine(store, queues, scheduler=scheduler,
                          remote=remote)
    if pad_to is not None:
        engine.pad_to = pad_to
    return FederationMember(name=name, store=store, queues=queues,
                            scheduler=scheduler, engine=engine)


def build_fleet(names, socket_path: Optional[str] = None,
                seed: Optional[Callable[[str, Store], None]] = None,
                pad_to: Optional[int] = None,
                **client_kwargs) -> dict[str, FederationMember]:
    """N members sharing one farm socket; ``seed(name, store)`` lets
    each tenant start from its own (usually identical) cluster shape."""
    return {name: build_member(
        name, socket_path=socket_path,
        seed=(lambda s, _n=name: seed(_n, s)) if seed is not None
        else None,
        pad_to=pad_to, **client_kwargs) for name in names}


def plan_fingerprint(store: Store,
                     queues: Optional[QueueManager] = None) -> tuple:
    """The bit-identity surface for farm-vs-dedicated parity: which
    workloads hold quota (admitted) and which sit parked in their
    queues' inadmissible sets. Two control planes that ran the same
    churn agree on their plans iff these tuples are equal."""
    admitted = tuple(sorted(
        k for k, w in store.workloads.items()
        if w.is_quota_reserved and not w.is_finished))
    parked = ()
    if queues is not None:
        parked = tuple(sorted(
            k for q in queues.queues.values()
            for k in q.inadmissible if k not in q._stale))
    return admitted, parked
