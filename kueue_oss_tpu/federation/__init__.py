"""Federation layer: multi-tenant solver farm + what-if-scored dispatch.

docs/FEDERATION.md is the narrative spec. Three pieces:

- ``federation.farm`` — the weighted deficit-round-robin request
  scheduler a shared ("farm") solver sidecar runs, arbitrating solver
  wall-time across N tenant control planes (attach with
  ``attach_farm(server)``); per-tenant session namespacing itself
  lives in solver/service.py (sessions keyed ``(tenant, sid)``);
- ``federation.fleet`` — helpers building N complete control planes
  against one farm socket, plus the ``plan_fingerprint`` bit-identity
  surface the parity tests assert;
- the what-if-scored dispatcher lives with its siblings in
  ``multikueue/dispatcher.py`` (strategy name ``"WhatIf"``), priced by
  ``sim/dispatch.py``'s batched counterfactual solve.
"""

from kueue_oss_tpu.federation.farm import FarmScheduler, attach_farm
from kueue_oss_tpu.federation.fleet import (
    FederationMember,
    build_fleet,
    build_member,
    plan_fingerprint,
)

__all__ = [
    "FarmScheduler",
    "attach_farm",
    "FederationMember",
    "build_fleet",
    "build_member",
    "plan_fingerprint",
]
