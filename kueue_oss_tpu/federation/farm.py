"""Multi-tenant solver farm: weighted DRR admission over solver wall-time.

docs/FEDERATION.md: ROADMAP item 4's "many clusters, one brain" — N
control planes share ONE pod-scale solver sidecar. Each control plane
is a *tenant*: its ``SolverClient`` stamps a tenant id into every frame
header, the sidecar keys resident sessions ``(tenant, sid)`` (service.py),
and this module arbitrates who gets the solver next.

The scheduler is a weighted deficit round robin (DRR) over solver
WALL-TIME, not request count — one tenant's 50 ms full solves must not
buy it 10x the farm share of a neighbor's 5 ms lean solves:

- each tenant owns a FIFO queue of waiting requests and a deficit
  counter in seconds;
- a grant opportunity walks the tenant ring from the rotating cursor;
  every backlogged tenant visited accrues ``quantum_s * weight``; the
  first whose deficit goes positive is granted (the walk is computed in
  closed form — O(tenants), not O(rounds));
- the granted request's ACTUAL wall-time is charged afterwards, so the
  deficit can go negative: an expensive solve is a debt the tenant
  pays off by waiting out its neighbors' quanta;
- a tenant with an empty queue forfeits its credit (deficit resets to
  0) — idle time is not bankable, exactly like classic DRR;
- positive credit is capped at ``max_credit_quanta`` quanta so a
  lightly-loaded tenant cannot hoard an unbounded burst.

Backpressure is the contract that keeps a starved tenant from wedging:
a tenant with ``max_queued`` requests already waiting gets an IN-BAND
error (``{"ok": false, "error": "...backpressure..."}``) instead of a
queue slot. The client collapses that into ``SolverUnavailable``, the
engine's breaker trips, and the control plane degrades to host cycles —
it keeps scheduling, just without the accelerator, and re-probes later.

One executor slot: the underlying solver is one device (or one mesh) —
running two tenants' solves concurrently would just interleave compile
queues. The DRR therefore serializes solve bodies; fairness comes from
the grant ORDER, not parallelism.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Optional

from kueue_oss_tpu import metrics, resilience


def _backpressure(tenant: str, why: str) -> tuple[dict, bytes]:
    """The in-band throttle answer. Deliberately NOT a resync: the
    client must degrade via SolverUnavailable (host cycles), not burn
    the farm's time re-SYNCing a session that is perfectly healthy."""
    return {"ok": False,
            "error": f"solver farm backpressure for tenant "
                     f"{tenant!r}: {why}"}, b""


class _Ticket:
    __slots__ = ("granted",)

    def __init__(self) -> None:
        self.granted = threading.Event()


#: the handler thread that called run() also runs fn() and builds the
#: response, so the grant-wait it just paid rides a thread-local out to
#: the service layer (the response header's grant_wait_ms field)
_tls = threading.local()


def last_grant_wait_s() -> float:
    """Grant-wait of the newest farm admission on THIS thread (0.0
    when the thread never went through a farm)."""
    return getattr(_tls, "grant_wait_s", 0.0)


class FarmScheduler:
    """Weighted deficit-round-robin admission over solver wall-time.

    ``run(tenant, fn)`` is the only entry point the sidecar uses: it
    enqueues, waits for its DRR grant, times ``fn()``, charges the
    wall-time, and hands the slot to the next winner. Attach to a
    ``SolverServer`` with :func:`attach_farm` (or ``server.farm = ...``).

    ``clock`` is injectable so tests drive fairness deterministically.
    """

    def __init__(self,
                 weights: Optional[dict[str, float]] = None,
                 default_weight: float = 1.0,
                 quantum_s: float = 0.025,
                 max_queued: int = 8,
                 max_credit_quanta: float = 4.0,
                 grant_timeout_s: float = 600.0,
                 clock=time.monotonic) -> None:
        self.weights = dict(weights or {})
        self.default_weight = float(default_weight)
        self.quantum_s = float(quantum_s)
        self.max_queued = max(1, int(max_queued))
        self.max_credit_quanta = float(max_credit_quanta)
        self.grant_timeout_s = float(grant_timeout_s)
        self._clock = clock
        #: optional debugger.profiling.Tracer — when set, every grant
        #: stamps a "farm_grant_wait" span on a per-tenant farm track
        self.tracer = None
        self._lock = threading.Lock()
        self._queues: dict[str, deque[_Ticket]] = {}
        self._ring: list[str] = []
        self._cursor = 0
        self._deficit: dict[str, float] = {}
        self._busy = False
        #: per-tenant ledgers (bench/tests read these directly; the
        #: metrics registry carries the same totals for operators)
        self.wall_by_tenant: dict[str, float] = {}
        self.served: dict[str, int] = {}
        self.throttled: dict[str, int] = {}
        #: chaos seam (ClusterLossInjector.partition_farm): tenant ->
        #: remaining run() calls to answer with forced backpressure
        self.throttle_fault: dict[str, int] = {}

    @classmethod
    def from_config(cls, cfg, clock=time.monotonic) -> "FarmScheduler":
        """Build from a ``config.FederationConfig``."""
        return cls(weights=dict(cfg.tenant_weights),
                   default_weight=cfg.default_weight,
                   quantum_s=cfg.quantum_seconds,
                   max_queued=cfg.max_queued,
                   max_credit_quanta=cfg.max_credit_quanta,
                   clock=clock)

    # -- accounting --------------------------------------------------------

    def weight(self, tenant: str) -> float:
        return max(1e-9, float(self.weights.get(tenant,
                                                self.default_weight)))

    def set_weights(self, weights: Optional[dict] = None,
                    default_weight: Optional[float] = None) -> dict:
        """Runtime re-weighting (closes the ROADMAP item 4 residual).

        Takes effect within ONE ring walk: the closed-form grant walk
        reads :meth:`weight` live for every visit computation, so the
        very next grant opportunity accrues at the new rates — no
        queue drain, no ring rebuild, and standing deficits (debt from
        already-charged solves) are preserved. Positive credit is
        re-capped against the new weights. Returns the effective map.

        Raises ValueError on a non-positive or non-finite weight: a
        zero weight would starve a tenant silently (use admission
        policy for that), and ``weight()``'s 1e-9 clamp would mask
        the operator's typo instead of rejecting it.
        """
        with self._lock:
            if weights is not None:
                parsed = {str(k): float(v) for k, v in weights.items()}
                for t, w in parsed.items():
                    if not (w > 0.0) or math.isinf(w):
                        raise ValueError(
                            f"weight for {t!r} must be finite and > 0, "
                            f"got {w}")
                self.weights = parsed
            if default_weight is not None:
                dw = float(default_weight)
                if not (dw > 0.0) or math.isinf(dw):
                    raise ValueError(
                        f"defaultWeight must be finite and > 0, got {dw}")
                self.default_weight = dw
            for t in self._ring:
                cap = (self.quantum_s * self.weight(t)
                       * self.max_credit_quanta)
                if self._deficit.get(t, 0.0) > cap:
                    self._deficit[t] = cap
            return dict(self.weights)

    def reload_config(self, cfg) -> dict:
        """Hot-reload the DRR knobs from a ``config.FederationConfig``
        (the /api/farm/weights surface and SIGHUP-style reloads)."""
        with self._lock:
            self.quantum_s = float(cfg.quantum_seconds)
            self.max_queued = max(1, int(cfg.max_queued))
            self.max_credit_quanta = float(cfg.max_credit_quanta)
        return self.set_weights(dict(cfg.tenant_weights),
                                cfg.default_weight)

    def force_throttle(self, tenant: str, times: int = 1) -> None:
        """Chaos seam: the next ``times`` run() calls for ``tenant``
        answer with in-band backpressure as if the farm were
        partitioned away — the client degrades to host cycles exactly
        like real starvation."""
        with self._lock:
            self.throttle_fault[str(tenant)] = (
                self.throttle_fault.get(str(tenant), 0) + max(1, times))

    def stats(self) -> dict[str, dict[str, float]]:
        with self._lock:
            tenants = set(self.wall_by_tenant) | set(self.throttled)
            return {t: {"wall_s": self.wall_by_tenant.get(t, 0.0),
                        "served": self.served.get(t, 0),
                        "throttled": self.throttled.get(t, 0)}
                    for t in tenants}

    # -- the DRR core ------------------------------------------------------

    def _register_locked(self, tenant: str) -> None:
        if tenant not in self._queues:
            self._queues[tenant] = deque()
            self._ring.append(tenant)
            self._deficit.setdefault(tenant, 0.0)
            metrics.solver_farm_tenants.set(value=len(self._ring))

    def _grant_next_locked(self) -> None:
        """Pick the next winner by simulating the ring walk in closed
        form: for each backlogged tenant, the number of quantum visits
        it needs before its deficit goes positive; the winner is the
        fewest-visits tenant, ring order from the cursor breaking ties.
        Every backlogged tenant then accrues the winner's visit count
        (that is exactly what walking the ring that many times would
        have paid out), so relative credit is preserved."""
        if self._busy:
            return
        n = len(self._ring)
        backlogged = [(i, t) for i, t in enumerate(self._ring)
                      if self._queues[t]]
        if not backlogged:
            return
        # idle tenants forfeit credit — DRR's "no banking" rule
        for t in self._ring:
            if not self._queues[t] and self._deficit.get(t, 0.0) > 0:
                self._deficit[t] = 0.0

        def visits_needed(t: str) -> int:
            d = self._deficit.get(t, 0.0)
            if d > 0:
                return 0
            per = self.quantum_s * self.weight(t)
            return int(-d / per) + 1

        best = None
        for i, t in backlogged:
            need = visits_needed(t)
            pos = (i - self._cursor) % n  # ring distance from cursor
            key = (need, pos)
            if best is None or key < best[0]:
                best = (key, i, t)
        (rounds, _), idx, winner = best
        if rounds:
            for _, t in backlogged:
                cap = (self.quantum_s * self.weight(t)
                       * self.max_credit_quanta)
                self._deficit[t] = min(
                    self._deficit.get(t, 0.0)
                    + rounds * self.quantum_s * self.weight(t), cap)
        self._cursor = (idx + 1) % n
        self._busy = True
        ticket = self._queues[winner].popleft()
        ticket.granted.set()

    def _complete(self, tenant: str, wall_s: float) -> None:
        with self._lock:
            self._busy = False
            self._deficit[tenant] = self._deficit.get(tenant, 0.0) - wall_s
            self.wall_by_tenant[tenant] = (
                self.wall_by_tenant.get(tenant, 0.0) + wall_s)
            self.served[tenant] = self.served.get(tenant, 0) + 1
            self._grant_next_locked()
        metrics.solver_farm_wall_seconds_total.inc(tenant, by=wall_s)

    # -- entry point -------------------------------------------------------

    def run(self, tenant: str, fn: Callable[[], tuple[dict, bytes]]
            ) -> tuple[dict, bytes]:
        """Admit one request for ``tenant`` through the DRR and run it.

        Returns ``fn()``'s (header, blob) — or the in-band backpressure
        tuple when the tenant's queue is full / the grant timed out.
        ``fn`` exceptions propagate after the slot is released (the
        sidecar's ``respond`` reports them in-band, same as unfarmed).
        """
        tenant = str(tenant)
        ticket = _Ticket()
        _tls.grant_wait_s = 0.0
        t_enq = self._clock()
        with self._lock:
            self._register_locked(tenant)
            if self.throttle_fault.get(tenant, 0) > 0:
                self.throttle_fault[tenant] -= 1
                return self._throttle_locked(
                    tenant, "injected farm partition (chaos)")
            q = self._queues[tenant]
            if len(q) >= self.max_queued:
                return self._throttle_locked(
                    tenant, f"{len(q)} requests already queued "
                            f"(max_queued={self.max_queued})")
            q.append(ticket)
            self._grant_next_locked()
        if not ticket.granted.wait(self.grant_timeout_s):
            with self._lock:
                if not ticket.granted.is_set():
                    # never granted: withdraw and throttle — the slot
                    # was starved past any sane client deadline
                    try:
                        self._queues[tenant].remove(ticket)
                    except ValueError:
                        pass
                    return self._throttle_locked(
                        tenant, "grant wait timed out")
                # granted in the race window: fall through and run
        wait_s = max(0.0, self._clock() - t_enq)
        _tls.grant_wait_s = wait_s
        metrics.solver_farm_grant_wait_seconds.observe(tenant,
                                                       value=wait_s)
        tracer = self.tracer
        if tracer is not None and getattr(tracer, "enabled", False):
            dur_us = int(wait_s * 1e6)
            now_us = int(tracer.clock() * 1e6)
            tracer.add_span("farm_grant_wait", now_us - dur_us, dur_us,
                            source=f"farm:{tenant or 'solver'}",
                            tenant=tenant)
        metrics.solver_farm_requests_total.inc(tenant)
        t0 = self._clock()
        try:
            out = fn()
        finally:
            self._complete(tenant, max(0.0, self._clock() - t0))
        ctl = resilience.controller
        if ctl.active(resilience.FEDERATION, "backpressure"):
            ctl.report(resilience.FEDERATION, "backpressure", False,
                       reason=f"farm served tenant {tenant!r}; "
                              "backpressure relieved")
        return out

    def _throttle_locked(self, tenant: str, why: str
                         ) -> tuple[dict, bytes]:
        self.throttled[tenant] = self.throttled.get(tenant, 0) + 1
        metrics.solver_farm_throttled_total.inc(tenant)
        resilience.controller.report(
            resilience.FEDERATION, "backpressure", True,
            reason=f"farm backpressure for tenant {tenant!r}: {why}")
        return _backpressure(tenant, why)


def attach_farm(server, scheduler: Optional[FarmScheduler] = None,
                **farm_kwargs) -> FarmScheduler:
    """Wire a FarmScheduler onto a ``SolverServer`` (service.py checks
    ``server.farm`` per request). Returns the scheduler for test/bench
    introspection."""
    if scheduler is None:
        scheduler = FarmScheduler(**farm_kwargs)
    server.farm = scheduler
    return scheduler
