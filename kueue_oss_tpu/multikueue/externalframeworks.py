"""Config-declared MultiKueue adapters for external (custom) job GVKs.

A Configuration can name job kinds kueue has no built-in integration for
(``multiKueue.externalFrameworks: [{name: "Kind.v1.example.com"}]``);
each entry gets a GENERIC adapter with the KEP's default behavior: the
job object is mirrored to the worker verbatim (minus ``spec.managedBy``,
plus the prebuilt-workload and origin labels) and its whole ``status``
is copied back from the remote. Gated by MultiKueueAdaptersForCustomJobs.

Reference parity:
pkg/controller/admissionchecks/multikueue/externalframeworks/adapter.go:1-232
(SyncJob/createRemoteObject/syncStatus/DeleteRemoteObject/
IsJobManagedByKueue/WorkloadKeysFor) and config.go:1-71
(NewAdapters GVK parse + duplicate aggregation).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.multikueue.controller import MULTIKUEUE_CONTROLLER_NAME

#: label binding a mirrored job object to its (prebuilt) Workload
PREBUILT_WORKLOAD_LABEL = "kueue.x-k8s.io/prebuilt-workload-name"
#: label marking the hub that owns a mirrored object
MULTIKUEUE_ORIGIN_LABEL = "kueue.x-k8s.io/multikueue-origin"


@dataclass(frozen=True)
class GVK:
    group: str
    version: str
    kind: str

    def __str__(self) -> str:  # "Kind.version.group"
        return f"{self.kind}.{self.version}.{self.group}"


@dataclass
class MultiKueueExternalFramework:
    """Configuration.multiKueue.externalFrameworks entry."""

    name: str  # "Kind.version.group"


def parse_gvk(name: str) -> GVK:
    """Parse "Kind.version.group" (schema.ParseKindArg semantics)."""
    if not name:
        raise ValueError("name is required")
    parts = name.split(".", 2)
    if len(parts) != 3 or not all(parts):
        raise ValueError(f"invalid GVK format '{name}'")
    kind, version, group = parts
    return GVK(group=group, version=version, kind=kind)


def new_adapters(
    configs: list[MultiKueueExternalFramework],
) -> list["ExternalAdapter"]:
    """Adapters from config entries; invalid or duplicate entries are
    aggregated into one error (config.go NewAdapters)."""
    seen: dict[GVK, MultiKueueExternalFramework] = {}
    errs: list[str] = []
    for cfg in configs:
        try:
            gvk = parse_gvk(cfg.name)
        except ValueError as e:
            errs.append(
                f"invalid external framework configuration for "
                f"{cfg.name!r}: {e}")
            continue
        if gvk in seen:
            errs.append(f"duplicate configuration for GVK {gvk}")
            continue
        seen[gvk] = cfg
    if errs:
        raise ValueError("; ".join(errs))
    return [ExternalAdapter(gvk) for gvk in seen]


@dataclass
class ExternalJobObject:
    """Unstructured job analog: an opaque spec/status under a GVK."""

    gvk: GVK
    name: str = ""
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


class ExternalAdapter:
    """Generic MultiKueue adapter for one external GVK."""

    def __init__(self, gvk: GVK) -> None:
        self.gvk = gvk

    # -- sync --------------------------------------------------------------

    def sync_job(self, local_jobs: dict[str, ExternalJobObject],
                 remote_jobs: dict[str, ExternalJobObject], key: str,
                 workload_name: str, origin: str) -> None:
        """Ensure the remote object exists; sync status back otherwise
        (adapter.go SyncJob)."""
        local = local_jobs.get(key)
        if local is None:
            raise KeyError(f"{self.gvk} {key} not found locally")
        remote = remote_jobs.get(key)
        if remote is None:
            mirror = copy.deepcopy(local)
            # default transformation: strip .spec.managedBy, label with
            # the prebuilt workload + origin (createRemoteObject)
            mirror.spec.pop("managedBy", None)
            mirror.labels[PREBUILT_WORKLOAD_LABEL] = workload_name
            mirror.labels[MULTIKUEUE_ORIGIN_LABEL] = origin
            remote_jobs[key] = mirror
            return
        # default status sync: copy the entire remote status to local
        if remote.status and local.status != remote.status:
            local.status = copy.deepcopy(remote.status)

    def delete_remote_object(
            self, remote_jobs: dict[str, ExternalJobObject],
            key: str) -> None:
        remote_jobs.pop(key, None)

    # -- management gate ---------------------------------------------------

    def is_job_managed_by_kueue(
            self, jobs: dict[str, ExternalJobObject],
            key: str) -> tuple[bool, str]:
        """(managed, reason) — default .spec.managedBy path, behind the
        MultiKueueAdaptersForCustomJobs gate (adapter.go:168-193)."""
        from kueue_oss_tpu import features

        if not features.enabled("MultiKueueAdaptersForCustomJobs"):
            return (False,
                    "MultiKueueAdaptersForCustomJobs feature gate is "
                    "disabled")
        obj = jobs.get(key)
        if obj is None:
            raise KeyError(f"{self.gvk} {key} not found")
        managed_by = obj.spec.get("managedBy")
        if managed_by != MULTIKUEUE_CONTROLLER_NAME:
            return (False,
                    f"Expecting .spec.managedBy to be "
                    f"{MULTIKUEUE_CONTROLLER_NAME!r} not {managed_by!r}")
        return True, ""

    # -- watcher surface ---------------------------------------------------

    def workload_keys_for(self, obj: ExternalJobObject) -> list[str]:
        """Workload keys of interest for a watched object
        (adapter.go WorkloadKeysFor)."""
        if obj.gvk != self.gvk:
            raise ValueError(
                f"unexpected GVK: expected {self.gvk}, got {obj.gvk}")
        prebuilt = obj.labels.get(PREBUILT_WORKLOAD_LABEL)
        if not prebuilt:
            raise ValueError(
                f"no prebuilt workload found for {self.gvk.kind}: "
                f"{obj.key}")
        return [f"{obj.namespace}/{prebuilt}"]

    def list_objects(
            self, jobs: dict[str, ExternalJobObject],
    ) -> list[ExternalJobObject]:
        """All objects of this adapter's GVK (GetEmptyList analog)."""
        return [o for o in jobs.values() if o.gvk == self.gvk]


def find_adapter(adapters: list[ExternalAdapter],
                 gvk: GVK) -> Optional[ExternalAdapter]:
    for a in adapters:
        if a.gvk == gvk:
            return a
    return None
