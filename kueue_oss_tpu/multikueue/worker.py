"""MultiKueue worker-cluster process.

Reference parity: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go — the hub talks to each worker cluster over a real
process/cluster boundary (remote clients built from kubeconfig
Secrets). Here the worker is a separate OS process hosting a full
WorkerEnvironment (store + queues + scheduler) behind a length-prefixed
pickle RPC on a unix socket; the hub side (remote.py) mirrors
workloads, polls status, and detects worker loss by connection failure,
exactly like the reference's watcher/reconnect loops
(multikueuecluster.go:205-283).

Transport note: pickle over a local unix socket — hub and workers are
one trust domain (the reference's kubeconfigs likewise grant full
API access); the socket path's filesystem permissions are the boundary.

Run: python -m kueue_oss_tpu.multikueue.worker --socket /tmp/w1.sock
"""

from __future__ import annotations

import argparse
import os
import pickle
import socket
import socketserver
import struct
import threading


def send_msg(sock: socket.socket, obj) -> None:
    blob = pickle.dumps(obj)
    sock.sendall(struct.pack("<I", len(blob)) + blob)


def recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, 4)
    (n,) = struct.unpack("<I", hdr)
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("worker connection closed")
        buf += chunk
    return buf


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        env = self.server.env  # type: ignore[attr-defined]
        while True:
            try:
                req = recv_msg(self.connection)
            except (ConnectionError, EOFError):
                return
            try:
                out = self._dispatch(env, req)
                send_msg(self.connection, {"ok": True, "result": out})
            except Exception as e:  # noqa: BLE001 - reported to hub
                send_msg(self.connection,
                         {"ok": False, "error": repr(e)})

    def _dispatch(self, env, req):
        op = req["op"]
        if op == "ping":
            return "pong"
        if op == "upsert":
            kind, obj = req["kind"], req["obj"]
            getattr(env.store, f"upsert_{kind}")(obj)
            return None
        if op == "get_workload":
            return env.store.workloads.get(req["key"])
        if op == "add_workload":
            env.store.add_workload(req["workload"])
            return None
        if op == "update_workload":
            env.store.update_workload(req["workload"])
            return None
        if op == "delete_workload":
            if req["key"] in env.store.workloads:
                env.store.delete_workload(req["key"])
            return None
        if op == "evict_workload":
            env.scheduler.evict_workload(
                req["key"], reason=req.get("reason", "Evicted"),
                message=req.get("message", ""), now=req.get("now", 0.0),
                requeue=req.get("requeue", True))
            return None
        if op == "run_cycle":
            stats = env.run_cycle(req["now"])
            return {"admitted": stats.admitted, "heads": stats.heads}
        if op == "list_keys":
            return list(env.store.workloads.keys())
        raise ValueError(f"unknown op {op!r}")


class WorkerServer(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, socket_path: str) -> None:
        from kueue_oss_tpu.multikueue.cluster import WorkerEnvironment

        if os.path.exists(socket_path):
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.env = WorkerEnvironment(
            name=os.path.basename(socket_path))

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--socket", required=True)
    args = parser.parse_args()
    server = WorkerServer(args.socket)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
