"""Worker-nomination dispatchers.

Reference parity: pkg/controller/workloaddispatcher — AllAtOnce nominates
every active worker immediately; Incremental nominates up to 3 new
workers per round and opens the next round after a timeout without
admission (incrementaldispatcher.go:130-197).
"""

from __future__ import annotations

from kueue_oss_tpu.api.types import Workload

DISPATCHER_ALL_AT_ONCE = "AllAtOnce"
DISPATCHER_INCREMENTAL = "Incremental"

INCREMENTAL_WORKERS_PER_ROUND = 3
INCREMENTAL_ROUND_TIMEOUT_S = 300.0


class AllAtOnceDispatcher:
    name = DISPATCHER_ALL_AT_ONCE

    def nominate(self, wl: Workload, clusters: list[str],
                 now: float) -> list[str]:
        return [c for c in clusters if c not in wl.status.nominated_cluster_names]


class IncrementalDispatcher:
    name = DISPATCHER_INCREMENTAL

    def __init__(self,
                 per_round: int = INCREMENTAL_WORKERS_PER_ROUND,
                 round_timeout_s: float = INCREMENTAL_ROUND_TIMEOUT_S) -> None:
        self.per_round = per_round
        self.round_timeout_s = round_timeout_s
        self._round_start: dict[str, float] = {}

    def nominate(self, wl: Workload, clusters: list[str],
                 now: float) -> list[str]:
        nominated = wl.status.nominated_cluster_names
        remaining = [c for c in clusters if c not in nominated]
        if not remaining:
            return []
        started = self._round_start.get(wl.key)
        if nominated and started is not None:
            if now - started < self.round_timeout_s:
                return []  # current round still racing
        self._round_start[wl.key] = now
        return remaining[:self.per_round]

    def clear(self, wl_key: str) -> None:
        self._round_start.pop(wl_key, None)
