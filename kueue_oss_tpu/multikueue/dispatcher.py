"""Worker-nomination dispatchers.

Reference parity: pkg/controller/workloaddispatcher — AllAtOnce nominates
every active worker immediately; Incremental nominates up to 3 new
workers per round and opens the next round after a timeout without
admission (incrementaldispatcher.go:130-197).

The third strategy is this repo's own (docs/FEDERATION.md): WhatIf
prices the candidate clusters with one batched counterfactual solve
(sim/dispatch.py) and nominates ONLY the predicted-best worker — no
blind racing, no wasted mirrors — degrading to Incremental whenever
the pricer cannot speak for a cluster.
"""

from __future__ import annotations

import time
from typing import Optional

from kueue_oss_tpu import metrics
from kueue_oss_tpu.api.types import Workload

DISPATCHER_ALL_AT_ONCE = "AllAtOnce"
DISPATCHER_INCREMENTAL = "Incremental"
DISPATCHER_WHAT_IF = "WhatIf"

INCREMENTAL_WORKERS_PER_ROUND = 3
INCREMENTAL_ROUND_TIMEOUT_S = 300.0


class AllAtOnceDispatcher:
    name = DISPATCHER_ALL_AT_ONCE

    def nominate(self, wl: Workload, clusters: list[str],
                 now: float) -> list[str]:
        return [c for c in clusters if c not in wl.status.nominated_cluster_names]


class IncrementalDispatcher:
    name = DISPATCHER_INCREMENTAL

    def __init__(self,
                 per_round: int = INCREMENTAL_WORKERS_PER_ROUND,
                 round_timeout_s: float = INCREMENTAL_ROUND_TIMEOUT_S) -> None:
        self.per_round = per_round
        self.round_timeout_s = round_timeout_s
        self._round_start: dict[str, float] = {}

    def nominate(self, wl: Workload, clusters: list[str],
                 now: float) -> list[str]:
        nominated = wl.status.nominated_cluster_names
        remaining = [c for c in clusters if c not in nominated]
        if not remaining:
            return []
        started = self._round_start.get(wl.key)
        if nominated and started is not None:
            if now - started < self.round_timeout_s:
                return []  # current round still racing
        self._round_start[wl.key] = now
        return remaining[:self.per_round]

    def clear(self, wl_key: str) -> None:
        self._round_start.pop(wl_key, None)


class WhatIfDispatcher:
    """Counterfactually-priced nomination (docs/FEDERATION.md).

    For each workload round, one batched what-if solve scores every
    active candidate cluster ("the workload lands on cluster k") and
    the single best-scoring worker is nominated. A round that fails to
    admit re-prices after ``round_timeout_s`` against the remaining
    candidates. When the pricer cannot score (full-kernel shapes, TAS,
    pricer fault, no environments bound), the round degrades to an
    internal IncrementalDispatcher — the dispatch contract (something
    always gets nominated while workers remain) never depends on the
    what-if engine being healthy.

    The controller calls ``bind(clusters)`` at construction so the
    dispatcher can reach worker environments for pricing; nominate()'s
    signature stays identical to its siblings.
    """

    name = DISPATCHER_WHAT_IF

    def __init__(self,
                 round_timeout_s: float = INCREMENTAL_ROUND_TIMEOUT_S,
                 check_oracle: bool = False,
                 clock=time.monotonic) -> None:
        self.round_timeout_s = round_timeout_s
        self.check_oracle = check_oracle
        self._clock = clock
        self._clusters: dict = {}
        self._round_start: dict[str, float] = {}
        self._fallback = IncrementalDispatcher(
            round_timeout_s=round_timeout_s)
        #: last DispatchReport per workload key (tests/bench introspect
        #: predicted scores and oracle agreement)
        self.last_reports: dict[str, object] = {}

    def bind(self, clusters: dict) -> None:
        """Controller wiring: name -> MultiKueueCluster (pricing needs
        each worker's store/queues, not just its name)."""
        self._clusters = clusters

    def nominate(self, wl: Workload, clusters: list[str],
                 now: float) -> list[str]:
        nominated = wl.status.nominated_cluster_names
        remaining = [c for c in clusters if c not in nominated]
        if not remaining:
            return []
        started = self._round_start.get(wl.key)
        if nominated and started is not None:
            if now - started < self.round_timeout_s:
                metrics.multikueue_whatif_dispatch_total.inc("deferred")
                return []  # current round still racing
        best = self._price(wl, remaining, now)
        if best is None:
            metrics.multikueue_whatif_dispatch_total.inc("fallback")
            # keep the fallback's round clock coherent with ours
            picked = self._fallback.nominate(wl, remaining, now)
            if picked:
                self._round_start[wl.key] = now
            return picked
        metrics.multikueue_whatif_dispatch_total.inc("scored")
        self._round_start[wl.key] = now
        return [best]

    def _price(self, wl: Workload, remaining: list[str],
               now: float) -> Optional[str]:
        envs = {}
        for name in remaining:
            cluster = self._clusters.get(name)
            if cluster is not None and cluster.active:
                envs[name] = cluster.environment
        if not envs:
            return None
        from kueue_oss_tpu.sim.dispatch import price_dispatch

        t0 = self._clock()
        try:
            report = price_dispatch(wl, envs, now=now,
                                    check_oracle=self.check_oracle)
        except Exception:
            # a pricer fault must degrade, never block dispatch
            return None
        finally:
            metrics.multikueue_dispatch_score_ms.observe(
                value=(self._clock() - t0) * 1e3)
        self.last_reports[wl.key] = report
        return report.best

    def clear(self, wl_key: str) -> None:
        self._round_start.pop(wl_key, None)
        self._fallback.clear(wl_key)
