"""MultiKueue multi-cluster dispatch (KEP-693).

Reference parity: pkg/controller/admissionchecks/multikueue — a hub
cluster mirrors quota-reserved workloads to worker clusters, races their
admissions (first worker to admit wins, the rest are cleaned up), copies
worker status back, and re-dispatches when a worker is lost past a
timeout (multikueuecluster.go remote clients/watchers; workload.go
mirroring). Dispatchers decide which workers to nominate: AllAtOnce or
Incremental (up to 3 per round with a round timeout,
workloaddispatcher/incrementaldispatcher.go:162), or this repo's own
WhatIf strategy — one batched counterfactual solve prices every
candidate and nominates only the predicted-best worker
(sim/dispatch.py, docs/FEDERATION.md).

A "worker cluster" here is a full in-process environment (Store + queues
+ scheduler), matching the reference's multiple-envtest-control-planes
test recipe (SURVEY.md §4).
"""

from kueue_oss_tpu.multikueue.cluster import (
    MultiKueueCluster,
    WorkerEnvironment,
)
from kueue_oss_tpu.multikueue.dispatcher import (
    AllAtOnceDispatcher,
    DISPATCHER_ALL_AT_ONCE,
    DISPATCHER_INCREMENTAL,
    DISPATCHER_WHAT_IF,
    IncrementalDispatcher,
    WhatIfDispatcher,
)
from kueue_oss_tpu.multikueue.controller import (
    MULTIKUEUE_CONTROLLER_NAME,
    MultiKueueController,
)

__all__ = [
    "MultiKueueCluster",
    "WorkerEnvironment",
    "AllAtOnceDispatcher",
    "IncrementalDispatcher",
    "WhatIfDispatcher",
    "DISPATCHER_ALL_AT_ONCE",
    "DISPATCHER_INCREMENTAL",
    "DISPATCHER_WHAT_IF",
    "MULTIKUEUE_CONTROLLER_NAME",
    "MultiKueueController",
]
