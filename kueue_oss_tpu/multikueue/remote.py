"""Hub-side remote worker client: proxies + watcher + config watch.

Reference parity: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go:91-283 — a remoteClient per worker with long-lived
watchers streaming remote events into the hub reconcile queue,
reconnect with backoff, and garbage collection of orphaned mirrors;
fswatch.go — kubeconfig directory watching that adds/removes clusters
live. The proxy classes present the same duck-typed surface the
MultiKueueController uses on an in-process WorkerEnvironment
(store.workloads.get / add_workload / delete_workload /
scheduler.evict_workload / run_cycle), so in-process and
process-separated workers are interchangeable.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Callable, Optional

from kueue_oss_tpu.multikueue.worker import recv_msg, send_msg


class RemoteWorkerError(ConnectionError):
    """Transport-level failure: the worker process is unreachable."""


class RemoteOpError(RuntimeError):
    """The worker processed the request and reported a failure."""


class _Conn:
    """One socket with request/response framing; thread-safe."""

    def __init__(self, path: str, timeout_s: float = 30.0) -> None:
        self.path = path
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def call(self, **req):
        with self._lock:
            if self._sock is None:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(self.timeout_s)
                try:
                    s.connect(self.path)
                except OSError as e:
                    raise RemoteWorkerError(str(e)) from e
                self._sock = s
            try:
                send_msg(self._sock, req)
                out = recv_msg(self._sock)
            except (OSError, ConnectionError, EOFError) as e:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
                raise RemoteWorkerError(str(e)) from e
        if not out["ok"]:
            raise RemoteOpError(f"worker error: {out['error']}")
        return out["result"]

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None


class _RemoteWorkloads:
    def __init__(self, conn: _Conn) -> None:
        self._conn = conn

    def get(self, key: str):
        return self._conn.call(op="get_workload", key=key)

    def __contains__(self, key: str) -> bool:
        return self.get(key) is not None

    def keys(self):
        return self._conn.call(op="list_keys")


class _RemoteStore:
    def __init__(self, conn: _Conn) -> None:
        self._conn = conn
        self.workloads = _RemoteWorkloads(conn)

    def add_workload(self, wl) -> None:
        self._conn.call(op="add_workload", workload=wl)

    def update_workload(self, wl) -> None:
        self._conn.call(op="update_workload", workload=wl)

    def delete_workload(self, key: str) -> None:
        self._conn.call(op="delete_workload", key=key)

    def upsert(self, kind: str, obj) -> None:
        self._conn.call(op="upsert", kind=kind, obj=obj)


class _RemoteScheduler:
    def __init__(self, conn: _Conn) -> None:
        self._conn = conn

    def evict_workload(self, key: str, reason: str = "Evicted",
                       message: str = "", now: float = 0.0,
                       requeue: bool = True, **_kw) -> None:
        self._conn.call(op="evict_workload", key=key, reason=reason,
                        message=message, now=now, requeue=requeue)


class RemoteWorkerEnvironment:
    """Duck-typed WorkerEnvironment over the worker-process socket."""

    def __init__(self, name: str, socket_path: str,
                 timeout_s: float = 30.0) -> None:
        self.name = name
        self._conn = _Conn(socket_path, timeout_s)
        self.store = _RemoteStore(self._conn)
        self.scheduler = _RemoteScheduler(self._conn)

    def run_cycle(self, now: float):
        return self._conn.call(op="run_cycle", now=now)

    def ping(self) -> bool:
        return self._conn.call(op="ping") == "pong"

    def close(self) -> None:
        self._conn.close()


class WorkerWatcher:
    """Health/watch loop per remote worker (multikueuecluster.go:205-283).

    Pings the worker on an interval; connection failure flips the
    MultiKueueCluster inactive (the hub's worker-lost timeout then
    triggers re-dispatch) and the loop keeps retrying with backoff until
    the worker returns, at which point the cluster reactivates and an
    optional callback requeues affected hub workloads (the reference
    re-lists watched GVKs after reconnect).
    """

    def __init__(self, cluster, env: RemoteWorkerEnvironment,
                 interval_s: float = 1.0,
                 on_reconnect: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.cluster = cluster
        self.env = env
        self.interval_s = interval_s
        self.on_reconnect = on_reconnect
        self.clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> bool:
        """One health probe; returns current liveness."""
        try:
            ok = self.env.ping()
        except (RemoteWorkerError, RuntimeError):
            ok = False
        was_active = self.cluster.active
        self.cluster.active = ok
        if ok:
            self.cluster.mark_seen(self.clock())
            if not was_active and self.on_reconnect is not None:
                self.on_reconnect()
        return ok

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                self.poll_once()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class WorkerConfigWatcher:
    """kubeconfig-analog file watch (fswatch.go): a JSON file mapping
    cluster name -> unix socket path; reloading on mtime change adds new
    clusters and deactivates removed ones via callbacks."""

    def __init__(self, path: str,
                 on_add: Callable[[str, str], None],
                 on_remove: Callable[[str], None]) -> None:
        self.path = path
        self.on_add = on_add
        self.on_remove = on_remove
        self._mtime = 0.0
        self._known: dict[str, str] = {}

    def poll(self) -> bool:
        try:
            mtime = os.stat(self.path).st_mtime
        except FileNotFoundError:
            return False
        if mtime == self._mtime:
            return False
        try:
            with open(self.path) as f:
                current = json.load(f)
        except (json.JSONDecodeError, OSError):
            # partially-written config (non-atomic writer): leave the
            # mtime uncommitted so the completed write is re-read
            return False
        self._mtime = mtime
        for name, sock_path in current.items():
            if name not in self._known:
                self.on_add(name, sock_path)
            elif self._known[name] != sock_path:
                # same cluster, new endpoint: rebuild the remote client
                # (fswatch.go rebuilds on kubeconfig content change)
                self.on_remove(name)
                self.on_add(name, sock_path)
        for name in list(self._known):
            if name not in current:
                self.on_remove(name)
        self._known = dict(current)
        return True
