"""Worker-cluster handles.

Reference parity: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go — a remoteClient per worker built from kubeconfig
Secrets, with an Active condition and reconnect handling. Here a worker is
an in-process environment; `active` models connectivity and `last_seen`
drives the worker-lost timeout (controllers.go:111).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.scheduler.scheduler import Scheduler


class WorkerEnvironment:
    """A self-contained worker cluster: store + queues + scheduler."""

    def __init__(self, name: str, store: Optional[Store] = None) -> None:
        self.name = name
        self.store = store or Store()
        self.queues = QueueManager(self.store)
        self.scheduler = Scheduler(self.store, self.queues)
        #: mirrored external-framework job objects keyed by "ns/name"
        #: (externalframeworks.ExternalJobObject)
        self.external_jobs: dict = {}

    def run_cycle(self, now: float):
        """One worker scheduling cycle (the driver/test advances workers)."""
        return self.scheduler.schedule(now)


class InsecureKubeConfig(Exception):
    """Raised for kubeconfig sources the gates forbid."""


@dataclass
class KubeConfigSource:
    """Where a worker cluster's kubeconfig comes from
    (MultiKueueCluster.spec.kubeConfig; multikueuecluster.go secret
    loading + the ClusterProfile alternative).

    ``location_type``: "Secret" | "Path" | "ClusterProfile".
    ``insecure``: the loaded config skips TLS verification
    (rest.Config.Insecure) — rejected unless the
    MultiKueueAllowInsecureKubeconfigs gate is on.
    """

    location: str = ""
    location_type: str = "Secret"
    insecure: bool = False

    def validate(self) -> None:
        from kueue_oss_tpu import features

        if (self.location_type == "ClusterProfile"
                and not features.enabled("MultiKueueClusterProfile")):
            raise InsecureKubeConfig(
                "ClusterProfile kubeconfig sources need the "
                "MultiKueueClusterProfile feature gate")
        if self.location_type not in ("Secret", "Path", "ClusterProfile"):
            raise InsecureKubeConfig(
                f"unknown kubeconfig location type {self.location_type!r}")
        if (self.insecure
                and not features.enabled(
                    "MultiKueueAllowInsecureKubeconfigs")):
            raise InsecureKubeConfig(
                "kubeconfig skips TLS verification; enable "
                "MultiKueueAllowInsecureKubeconfigs to allow it")


@dataclass
class MultiKueueCluster:
    """MultiKueueCluster CRD analog: names a worker and its connection."""

    name: str
    environment: WorkerEnvironment
    #: connectivity (reference: cluster Active condition)
    active: bool = True
    last_seen: float = 0.0
    #: how the connection is configured; validated against the
    #: MultiKueueAllowInsecureKubeconfigs / MultiKueueClusterProfile
    #: gates when set (None = in-process test cluster, always allowed)
    kubeconfig: Optional[KubeConfigSource] = None

    def __post_init__(self) -> None:
        if self.kubeconfig is not None:
            self.kubeconfig.validate()

    def mark_seen(self, now: float) -> None:
        self.last_seen = now
