"""Worker-cluster handles.

Reference parity: pkg/controller/admissionchecks/multikueue/
multikueuecluster.go — a remoteClient per worker built from kubeconfig
Secrets, with an Active condition and reconnect handling. Here a worker is
an in-process environment; `active` models connectivity and `last_seen`
drives the worker-lost timeout (controllers.go:111).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.scheduler.scheduler import Scheduler


class WorkerEnvironment:
    """A self-contained worker cluster: store + queues + scheduler."""

    def __init__(self, name: str, store: Optional[Store] = None) -> None:
        self.name = name
        self.store = store or Store()
        self.queues = QueueManager(self.store)
        self.scheduler = Scheduler(self.store, self.queues)

    def run_cycle(self, now: float):
        """One worker scheduling cycle (the driver/test advances workers)."""
        return self.scheduler.schedule(now)


@dataclass
class MultiKueueCluster:
    """MultiKueueCluster CRD analog: names a worker and its connection."""

    name: str
    environment: WorkerEnvironment
    #: connectivity (reference: cluster Active condition)
    active: bool = True
    last_seen: float = 0.0

    def mark_seen(self, now: float) -> None:
        self.last_seen = now
