"""The MultiKueue admission-check controller.

Reference parity: pkg/controller/admissionchecks/multikueue/workload.go —
for every hub workload whose CQ carries a MultiKueue admission check:
mirror it to nominated workers, race remote admissions (first wins,
losers are cleaned), flip the check Ready, copy worker status back on
finish, and re-dispatch when the admitting worker is lost past
workerLostTimeout (controllers.go:111).
"""

from __future__ import annotations

from typing import Optional

from kueue_oss_tpu.api.types import (
    CheckState,
    PodSet,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.multikueue.cluster import MultiKueueCluster
from kueue_oss_tpu.multikueue.dispatcher import AllAtOnceDispatcher

MULTIKUEUE_CONTROLLER_NAME = "kueue.x-k8s.io/multikueue"

#: prefix marking a mirrored workload on a worker (reference uses the
#: kueue.x-k8s.io/multikueue-origin label)
ORIGIN_LABEL = "multikueue-origin"


class MultiKueueController:
    def __init__(self, hub_store: Store, hub_scheduler,
                 clusters: list[MultiKueueCluster],
                 dispatcher=None,
                 worker_lost_timeout_s: float = 900.0,
                 check_name: str = "multikueue",
                 external_adapters=None,
                 hub_jobs: Optional[dict] = None) -> None:
        self.store = hub_store
        self.scheduler = hub_scheduler
        self.clusters = {c.name: c for c in clusters}
        self.dispatcher = dispatcher or AllAtOnceDispatcher()
        if hasattr(self.dispatcher, "bind"):
            # pricing dispatchers (WhatIf) need the worker environments,
            # not just the names nominate() receives
            self.dispatcher.bind(self.clusters)
        self.worker_lost_timeout_s = worker_lost_timeout_s
        self.check_name = check_name
        #: config-declared generic adapters for custom job GVKs
        #: (externalframeworks.new_adapters); each syncs its job object
        #: alongside the workload mirror
        self.external_adapters = external_adapters or []
        #: hub-side external job objects keyed by "ns/name"
        self.hub_jobs = hub_jobs if hub_jobs is not None else {}
        #: origin label value stamped on mirrored objects
        self.store_name = "hub"

    # -- main loop ----------------------------------------------------------

    def reconcile_all(self, now: float) -> None:
        from kueue_oss_tpu import features

        if not features.enabled("MultiKueue"):
            return
        for c in self.clusters.values():
            if c.active:
                c.mark_seen(now)
        for wl in list(self.store.workloads.values()):
            # Eviction clears the admission-check states, so a workload
            # that still has remote state (nominations or a winner) must
            # keep reconciling until its mirrors are withdrawn.
            if (self.check_name in wl.status.admission_checks
                    or wl.status.cluster_name is not None
                    or wl.status.nominated_cluster_names):
                from kueue_oss_tpu.multikueue.remote import RemoteOpError

                try:
                    self.reconcile(wl, now)
                except (ConnectionError, RemoteOpError):
                    # A worker died mid-RPC (remote.RemoteWorkerError)
                    # or a worker-side op failed (e.g. the mirror was
                    # deleted concurrently): skip just this workload and
                    # reconcile it again next pass — the reference logs
                    # and requeues the failing workload only
                    # (multikueuecluster.go reconnect handling).
                    continue

    def reconcile(self, wl: Workload, now: float) -> None:
        if (wl.is_finished or not wl.active
                or not wl.is_quota_reserved):
            # Finished, deactivated, or reservation lost on the hub:
            # withdraw all mirrors and reset remote state.
            self._cleanup_remotes(wl, keep=None)
            wl.status.nominated_cluster_names = []
            wl.status.cluster_name = None
            return
        state = wl.status.admission_checks.get(self.check_name)
        if state is None:
            return

        # External-framework job (config-declared adapter): refuse to
        # dispatch unless the custom job delegates to the MultiKueue
        # controller via .spec.managedBy (adapter.go IsJobManagedByKueue,
        # gated by MultiKueueAdaptersForCustomJobs).
        ext = self._external_job_for(wl)
        if ext is not None:
            adapter, job = ext
            managed, reason = adapter.is_job_managed_by_kueue(
                self.hub_jobs, job.key)
            if not managed:
                state.message = reason
                return

        winner = wl.status.cluster_name
        if winner is not None:
            self._sync_winner(wl, winner, state, now)
            return

        # Race phase: ensure mirrors exist on nominated workers.
        active_names = [c.name for c in self.clusters.values() if c.active]
        new = self.dispatcher.nominate(wl, active_names, now)
        if new:
            wl.status.nominated_cluster_names.extend(new)
        for name in wl.status.nominated_cluster_names:
            cluster = self.clusters.get(name)
            if cluster is None or not cluster.active:
                continue
            self._ensure_mirror(wl, cluster)

        # Did any worker admit its mirror? Under the GA
        # MultiKueueWaitForWorkloadAdmitted gate the race is won only by
        # full admission (all worker checks ready); with the gate off,
        # the pre-0.18 behavior settles for quota reservation.
        from kueue_oss_tpu import features

        wait_admitted = features.enabled("MultiKueueWaitForWorkloadAdmitted")
        for name in wl.status.nominated_cluster_names:
            cluster = self.clusters.get(name)
            if cluster is None or not cluster.active:
                continue
            mirror = cluster.environment.store.workloads.get(wl.key)
            won = (mirror is not None
                   and (mirror.is_admitted if wait_admitted
                        else mirror.is_quota_reserved))
            if won:
                wl.status.cluster_name = name
                wl.status.nominated_cluster_names = []
                state.state = CheckState.READY
                state.message = f"The workload got reservation on \"{name}\""
                self._cleanup_remotes(wl, keep=name)
                if hasattr(self.dispatcher, "clear"):
                    self.dispatcher.clear(wl.key)
                self.store.update_workload(wl)
                return

    # -- winner tracking ----------------------------------------------------

    def _sync_winner(self, wl: Workload, winner: str, state, now: float) -> None:
        cluster = self.clusters.get(winner)
        if cluster is None:
            # Removed from config: the remote client is gone for good, so
            # the workload is lost immediately — no workerLostTimeout grace
            # (the timeout covers transient disconnects only;
            # multikueuecluster.go removal vs watcher-reconnect handling).
            lost = True
        elif not cluster.active:
            # Transiently unreachable: lost only past the grace window.
            lost = now - cluster.last_seen >= self.worker_lost_timeout_s
        else:
            lost = False
        if lost:
            # Worker lost past the timeout: redo the admission process
            # (workload.go remote-lost handling).
            wl.status.cluster_name = None
            wl.status.nominated_cluster_names = []
            state.state = CheckState.RETRY
            state.message = f"Worker cluster \"{winner}\" is lost"
            self.store.update_workload(wl)
            return
        if cluster is None or not cluster.active:
            return  # transiently unreachable; wait for the timeout
        # external-framework job: pull the whole remote status back to
        # the hub object (adapter.go syncStatus default behavior)
        ext = self._external_job_for(wl)
        if ext is not None:
            adapter, job = ext
            try:
                adapter.sync_job(self.hub_jobs,
                                 cluster.environment.external_jobs,
                                 job.key, workload_name=wl.name,
                                 origin=self.store_name)
            except KeyError:
                pass
        mirror = cluster.environment.store.workloads.get(wl.key)
        if mirror is None:
            # Mirror vanished on the worker: retry admission.
            wl.status.cluster_name = None
            state.state = CheckState.RETRY
            state.message = f"Mirror lost on worker \"{winner}\""
            self.store.update_workload(wl)
            return
        from kueue_oss_tpu import features

        if (not mirror.is_quota_reserved and not mirror.is_finished
                and features.enabled(
                    "MultiKueueRedoAdmissionOnEvictionInWorker")):
            # The worker evicted the mirror (preemption / stop policy):
            # redo the hub-side admission race instead of waiting for
            # the worker to re-admit (workload.go eviction redo, GA).
            # The requeued mirror must be WITHDRAWN first — a fresh race
            # could pick a different worker while the old mirror
            # re-admits, running the workload on two clusters.
            self._cleanup_remotes(wl, keep=None)
            if hasattr(self.dispatcher, "clear"):
                self.dispatcher.clear(wl.key)
            wl.status.cluster_name = None
            wl.status.nominated_cluster_names = []
            state.state = CheckState.RETRY
            state.message = (f"The workload got evicted on worker "
                             f"\"{winner}\"")
            self.store.update_workload(wl)
            return
        # Propagate the worker's PodsReady condition to the hub
        # workload: the hub's WaitForPodsReady timers must see the
        # delegated job's real readiness (the local job never starts
        # under MultiKueueBatchJobWithManagedBy).
        ready = mirror.condition(WorkloadConditionType.PODS_READY)
        if ready is not None:
            cur = wl.condition(WorkloadConditionType.PODS_READY)
            if cur is None or cur.status != ready.status:
                wl.set_condition(
                    WorkloadConditionType.PODS_READY, ready.status,
                    reason=ready.reason, message=ready.message, now=now)
                if ready.status:
                    wl.status.requeue_state = None
                self.store.update_workload(wl)
        if mirror.is_finished and not wl.is_finished:
            # Copy terminal status back to the hub (workload.go status sync).
            fin = mirror.condition(WorkloadConditionType.FINISHED)
            wl.set_condition(WorkloadConditionType.FINISHED, True,
                             reason=fin.reason if fin else "JobFinished",
                             message=fin.message if fin else "", now=now)
            self.store.update_workload(wl)
            self.scheduler.queues.report_workload_finished(wl)
            self._cleanup_remotes(wl, keep=None)

    # -- mirroring ----------------------------------------------------------

    def _external_job_for(self, wl: Workload):
        """(adapter, hub job) bound to this workload via the prebuilt
        label, when a config-declared adapter covers the job's GVK."""
        if not self.external_adapters or not self.hub_jobs:
            return None
        from kueue_oss_tpu.multikueue.externalframeworks import (
            PREBUILT_WORKLOAD_LABEL,
            find_adapter,
        )

        for job in self.hub_jobs.values():
            if (job.namespace == wl.namespace
                    and job.labels.get(PREBUILT_WORKLOAD_LABEL) == wl.name):
                adapter = find_adapter(self.external_adapters, job.gvk)
                if adapter is not None:
                    return adapter, job
        return None

    def _ensure_mirror(self, wl: Workload,
                       cluster: MultiKueueCluster) -> None:
        ext = self._external_job_for(wl)
        if ext is not None:
            adapter, job = ext
            adapter.sync_job(self.hub_jobs,
                             cluster.environment.external_jobs, job.key,
                             workload_name=wl.name, origin=self.store_name)
        wstore = cluster.environment.store
        if wl.key in wstore.workloads:
            return
        mirror = Workload(
            name=wl.name,
            namespace=wl.namespace,
            queue_name=wl.queue_name,
            priority=wl.priority,
            priority_class=None,  # priority already resolved on the hub
            podsets=[PodSet(
                name=ps.name, count=ps.count, requests=dict(ps.requests),
                min_count=ps.min_count,
                topology_request=ps.topology_request,
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
            ) for ps in wl.podsets],
            creation_time=wl.creation_time,
            owner=f"{ORIGIN_LABEL}/{wl.key}",
        )
        mirror.priority = wl.priority
        wstore.add_workload(mirror)

    def _cleanup_remotes(self, wl: Workload, keep: Optional[str]) -> None:
        ext = self._external_job_for(wl)
        for name, cluster in self.clusters.items():
            if name == keep or not cluster.active:
                continue
            if ext is not None:
                adapter, job = ext
                adapter.delete_remote_object(
                    cluster.environment.external_jobs, job.key)
            wstore = cluster.environment.store
            mirror = wstore.workloads.get(wl.key)
            if mirror is None:
                continue
            cluster.environment.scheduler.evict_workload(
                mirror.key, reason="MultiKueueCleanup",
                message="another worker won the admission race",
                now=cluster.last_seen, requeue=False)
            wstore.delete_workload(mirror.key)
