"""Pending-workload queue manager.

Reference parity: pkg/cache/queue/manager.go + cluster_queue.go —
per-ClusterQueue heaps ordered by (priority desc, queue-order timestamp asc,
uid), StrictFIFO vs BestEffortFIFO requeue behavior, inadmissible-workload
parking, and cohort-scoped flushing when capacity frees up.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Iterable, Optional

from kueue_oss_tpu.api.types import QueueingStrategy, StopPolicy, Workload
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_priority,
    queue_order_timestamp,
)


class RequeueReason:
    """Reference parity: pkg/cache/queue RequeueReason values."""

    GENERIC = "Generic"
    FAILED_AFTER_NOMINATION = "FailedAfterNomination"
    PENDING_PREEMPTION = "PendingPreemption"
    PREEMPTION_FAILED = "PreemptionFailed"
    NAMESPACE_MISMATCH = "NamespaceMismatch"


def _order_key(info: WorkloadInfo) -> tuple:
    # Higher priority first, then FIFO on the eviction-aware timestamp.
    return (-effective_priority(info.obj), queue_order_timestamp(info.obj),
            info.obj.uid)


class ClusterQueuePendingQueue:
    """Heap + inadmissible parking for one ClusterQueue."""

    def __init__(self, name: str, strategy: str,
                 on_change=None) -> None:
        self.name = name
        self.strategy = strategy
        self._heap: list[tuple[tuple, int, WorkloadInfo]] = []
        self._in_heap: dict[str, WorkloadInfo] = {}
        self._counter = itertools.count()
        self.inadmissible: dict[str, WorkloadInfo] = {}
        #: cycle at which inadmissible workloads were last re-queued
        self.queue_inadmissible_cycle = -1
        self.active = True
        #: called with the CQ name on any pending-count mutation (the
        #: manager uses it to keep a dirty set so metric reporting is
        #: O(changed CQs), not O(all CQs))
        self._on_change = on_change or (lambda name: None)
        #: admission-fair-sharing rank fn (info -> decayed LQ usage);
        #: set by the manager for CQs with UsageBasedAdmissionFairSharing
        self.afs_key = None
        #: scheduling-equivalence classes known NoFit since the last
        #: capacity-freed flush (cluster_queue.go noFitSchedulingHashes)
        self.no_fit_hashes: set = set()
        #: XOR accumulator over (key, heap|inadmissible) membership —
        #: mutated O(1) on every queue transition so run_until_quiet can
        #: detect quiescence without walking queue internals
        self.state_hash = 0
        #: solver-managed mode: capacity-freed flushes mark parked
        #: entries STALE instead of physically re-heaping them (the
        #: eager flush is O(parked) per finish — at flood scale that is
        #: millions of heap pushes per run). Stale entries are exported
        #: to the solver as pending; the host path materializes them
        #: (moves them back into the heap) before it ever schedules.
        self.lazy_flush = False
        #: entries parked before the latest capacity-freed flush
        #: (key -> info); they are schedulable-in-waiting, so they count
        #: in pending_totals like heap members
        self._stale: dict[str, WorkloadInfo] = {}
        #: per-resource request totals over heap + stale members,
        #: maintained O(requests) per transition so the metrics flush
        #: never sorts or walks the backlog
        #: (cluster_queue_resource_pending gauges)
        self.pending_totals: dict[str, int] = {}

    _HEAP, _INADM = 1, 2

    def _hx(self, key: str, state: int) -> None:
        self.state_hash ^= hash((key, state))

    def _tot(self, info: WorkloadInfo, sign: int) -> None:
        for psr in info.total_requests:
            for r, v in psr.requests.items():
                nv = self.pending_totals.get(r, 0) + sign * v
                if nv:
                    self.pending_totals[r] = nv
                else:
                    self.pending_totals.pop(r, None)

    def _stale_pop(self, key: str) -> None:
        info = self._stale.pop(key, None)
        if info is not None:
            self._tot(info, -1)

    def __len__(self) -> int:
        return len(self._heap) + len(self.inadmissible)

    @property
    def pending_active(self) -> int:
        return len(self._in_heap)

    @property
    def pending_inadmissible(self) -> int:
        return len(self.inadmissible)

    def push(self, info: WorkloadInfo, check_no_fit: bool = False) -> None:
        """Insert into the heap. With check_no_fit (the PushOrUpdate path,
        cluster_queue.go:371), a BestEffortFIFO queue parks workloads whose
        scheduling-equivalence class is already known NoFit."""
        from kueue_oss_tpu import features

        if (check_no_fit
                and self.strategy == QueueingStrategy.BEST_EFFORT_FIFO
                and info.key not in self._in_heap
                and self.no_fit_hashes
                and features.enabled("SchedulingEquivalenceHashing")
                and info.scheduling_hash() in self.no_fit_hashes):
            if info.key not in self.inadmissible:
                self._hx(info.key, self._INADM)
            self.inadmissible[info.key] = info
            self._stale_pop(info.key)  # updated shape => freshly parked
            self._on_change(self.name)
            return
        if info.key in self.inadmissible:
            del self.inadmissible[info.key]
            self._stale_pop(info.key)
            self._hx(info.key, self._INADM)
        if info.key in self._in_heap:
            # Re-push with fresh ordering (priority/timestamps may change).
            self.delete(info.key)
        self._in_heap[info.key] = info
        self._tot(info, +1)
        self._hx(info.key, self._HEAP)
        heapq.heappush(self._heap, (_order_key(info), next(self._counter), info))
        self._on_change(self.name)

    def pop_head(self) -> Optional[WorkloadInfo]:
        if self.afs_key is not None and self._in_heap:
            # Admission fair sharing: the head is the entry whose
            # LocalQueue has the lowest decayed usage (KEP-4136); the
            # static heap order is the tie-break. O(n) scan — usage decays
            # between cycles, so the rank can't be baked into the heap.
            info = min(self._in_heap.values(),
                       key=lambda i: (self.afs_key(i), _order_key(i)))
            del self._in_heap[info.key]
            self._tot(info, -1)
            self._hx(info.key, self._HEAP)
            # The AFS path never pops _heap, so stale tuples would pile up
            # forever; rebuild once they dominate (amortized O(1)).
            if len(self._heap) > 2 * len(self._in_heap):
                self._heap = [(k, c, i) for k, c, i in self._heap
                              if self._in_heap.get(i.key) is i]
                heapq.heapify(self._heap)
            self._on_change(self.name)
            return info
        while self._heap:
            _, _, info = heapq.heappop(self._heap)
            if self._in_heap.get(info.key) is info:
                del self._in_heap[info.key]
                self._tot(info, -1)
                self._hx(info.key, self._HEAP)
                self._on_change(self.name)
                return info
        return None

    def delete(self, key: str) -> None:
        live = self._in_heap.pop(key, None)
        if live is not None:
            self._tot(live, -1)
            self._hx(key, self._HEAP)
            self._on_change(self.name)
        if key in self.inadmissible:
            self._hx(key, self._INADM)
            self._on_change(self.name)
        self.inadmissible.pop(key, None)
        self._stale_pop(key)

    def snapshot_order(self) -> list[WorkloadInfo]:
        """Heap contents in pop (rank) order, without consuming them."""
        return sorted(self._in_heap.values(), key=_order_key)

    def park(self, key: str) -> None:
        """Move a heap entry to the inadmissible set (external decision).

        Re-parking an already-parked entry refreshes it: a stale entry
        the solver retried and could not admit is parked *again* (it is
        no longer owed a retry until the next capacity-freed flush)."""
        info = self._in_heap.get(key)
        if info is not None:
            self.delete(key)
            self.inadmissible[key] = info
            self._hx(key, self._INADM)
            self._on_change(self.name)
        elif key in self.inadmissible:
            self._stale_pop(key)

    def requeue_if_not_present(self, info: WorkloadInfo, reason: str,
                               pop_cycle: int = -1) -> bool:
        """Requeue semantics (reference: cluster_queue.go requeueIfNotPresent).

        StrictFIFO always goes back to the heap (the head blocks the queue).
        BestEffortFIFO parks generically-inadmissible workloads until an
        event in the cohort frees capacity; scheduling-affecting reasons go
        straight back to the heap. A capacity-freed flush that fired after
        this workload was popped (queue_inadmissible_cycle >= pop_cycle)
        also sends it to the heap, so mid-cycle events aren't lost.
        """
        if info.key in self._in_heap or info.key in self.inadmissible:
            return False
        if (self.strategy == QueueingStrategy.STRICT_FIFO
                or reason != RequeueReason.GENERIC
                or (pop_cycle >= 0
                    and self.queue_inadmissible_cycle >= pop_cycle)):
            self.push(info)
            return True
        self.inadmissible[info.key] = info
        self._hx(info.key, self._INADM)
        self._on_change(self.name)
        self._handle_inadmissible_hash(info)
        return False

    def _handle_inadmissible_hash(self, info: WorkloadInfo) -> None:
        """Record the parked workload's equivalence class as NoFit and
        bulk-move equivalent heap entries to inadmissible, so the scheduler
        never pays a nomination cycle for a shape it just rejected
        (cluster_queue.go handleInadmissibleHash, :559-575)."""
        from kueue_oss_tpu import features

        if (self.strategy != QueueingStrategy.BEST_EFFORT_FIFO
                or not features.enabled("SchedulingEquivalenceHashing")):
            return
        h = info.scheduling_hash()
        self.no_fit_hashes.add(h)
        equivalent = [k for k, i in self._in_heap.items()
                      if i.scheduling_hash() == h]
        for k in equivalent:
            self.park(k)

    def queue_inadmissible(self, cycle: int) -> bool:
        """Move all parked workloads back into the heap. Known-NoFit
        classes reset: freed capacity may fit them now
        (inadmissible_workloads.go:174).

        In solver-managed (lazy) mode the move is virtual: every parked
        entry becomes STALE in O(parked) set construction — no heap
        pushes. The solver exports stale entries as pending; the host
        path materializes them first (materialize_stale)."""
        self.no_fit_hashes.clear()
        if self.lazy_flush:
            self.queue_inadmissible_cycle = cycle
            if not self.inadmissible:
                return False
            changed = False
            for k, info in self.inadmissible.items():
                if k not in self._stale:
                    self._stale[k] = info
                    self._tot(info, +1)  # schedulable-in-waiting again
                    changed = True
            if changed:
                self._on_change(self.name)
            return True
        if not self.inadmissible:
            self.queue_inadmissible_cycle = cycle
            return False
        parked = list(self.inadmissible.values())
        self.inadmissible.clear()
        for info in parked:
            self._stale_pop(info.key)
            self._hx(info.key, self._INADM)
            self.push(info)
        self.queue_inadmissible_cycle = cycle
        self._on_change(self.name)
        return True

    def stale_infos(self) -> list[WorkloadInfo]:
        """Parked entries owed a retry since the last capacity-freed
        flush (lazy mode)."""
        return list(self._stale.values())

    def materialize_stale(self) -> bool:
        """Physically re-heap stale entries (host-path handoff)."""
        if not self._stale:
            return False
        for k in list(self._stale):
            info = self.inadmissible.pop(k, None)
            self._stale_pop(k)
            if info is not None:
                self._hx(k, self._INADM)
                self.push(info)
        self._on_change(self.name)
        return True


class QueueManager:
    """Reference parity: pkg/cache/queue/manager.go."""

    def __init__(self, store: Store, afs=None) -> None:
        self.store = store
        #: guards all queue mutations; the condition signals new pending
        #: work the way the reference's manager blocks scheduler Heads()
        #: on a sync.Cond (manager.go Heads/CleanUpOnContext)
        self._mu = threading.RLock()
        self._cond = threading.Condition(self._mu)
        self.queues: dict[str, ClusterQueuePendingQueue] = {}
        self.cycle = 0
        #: CQs whose pending counts changed since the last drain
        self.dirty_cqs: set[str] = set()
        #: optional AfsManager (admission fair sharing, KEP-4136)
        self.afs = afs
        #: wall-clock of the current scheduling cycle, used by AFS decay
        self.current_time = 0.0
        #: solver-managed lazy capacity-freed flushes (set_lazy_flush)
        self.lazy_flush = False
        #: monotone count of genuinely NEW pending entries; the
        #: scheduler's solver re-engagement gate diffs it to detect
        #: fresh arrival floods. Keys ever counted are remembered so
        #: eviction-backoff requeues and other re-adds of known
        #: workloads don't masquerade as arrivals.
        self.new_pending_total = 0
        self._counted_pending: set[str] = set()
        #: second-pass queue (second_pass_queue.go): min-heap of
        #: (ready_at, workload key) plus per-key attempt counts driving
        #: the 1s -> 30s exponential backoff
        self._second_pass_heap: list[tuple[float, str]] = []
        self._second_pass_iteration: dict[str, int] = {}
        for cq in store.cluster_queues.values():
            self.add_cluster_queue(cq.name)
        # Initial LIST: enqueue pending workloads already in the store
        # (reference parity: informer list+watch startup).
        for wl in store.workloads.values():
            self.add_or_update_workload(wl)
        store.watch(self._on_event)

    # -- second pass (TAS delayed assignment; second_pass_queue.go) ---------

    SECOND_PASS_INITIAL_BACKOFF_S = 1.0
    SECOND_PASS_MAX_BACKOFF_S = 30.0

    def queue_second_pass(self, key: str, now: float) -> float:
        """Schedule a workload for a second scheduling pass with
        exponential delay (manager.go:868 QueueSecondPassIfNeeded).
        Returns the ready-at time."""
        it = self._second_pass_iteration.get(key, 0) + 1
        self._second_pass_iteration[key] = it
        delay = min(self.SECOND_PASS_INITIAL_BACKOFF_S * (2 ** (it - 1)),
                    self.SECOND_PASS_MAX_BACKOFF_S)
        ready_at = now + delay
        heapq.heappush(self._second_pass_heap, (ready_at, key))
        return ready_at

    def take_second_pass_ready(self, now: float) -> list[str]:
        out = []
        while self._second_pass_heap and self._second_pass_heap[0][0] <= now:
            _, key = heapq.heappop(self._second_pass_heap)
            out.append(key)
        return out

    def clear_second_pass(self, key: str) -> None:
        self._second_pass_iteration.pop(key, None)

    def second_pass_pending(self, key: str) -> bool:
        return key in self._second_pass_iteration

    def next_second_pass_at(self) -> Optional[float]:
        return self._second_pass_heap[0][0] if self._second_pass_heap else None

    # -- CQ lifecycle ------------------------------------------------------

    def add_cluster_queue(self, name: str) -> None:
        spec = self.store.cluster_queues[name]
        if name not in self.queues:
            self.queues[name] = ClusterQueuePendingQueue(
                name, spec.queueing_strategy,
                on_change=self.dirty_cqs.add)
            self.queues[name].lazy_flush = self.lazy_flush
        q = self.queues[name]
        q.strategy = spec.queueing_strategy
        q.active = spec.stop_policy == StopPolicy.NONE
        from kueue_oss_tpu import features

        if (self.afs is not None and spec.admission_scope is not None
                and features.enabled("AdmissionFairSharing")
                and spec.admission_scope.admission_mode
                == "UsageBasedAdmissionFairSharing"):
            q.afs_key = lambda info: self.afs.ordering_key(
                f"{info.obj.namespace}/{info.obj.queue_name}",
                self.current_time)
        else:
            q.afs_key = None

    def _on_event(self, event) -> None:
        with self._mu:
            self._on_event_locked(event)
            self._cond.notify_all()

    def _on_event_locked(self, event) -> None:
        verb, kind, obj = event
        if kind == "ClusterQueue":
            if verb == "delete":
                q = self.queues.pop(obj.name, None)
                if q is not None:
                    self.dirty_cqs.add(obj.name)
                return
            self.add_cluster_queue(obj.name)
            self.queues[obj.name].queue_inadmissible(self.cycle)
        elif kind == "LocalQueue":
            # list(...) snapshots: watchers run outside Store._lock, so a
            # concurrent add_workload may mutate the dict mid-iteration
            if verb == "delete":
                # Workloads of a deleted LQ are no longer schedulable.
                q = self.queues.get(obj.cluster_queue)
                if q is not None:
                    for wl in list(self.store.workloads.values()):
                        if (wl.namespace == obj.namespace
                                and wl.queue_name == obj.name):
                            q.delete(wl.key)
                return
            # Resume/stop of an LQ re-evaluates its pending workloads.
            for wl in list(self.store.workloads.values()):
                if (wl.namespace == obj.namespace
                        and wl.queue_name == obj.name):
                    self.add_or_update_workload(wl)
        elif kind == "Workload":
            if verb in ("add", "update"):
                self.add_or_update_workload(obj)
            elif verb == "delete":
                self._counted_pending.discard(obj.key)
                cq = self._cq_for(obj)
                if cq is not None:
                    self.queues[cq].delete(obj.key)
                    self.flush_cohort_for(cq)

    # -- workload flow -----------------------------------------------------

    def _cq_for(self, wl: Workload) -> Optional[str]:
        cq = self.store.cluster_queue_for(wl)
        if cq is None and wl.status.admission is not None:
            cq = wl.status.admission.cluster_queue
        return cq if cq in self.queues else None

    def _local_queue_stopped(self, wl: Workload) -> bool:
        """A Hold/HoldAndDrain LocalQueue keeps its workloads out of the
        pending heaps entirely (reference: manager.go LocalQueue active
        check; the drain side is handled by the Workload controller)."""
        lq = self.store.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        return lq is not None and lq.stop_policy != StopPolicy.NONE

    def add_or_update_workload(self, wl: Workload) -> bool:
        """Queue a workload if it is pending (active, no quota reserved)."""
        with self._mu:
            queued = self._add_or_update_locked(wl)
            if queued:
                self._cond.notify_all()
            return queued

    def _add_or_update_locked(self, wl: Workload) -> bool:
        cq = self._cq_for(wl)
        if cq is None:
            return False
        from kueue_oss_tpu import features

        # A concurrent-admission parent never schedules directly; its
        # variants do (concurrentadmission controller fan-out). With the
        # gate off the parent falls back to normal scheduling.
        is_ca_parent = (wl.ca_parent
                        and features.enabled("ConcurrentAdmission"))
        if (not wl.active or wl.is_quota_reserved or wl.is_finished
                or is_ca_parent or self._local_queue_stopped(wl)):
            self.queues[cq].delete(wl.key)
            return False
        rs = wl.status.requeue_state
        if rs is not None and rs.requeue_at is not None:
            # Eviction backoff pending; Scheduler.requeue_due clears the
            # gate when the backoff expires. Drop any stale heap entry so
            # a gated workload can't still be popped.
            self.queues[cq].delete(wl.key)
            return False
        q = self.queues[cq]
        # fresh-arrival signal for the scheduler's solver re-engagement
        # gate: count each workload key ONCE, the first time it queues —
        # via any path (add event, update event, LocalQueue resume
        # sweep) — so a second flood re-engages the device drain even
        # with zero finishes, while eviction-backoff requeues and other
        # re-adds of known workloads don't masquerade as arrivals.
        if wl.key not in self._counted_pending:
            self._counted_pending.add(wl.key)
            self.new_pending_total += 1
        q.push(WorkloadInfo(wl, cluster_queue=cq), check_no_fit=True)
        return True

    def requeue_workload(self, info: WorkloadInfo, reason: str) -> bool:
        """Re-fetch latest object state and requeue (manager.go:645)."""
        with self._mu:
            wl = self.store.workloads.get(info.key)
            if (wl is None or not wl.active or wl.is_quota_reserved
                    or wl.is_finished or self._local_queue_stopped(wl)):
                return False
            fresh = WorkloadInfo(wl, cluster_queue=info.cluster_queue)
            fresh.last_assignment = info.last_assignment
            q = self.queues.get(info.cluster_queue)
            if q is None:
                return False
            requeued = q.requeue_if_not_present(
                fresh, reason, pop_cycle=getattr(info, "pop_cycle", -1))
            if requeued:
                self._cond.notify_all()
            return requeued

    def delete_workload(self, wl: Workload) -> None:
        with self._mu:
            cq = self._cq_for(wl)
            if cq is not None:
                self.queues[cq].delete(wl.key)

    # -- heads -------------------------------------------------------------

    def heads(self) -> list[WorkloadInfo]:
        """Pop the head of every active ClusterQueue (one per CQ).

        Non-popped entries stay; non-admitted heads must be requeued by the
        scheduler (mirrors Heads+requeue contract of the reference cycle).
        """
        with self._mu:
            self.cycle += 1
            out: list[WorkloadInfo] = []
            for q in self.queues.values():
                if not q.active:
                    continue
                head = q.pop_head()
                if head is not None:
                    head.pop_cycle = self.cycle
                    out.append(head)
            return out

    def wait_for_pending(self, timeout: Optional[float] = None) -> bool:
        """Block until some queue has pending work (or timeout); the
        reference scheduler blocks in manager.Heads() the same way."""
        with self._cond:
            if self.has_pending():
                return True
            self._cond.wait(timeout)
            return self.has_pending()

    def wakeup(self) -> None:
        """Wake any blocked wait_for_pending (shutdown / external nudge)."""
        with self._cond:
            self._cond.notify_all()

    def has_pending(self) -> bool:
        with self._mu:
            return any(len(q._in_heap) > 0 or len(q._stale) > 0
                       for q in self.queues.values() if q.active)

    def set_lazy_flush(self, on: bool) -> None:
        """Toggle solver-managed lazy flushing; turning it off hands any
        stale entries back to the host path."""
        with self._mu:
            self.lazy_flush = on
            for q in self.queues.values():
                q.lazy_flush = on
                if not on:
                    q.materialize_stale()
            self._cond.notify_all()

    def any_stale(self) -> bool:
        with self._mu:
            return any(q._stale for q in self.queues.values() if q.active)

    def materialize_stale_all(self) -> bool:
        """Re-heap every stale entry (host-path handoff before host
        cycles run with the solver disengaged)."""
        with self._mu:
            moved = False
            for q in self.queues.values():
                moved = q.materialize_stale() or moved
            if moved:
                self._cond.notify_all()
            return moved

    def solver_backlog_count(self) -> int:
        """Pending work the solver would drain: heap entries plus stale
        parked entries owed a retry."""
        with self._mu:
            return sum(len(q._in_heap) + len(q._stale)
                       for q in self.queues.values() if q.active)

    def cqs_with_pending(self) -> list[str]:
        """Active CQs holding any drainable work (heap or stale) —
        the streaming fast path's per-tick candidate list
        (scheduler/streaming.py), read in one pass under the mutex."""
        with self._mu:
            return [name for name, q in self.queues.items()
                    if q.active and (q._in_heap or q._stale)]

    def membership_fingerprint(self) -> int:
        """Order-insensitive digest of every queue's (key, heap|parked)
        membership, maintained O(1) per transition — the scheduler's
        run_until_quiet quiescence probe (replaces walking queue internals)."""
        with self._mu:
            acc = 0
            for name, q in self.queues.items():
                acc ^= hash((name, q.state_hash))
            return acc

    def drain_dirty_pending_counts(self) -> dict[str, tuple[int, int]]:
        """Pending counts for CQs that changed since the last drain —
        O(changed CQs) so the scheduler's metric refresh stays off the
        all-CQs path."""
        with self._mu:
            dirty, self.dirty_cqs = self.dirty_cqs, set()
            out = {}
            for name in dirty:
                q = self.queues.get(name)
                if q is not None:
                    out[name] = (q.pending_active, q.pending_inadmissible)
            return out

    def pending_counts(self) -> dict[str, tuple[int, int]]:
        with self._mu:
            return {
                name: (q.pending_active, q.pending_inadmissible)
                for name, q in self.queues.items()
            }

    # -- capacity-freed events ---------------------------------------------

    def _cohort_members(self, cq_name: str) -> Iterable[str]:
        spec = self.store.cluster_queues.get(cq_name)
        if spec is None or not spec.cohort:
            return [cq_name]
        # All CQs sharing the cohort forest root with cq_name.
        roots: dict[str, str] = {}

        def root_of(cohort_name: str, seen=None) -> str:
            if cohort_name in roots:
                return roots[cohort_name]
            seen = seen or set()
            cur = cohort_name
            while True:
                if cur in seen:
                    break
                seen.add(cur)
                spec_c = self.store.cohorts.get(cur)
                if spec_c is None or not spec_c.parent:
                    break
                cur = spec_c.parent
            roots[cohort_name] = cur
            return cur

        my_root = root_of(spec.cohort)
        return [
            name for name, other in list(self.store.cluster_queues.items())
            if other.cohort and root_of(other.cohort) == my_root
        ]

    def flush_cohort_for(self, cq_name: str) -> None:
        """Re-queue inadmissible workloads across the whole cohort.

        Called when capacity may have freed (workload finished/evicted) —
        reference: QueueAssociatedInadmissibleWorkloadsAfter.
        """
        with self._mu:
            for member in self._cohort_members(cq_name):
                q = self.queues.get(member)
                if q is not None:
                    q.queue_inadmissible(self.cycle)
            self._cond.notify_all()

    def report_workload_finished(self, wl: Workload) -> None:
        cq = self._cq_for(wl)
        if cq is not None:
            self.flush_cohort_for(cq)

    def report_workload_evicted(self, wl: Workload) -> None:
        cq = self._cq_for(wl)
        if cq is not None:
            self.flush_cohort_for(cq)
