"""Per-cycle scheduling snapshot over the quota forest.

Reference parity: pkg/cache/scheduler/snapshot.go, clusterqueue_snapshot.go,
cohort_snapshot.go. The snapshot is built once per scheduling cycle and then
mutated freely (usage simulation, workload removal) without affecting the
authoritative store; the TPU solver exports its tensors from this object.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional

from kueue_oss_tpu.api.types import (
    ClusterQueue,
    FlavorResource,
    ResourceFlavor,
    ResourceQuota,
)
from kueue_oss_tpu.core.quota import (
    DRS,
    QuotaForest,
    QuotaNode,
    dominant_resource_share,
)
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_per_pod_requests,
)
from kueue_oss_tpu.tas.snapshot import (
    TASAssignmentResult,
    TASFlavorSnapshot,
    TASPodSetRequest,
    build_tas_flavor_snapshot,
)


class CohortSnapshot:
    """A cohort node plus navigation to child CQ snapshots."""

    def __init__(self, node: QuotaNode, snapshot: "Snapshot") -> None:
        self.node = node
        self._snapshot = snapshot

    @property
    def name(self) -> str:
        return self.node.name

    def has_parent(self) -> bool:
        return self.node.parent is not None

    def parent(self) -> Optional["CohortSnapshot"]:
        if self.node.parent is None:
            return None
        return self._snapshot.cohort_snapshot(self.node.parent)

    def root(self) -> "CohortSnapshot":
        return self._snapshot.cohort_snapshot(self.node.root())

    def child_cohorts(self) -> list["CohortSnapshot"]:
        return [
            self._snapshot.cohort_snapshot(c)
            for c in self.node.children.values()
            if not c.is_cq
        ]

    def child_cqs(self) -> list["ClusterQueueSnapshot"]:
        return [
            self._snapshot.cq_for_node(c)
            for c in self.node.children.values()
            if c.is_cq
        ]

    def child_count(self) -> int:
        return len(self.node.children)

    def subtree_cluster_queues(self) -> Iterator["ClusterQueueSnapshot"]:
        for cq in self.child_cqs():
            yield cq
        for coh in self.child_cohorts():
            yield from coh.subtree_cluster_queues()

    def is_within_nominal(self, frs: Iterable[FlavorResource]) -> bool:
        return self.node.is_within_nominal(frs)

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        return self.node.borrowing_with(fr, val)

    def dominant_resource_share(self) -> DRS:
        return dominant_resource_share(self.node)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, CohortSnapshot) and other.node is self.node

    def __hash__(self) -> int:
        return id(self.node)


class ClusterQueueSnapshot:
    """Reference parity: pkg/cache/scheduler/clusterqueue_snapshot.go."""

    def __init__(self, spec: ClusterQueue, node: QuotaNode,
                 snapshot: "Snapshot", generation: int) -> None:
        self.spec = spec
        self.name = spec.name
        self.node = node
        self._snapshot = snapshot
        self.generation = generation
        #: admitted workloads (holding quota) by workload key
        self.workloads: dict[str, WorkloadInfo] = {}
        # TAS lookups are hot (checked per podset x flavor candidate);
        # the snapshot is immutable for the cycle, so compute once.
        cq_flavors = [fq.name for rg in spec.resource_groups
                      for fq in rg.flavors]
        self.tas_flavors: dict[str, TASFlavorSnapshot] = {
            f: snapshot.tas_flavors[f] for f in cq_flavors
            if f in snapshot.tas_flavors
        }
        self._tas_only = bool(cq_flavors) and (
            len(self.tas_flavors) == len(set(cq_flavors)))

    # -- TAS ---------------------------------------------------------------

    def is_tas_only(self) -> bool:
        """True when every flavor in the CQ is a TAS flavor
        (reference: ClusterQueueSnapshot.IsTASOnly)."""
        return self._tas_only

    def find_topology_assignments_for_workload(
        self,
        tas_requests: dict[str, list[TASPodSetRequest]],
        simulate_empty: bool = False,
        workload=None,
    ) -> dict[str, TASAssignmentResult]:
        """Per-flavor placement (clusterqueue_snapshot.go:191)."""
        result: dict[str, TASAssignmentResult] = {}
        for flavor, requests in tas_requests.items():
            snap = self._snapshot.tas_flavors.get(flavor)
            if snap is None:
                for tr in requests:
                    result[tr.podset.name] = TASAssignmentResult(
                        failure=f"flavor {flavor} has no TAS information")
                continue
            result.update(snap.find_topology_assignments(
                requests, simulate_empty=simulate_empty, workload=workload))
        return result

    # -- hierarchy ---------------------------------------------------------

    def has_parent(self) -> bool:
        return self.node.parent is not None

    def parent(self) -> Optional[CohortSnapshot]:
        if self.node.parent is None:
            return None
        return self._snapshot.cohort_snapshot(self.node.parent)

    def path_parent_to_root(self) -> Iterator[CohortSnapshot]:
        cur = self.node.parent
        while cur is not None:
            yield self._snapshot.cohort_snapshot(cur)
            cur = cur.parent

    # -- quota queries -----------------------------------------------------

    def quota_for(self, fr: FlavorResource) -> ResourceQuota:
        q = self.node.quotas.get(fr)
        return q if q is not None else ResourceQuota(name=fr[1], nominal=0)

    def available(self, fr: FlavorResource) -> int:
        return self.node.available(fr)

    def potential_available(self, fr: FlavorResource) -> int:
        return self.node.potential_available(fr)

    def borrowing(self, fr: FlavorResource) -> bool:
        return self.node.usage.get(fr, 0) > self.node.subtree_quota.get(fr, 0)

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        return self.node.borrowing_with(fr, val)

    def is_within_nominal(self, frs: Iterable[FlavorResource]) -> bool:
        return self.node.is_within_nominal(frs)

    def fits(self, usage: dict[FlavorResource, int]) -> bool:
        return self.node.fits(usage)

    def rg_by_resource(self, resource: str):
        for rg in self.spec.resource_groups:
            if resource in rg.covered_resources:
                return rg
        return None

    # -- usage mutation ----------------------------------------------------

    def add_usage(self, usage: dict[FlavorResource, int]) -> None:
        for fr, v in usage.items():
            self.node.add_usage(fr, v)

    def remove_usage(self, usage: dict[FlavorResource, int]) -> None:
        for fr, v in usage.items():
            self.node.remove_usage(fr, v)

    def simulate_usage_addition(
        self, usage: dict[FlavorResource, int]
    ) -> Callable[[], None]:
        self.add_usage(usage)
        return lambda: self.remove_usage(usage)

    def simulate_usage_removal(
        self, usage: dict[FlavorResource, int]
    ) -> Callable[[], None]:
        self.remove_usage(usage)
        return lambda: self.add_usage(usage)

    # -- fair sharing ------------------------------------------------------

    def fair_weight(self) -> float:
        return self.spec.fair_sharing.weight

    def dominant_resource_share(
        self, wl_req: Optional[dict[FlavorResource, int]] = None
    ) -> DRS:
        return dominant_resource_share(self.node, wl_req)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ClusterQueueSnapshot) and other.node is self.node

    def __hash__(self) -> int:
        return id(self.node)

    def __repr__(self) -> str:
        return f"CQSnapshot({self.name})"


class Snapshot:
    """Whole-cluster scheduling snapshot."""

    def __init__(
        self,
        forest: QuotaForest,
        cluster_queues: dict[str, ClusterQueueSnapshot],
        resource_flavors: dict[str, ResourceFlavor],
        inactive_cluster_queues: frozenset[str] = frozenset(),
        tas_flavors: Optional[dict[str, TASFlavorSnapshot]] = None,
    ) -> None:
        self.forest = forest
        self.cluster_queues = cluster_queues
        self.resource_flavors = resource_flavors
        self.inactive_cluster_queues = inactive_cluster_queues
        #: TAS snapshots keyed by ResourceFlavor name (flavors naming a
        #: Topology); shared across CQs — the nodes are physical
        self.tas_flavors: dict[str, TASFlavorSnapshot] = tas_flavors or {}
        self._cohort_snapshots: dict[int, CohortSnapshot] = {}
        self._node_to_cq: dict[int, ClusterQueueSnapshot] = {
            id(cq.node): cq for cq in cluster_queues.values()
        }

    def cluster_queue(self, name: str) -> Optional[ClusterQueueSnapshot]:
        return self.cluster_queues.get(name)

    def cq_for_node(self, node: QuotaNode) -> ClusterQueueSnapshot:
        return self._node_to_cq[id(node)]

    def cohort_snapshot(self, node: QuotaNode) -> CohortSnapshot:
        cs = self._cohort_snapshots.get(id(node))
        if cs is None:
            cs = CohortSnapshot(node, self)
            self._cohort_snapshots[id(node)] = cs
        return cs

    # -- workload add/remove (preemption simulation) -----------------------

    def _tas_usage_entries(self, info: WorkloadInfo):
        """Yield (flavor, domain_values, per_pod_requests, count) for every
        TAS domain assignment held by an admitted workload."""
        wl = info.obj
        if wl.status.admission is None or not self.tas_flavors:
            return
        podsets = {ps.name: ps for ps in wl.podsets}
        for psa in wl.status.admission.podset_assignments:
            ta = psa.topology_assignment
            if ta is None:
                continue
            flavor = next(
                (f for f in psa.flavors.values() if f in self.tas_flavors),
                None)
            if flavor is None:
                continue
            ps = podsets.get(psa.name)
            per_pod = (effective_per_pod_requests(ps, wl.namespace)
                       if ps is not None else {})
            for dom in ta.domains:
                yield flavor, tuple(dom.values), per_pod, dom.count

    def _apply_tas_usage(self, info: WorkloadInfo, sign: int) -> None:
        for flavor, values, per_pod, count in self._tas_usage_entries(info):
            snap = self.tas_flavors[flavor]
            if sign > 0:
                snap.add_tas_usage(values, per_pod, count)
            else:
                snap.remove_tas_usage(values, per_pod, count)

    def remove_workload(self, info: WorkloadInfo) -> None:
        cq = self.cluster_queues[info.cluster_queue]
        cq.workloads.pop(info.key, None)
        cq.remove_usage(info.usage())
        self._apply_tas_usage(info, -1)

    def add_workload(self, info: WorkloadInfo) -> None:
        cq = self.cluster_queues[info.cluster_queue]
        cq.workloads[info.key] = info
        cq.add_usage(info.usage())
        self._apply_tas_usage(info, +1)

    def simulate_workload_removal(
        self, infos: list[WorkloadInfo]
    ) -> Callable[[], None]:
        """Remove only the usage (not queue membership); O(1) revert."""
        for info in infos:
            self.cluster_queues[info.cluster_queue].remove_usage(info.usage())
            self._apply_tas_usage(info, -1)

        def revert() -> None:
            for info in infos:
                self.cluster_queues[info.cluster_queue].add_usage(info.usage())
                self._apply_tas_usage(info, +1)

        return revert


def build_snapshot(store: Store, profile_mixed: bool = False) -> Snapshot:
    """Build a cycle snapshot from the store's current state."""
    forest = QuotaForest()
    forest.build(store.cluster_queues.values(), store.cohorts.values())

    from kueue_oss_tpu import features

    tas_flavors: dict[str, TASFlavorSnapshot] = {}
    for rf in store.resource_flavors.values():
        if rf.topology_name is None:
            continue
        if not features.enabled("TopologyAwareScheduling"):
            continue
        topology = store.topologies.get(rf.topology_name)
        if topology is None:
            continue
        tas_flavors[rf.name] = build_tas_flavor_snapshot(
            topology.name, topology.levels, store.nodes.values(),
            flavor_node_labels=rf.node_labels, tolerations=rf.tolerations,
            profile_mixed=profile_mixed)

    cqs: dict[str, ClusterQueueSnapshot] = {}
    snapshot = Snapshot(
        forest,
        cqs,
        dict(store.resource_flavors),
        inactive_cluster_queues=frozenset(
            name for name, cq in store.cluster_queues.items()
            if cq.stop_policy != "None"
        ),
        tas_flavors=tas_flavors,
    )
    for name, spec in store.cluster_queues.items():
        cqs[name] = ClusterQueueSnapshot(
            spec, forest.cqs[name], snapshot,
            generation=store.cq_generation.get(name, 0),
        )
    snapshot._node_to_cq = {id(cq.node): cq for cq in cqs.values()}

    for info in store.admitted_infos():
        # CQ targeting + WorkloadInfo construction live in the store's
        # admitted index (cached across cycles); skip CQs deleted since.
        if info.cluster_queue not in cqs:
            continue
        snapshot.add_workload(info)
    return snapshot
