"""Admission fair sharing (KEP-4136).

Reference parity: pkg/cache/queue/afs + pkg/util/admissionfairsharing —
LocalQueues accumulate *historical* resource usage that decays with a
configurable half-life; within a ClusterQueue whose admissionScope is
UsageBasedAdmissionFairSharing, pending workloads from lighter-usage
LocalQueues are admitted first (cluster_queue.go queueOrderingFunc AFS
branch). Admissions immediately charge an *entry penalty* equal to the
admitted usage so back-to-back admissions from one LQ can't outrun the
usage sampling (afs/entry_penalties.go; scheduler.go:1105 subtracts the
penalty once sampling catches up — here the penalty IS the sample).
"""

from __future__ import annotations

import math
from typing import Optional

from kueue_oss_tpu.config.configuration import AdmissionFairSharingConfig

#: resources counted when no explicit weights are configured
_DEFAULT_WEIGHT = 1.0


class AfsManager:
    """Decayed per-LocalQueue usage store."""

    def __init__(self, config: Optional[AdmissionFairSharingConfig] = None,
                 lq_weights: Optional[dict[str, float]] = None) -> None:
        self.config = config or AdmissionFairSharingConfig()
        #: lq key -> (resource -> decayed quantity, last decay timestamp)
        self._usage: dict[str, tuple[dict[str, float], float]] = {}
        #: optional per-LQ fair-sharing weight (localqueue fairSharing.weight)
        self.lq_weights = lq_weights or {}

    # -- decay model --------------------------------------------------------

    def _decay_factor(self, dt: float) -> float:
        hl = self.config.usage_half_life_time_seconds
        if hl <= 0:
            return 0.0
        return math.pow(0.5, max(dt, 0.0) / hl)

    def _decayed(self, lq_key: str, now: float) -> dict[str, float]:
        entry = self._usage.get(lq_key)
        if entry is None:
            return {}
        usage, t0 = entry
        f = self._decay_factor(now - t0)
        return {r: q * f for r, q in usage.items()}

    # -- writes -------------------------------------------------------------

    def record_admission(self, lq_key: str, usage: dict[str, int],
                         now: float) -> None:
        """Charge an admitted workload's usage to its LocalQueue (entry
        penalty + sampled usage in one step)."""
        current = self._decayed(lq_key, now)
        for r, q in usage.items():
            current[r] = current.get(r, 0.0) + float(q)
        self._usage[lq_key] = (current, now)

    def reset_lq(self, lq_key: str) -> None:
        self._usage.pop(lq_key, None)

    # -- reads --------------------------------------------------------------

    def lq_usage(self, lq_key: str, now: float) -> dict[str, float]:
        return self._decayed(lq_key, now)

    def weighted_usage(self, lq_key: str, now: float) -> float:
        """Scalarized usage: sum of weight[r] * usage[r], divided by the
        LQ's fair-sharing weight (admissionfairsharing.go)."""
        weights = self.config.resource_weights
        total = 0.0
        for r, q in self._decayed(lq_key, now).items():
            total += weights.get(r, _DEFAULT_WEIGHT) * q
        lq_w = self.lq_weights.get(lq_key, 1.0)
        if lq_w <= 0:
            return math.inf if total > 0 else 0.0
        return total / lq_w

    def ordering_key(self, lq_key: str, now: float) -> float:
        return self.weighted_usage(lq_key, now)
