"""Core state layer: hierarchical quota algebra, caches, queue manager.

Reference parity: pkg/cache/{scheduler,queue,hierarchy} of hiboyang/kueue_oss.
"""
