"""In-memory object store — the control-plane state backing.

The reference persists all state as CRDs in etcd behind an apiserver
(SURVEY.md §5 checkpoint/resume: the store is the only source of truth, and
caches rebuild from watches). Here the store is an in-process dict-of-objects
with the same contract: everything durable lives on the objects' status; the
scheduler and controllers read/write through it, and watchers can subscribe
to change events.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from kueue_oss_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    Node,
    ResourceFlavor,
    Topology,
    Workload,
    WorkloadPriorityClass,
)

Event = tuple[str, str, object]  # (verb, kind, obj)


class Store:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.cluster_queues: dict[str, ClusterQueue] = {}
        self.cohorts: dict[str, Cohort] = {}
        self.local_queues: dict[str, LocalQueue] = {}  # key "ns/name"
        self.resource_flavors: dict[str, ResourceFlavor] = {}
        self.topologies: dict[str, Topology] = {}
        self.admission_checks: dict[str, AdmissionCheck] = {}
        self.priority_classes: dict[str, WorkloadPriorityClass] = {}
        self.workloads: dict[str, Workload] = {}  # key "ns/name"
        self.nodes: dict[str, Node] = {}
        self.namespaces: dict[str, dict[str, str]] = {"default": {}}
        #: bumped whenever a CQ's quota config changes; invalidates flavor cursors
        self.cq_generation: dict[str, int] = {}
        self._watchers: list[Callable[[Event], None]] = []
        #: index of workloads currently holding quota, maintained on every
        #: workload write so per-cycle snapshot builds are O(admitted), not
        #: O(all workloads) (the reference keeps admitted usage in a
        #: dedicated cache fed by watches, pkg/cache/scheduler/cache.go)
        self._admitted: dict[str, Workload] = {}
        #: cached WorkloadInfo for admitted workloads; invalidated on write
        self._admitted_infos: dict[str, object] = {}
        #: keys whose FINISHED transition was counted into the
        #: retained-finished gauges (see _track_finished)
        self._finished_counted: set[str] = set()
        #: cloned/simulation stores must not touch the process-wide
        #: metric registry (Store.clone sets this False)
        self._metrics_enabled = True
        #: generation of the global request-shaping config (LimitRanges /
        #: resource transformations) the info cache was computed under
        self._info_cache_gen = -1
        #: persist.PersistenceManager wired by attach(); the scheduler
        #: and solver engine write decision intents / cycle-end flushes
        #: through this handle (docs/DURABILITY.md). None = volatile
        #: store (clones, simulations, tests).
        self.persistence = None

    def clone(self) -> "Store":
        """Deep copy of all objects into a fresh Store — no watchers, a
        new lock (dry-run scheduling, restart/rebuild tests)."""
        import copy

        with self._lock:
            out = Store()
            out._metrics_enabled = False
            out.namespaces = copy.deepcopy(self.namespaces)
            for cohort in self.cohorts.values():
                out.upsert_cohort(copy.deepcopy(cohort))
            for rf in self.resource_flavors.values():
                out.upsert_resource_flavor(copy.deepcopy(rf))
            for t in self.topologies.values():
                out.upsert_topology(copy.deepcopy(t))
            for ac in self.admission_checks.values():
                out.upsert_admission_check(copy.deepcopy(ac))
            for pc in self.priority_classes.values():
                out.upsert_priority_class(copy.deepcopy(pc))
            for cq in self.cluster_queues.values():
                out.upsert_cluster_queue(copy.deepcopy(cq))
            for lq in self.local_queues.values():
                out.upsert_local_queue(copy.deepcopy(lq))
            for node in self.nodes.values():
                out.upsert_node(copy.deepcopy(node))
            for wl in self.workloads.values():
                out.add_workload(copy.deepcopy(wl))
            out.cq_generation = dict(self.cq_generation)
            return out

    # -- watch -------------------------------------------------------------

    def watch(self, fn: Callable[[Event], None]) -> None:
        self._watchers.append(fn)

    def _emit(self, verb: str, kind: str, obj: object) -> None:
        for fn in self._watchers:
            fn((verb, kind, obj))

    # -- writers -----------------------------------------------------------

    def upsert_cluster_queue(self, cq: ClusterQueue) -> None:
        with self._lock:
            verb = "update" if cq.name in self.cluster_queues else "add"
            self.cluster_queues[cq.name] = cq
            self.cq_generation[cq.name] = self.cq_generation.get(cq.name, 0) + 1
        self._emit(verb, "ClusterQueue", cq)

    def delete_cluster_queue(self, name: str) -> Optional[ClusterQueue]:
        with self._lock:
            cq = self.cluster_queues.pop(name, None)
            self.cq_generation.pop(name, None)
        if cq is not None:
            self._emit("delete", "ClusterQueue", cq)
        return cq

    def delete_local_queue(self, key: str) -> Optional[LocalQueue]:
        with self._lock:
            lq = self.local_queues.pop(key, None)
        if lq is not None:
            self._emit("delete", "LocalQueue", lq)
        return lq

    def upsert_cohort(self, cohort: Cohort) -> None:
        from kueue_oss_tpu import features

        if cohort.parent and not features.enabled("HierarchicalCohorts"):
            # flat cohorts only when the gate is off (KEP-79); store a
            # flat copy, never mutate the caller's object
            import dataclasses

            cohort = dataclasses.replace(cohort, parent=None)
        with self._lock:
            self.cohorts[cohort.name] = cohort
        self._emit("update", "Cohort", cohort)

    def upsert_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq
        self._emit("update", "LocalQueue", lq)

    def upsert_resource_flavor(self, rf: ResourceFlavor) -> None:
        with self._lock:
            self.resource_flavors[rf.name] = rf
        self._emit("update", "ResourceFlavor", rf)

    def upsert_topology(self, t: Topology) -> None:
        with self._lock:
            self.topologies[t.name] = t
        self._emit("update", "Topology", t)

    def upsert_admission_check(self, ac: AdmissionCheck) -> None:
        with self._lock:
            self.admission_checks[ac.name] = ac
        self._emit("update", "AdmissionCheck", ac)

    def upsert_priority_class(self, pc: WorkloadPriorityClass) -> None:
        with self._lock:
            self.priority_classes[pc.name] = pc
        self._emit("update", "WorkloadPriorityClass", pc)

    def upsert_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
        self._emit("update", "Node", node)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.pop(name, None)
        if node is not None:
            self._emit("delete", "Node", node)

    def add_workload(self, wl: Workload) -> None:
        with self._lock:
            if wl.priority_class and wl.priority == 0:
                pc = self.priority_classes.get(wl.priority_class)
                if pc is not None:
                    wl.priority = pc.value
            wl.resource_version += 1
            self.workloads[wl.key] = wl
            self._index_workload(wl)
            self._track_finished(wl)
        self._emit("add", "Workload", wl)

    def update_workload(self, wl: Workload) -> None:
        with self._lock:
            wl.resource_version += 1
            self.workloads[wl.key] = wl
            self._index_workload(wl)
            self._track_finished(wl)
        self._emit("update", "Workload", wl)

    def update_workload_if(self, wl: Workload, expected_rv: int) -> bool:
        """Atomic conditional write: lands only if the stored object
        still exists at exactly `expected_rv` (the apiserver's
        optimistic-concurrency precondition; backs the client's
        merge-patch path). Returns False on conflict or deletion —
        never resurrects a concurrently deleted workload."""
        with self._lock:
            live = self.workloads.get(wl.key)
            if live is None or live.resource_version != expected_rv:
                return False
            wl.resource_version = expected_rv + 1
            self.workloads[wl.key] = wl
            self._index_workload(wl)
            self._track_finished(wl)
        self._emit("update", "Workload", wl)
        return True

    def _track_finished(self, wl: Workload) -> None:
        """The retained-finished gauges count workloads whose FINISHED
        condition is true and that still exist in the store. Tracking
        the transition HERE (the single write choke point) keeps inc/dec
        balanced regardless of which component set the condition
        (scheduler, MultiKueue copy-back, slice replacement)."""
        if wl.is_finished and wl.key not in self._finished_counted:
            self._finished_counted.add(wl.key)
            self._finished_gauges(wl, +1)

    def _finished_gauges(self, wl: Workload, delta: int) -> None:
        if not self._metrics_enabled:
            return
        from kueue_oss_tpu import metrics

        cq = (wl.status.admission.cluster_queue
              if wl.status.admission is not None
              else self.cluster_queue_for(wl))
        if cq:
            metrics.finished_workloads_gauge.inc(cq, by=delta)
            if metrics._lq_metrics_enabled():
                metrics.local_queue_finished_workloads_gauge.inc(
                    wl.queue_name, wl.namespace, by=delta)

    def delete_workload(self, key: str) -> Optional[Workload]:
        with self._lock:
            wl = self.workloads.pop(key, None)
            self._admitted.pop(key, None)
            self._admitted_infos.pop(key, None)
            counted = key in self._finished_counted
            self._finished_counted.discard(key)
        if wl is not None:
            if counted:
                # shed the retained-finished sample on ANY deletion path
                # (retention GC, job deletion, slices)
                self._finished_gauges(wl, -1)
            self._emit("delete", "Workload", wl)
        return wl

    def _index_workload(self, wl: Workload) -> None:
        if wl.is_quota_reserved and not wl.is_finished:
            self._admitted[wl.key] = wl
        else:
            self._admitted.pop(wl.key, None)
        # The cached info reflects pre-write state; rebuild lazily.
        self._admitted_infos.pop(wl.key, None)

    # -- readers -----------------------------------------------------------

    def cluster_queues_using_flavor(self, flavor_name: str) -> list[str]:
        """Sorted ClusterQueues whose resource groups reference the
        flavor (shared by kueuectl describe/list and the dashboard)."""
        return sorted(
            cq.name for cq in self.cluster_queues.values()
            if any(fq.name == flavor_name for rg in cq.resource_groups
                   for fq in rg.flavors))

    def cluster_queue_for(self, wl: Workload) -> Optional[str]:
        lq = self.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        return lq.cluster_queue if lq is not None else None

    def admitted_workloads(self) -> Iterable[Workload]:
        """Workloads holding quota (reserved and not finished)."""
        return list(self._admitted.values())

    def admitted_infos(self) -> list:
        """Cached WorkloadInfo for every admitted workload.

        The cache is invalidated per workload on write and wholesale when
        the request-shaping config (LimitRanges, transformations) changes,
        so repeated snapshot builds don't recompute effective requests.
        """
        from kueue_oss_tpu.core import workload_info as wli

        with self._lock:
            gen = wli.requests_config_generation()
            if gen != self._info_cache_gen:
                self._admitted_infos.clear()
                self._info_cache_gen = gen
            out = []
            for key, wl in self._admitted.items():
                info = self._admitted_infos.get(key)
                if info is None:
                    # Usage is charged to the CQ recorded in the admission,
                    # not the LocalQueue's current target (workload.go:299).
                    if wl.status.admission is not None:
                        info = wli.WorkloadInfo(
                            wl,
                            cluster_queue=wl.status.admission.cluster_queue)
                        self._admitted_infos[key] = info
                    else:
                        # No recorded admission: the CQ comes from the
                        # LocalQueue, which may be repointed at any time —
                        # resolve fresh every call, never cache.
                        cq_name = self.cluster_queue_for(wl)
                        if cq_name is None:
                            continue
                        info = wli.WorkloadInfo(wl, cluster_queue=cq_name)
                out.append(info)
            return out
